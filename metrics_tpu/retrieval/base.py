"""RetrievalMetric base: grouped-by-query mean of a per-query metric.

Behavior parity with /root/reference/torchmetrics/retrieval/base.py:27-150:
cat-states ``indexes/preds/target``; compute = concat -> group by query id ->
per-group ``_metric`` -> mean; ``empty_target_action`` in neg/pos/skip/error.

The reference groups with a Python dict loop (utilities/data.py:244-253, a
known hot spot — SURVEY.md §3.6). TPU-native compute path (SURVEY §7.5):
the ragged per-query structure is packed once into static
``[num_queries, max_docs]`` device buffers (sort + scatter on device), and the per-query
kernel, empty-query policy, and final mean all run as ONE jitted vmapped
call (functional/retrieval/padded.py). Subclasses declare their padded row
kernel via ``_padded_metric``; user subclasses that only implement
``_metric`` fall back to the host group loop (exact-parity mode).
"""
from abc import ABC, abstractmethod
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.retrieval.padded import (
    _padded_compute_fn,
    _padded_compute_fn_raw,
    pack_queries_cached,
    sorted_row_layout,
)
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat, get_group_indexes

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base class for retrieval metrics over (indexes, preds, target) triples."""

    higher_is_better = True
    __jit_unsafe__ = True  # grouping by query id has data-dependent shapes

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def _update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")

        indexes, preds, target = _check_retrieval_inputs(
            indexes,
            preds,
            target,
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )

        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    #: padded per-query row kernel ``(preds, target, mask, k) -> value`` from
    #: functional/retrieval/padded.py; None falls back to the host group loop
    _padded_metric: Optional[Callable] = None
    #: static top-k forwarded to the padded kernel (subclasses with a ``k`` arg
    #: override via property)
    _padded_k: Optional[int] = None

    def _group_empty(self, mini_target: Array) -> bool:
        """True if this query has no positive target (override to invert)."""
        return not bool(jnp.sum(mini_target))

    def _empty_rows(self, padded_target: Array, mask: Array) -> Array:
        """Vectorized ``_group_empty`` over the padded layout (override to invert)."""
        return (padded_target * mask).sum(-1) == 0

    def _empty_error_message(self) -> str:
        return "`compute` method was provided with a query with no positive target."

    def _compute(self) -> Array:
        if self._padded_metric is not None:
            return self._compute_padded()
        return self._compute_host_loop()

    def _compute_padded(self) -> Array:
        """Device-resident compute over the packed [num_queries, max_docs]
        layout: pack (sort + scatter), per-query kernels, empty policy, and
        mean all run on device; only two static-shape scalars (and the error
        flag when ``empty_target_action='error'``) cross to the host.

        The pack is memoized on the identity of the state arrays
        (``pack_queries_cached``): metrics sharing states through a
        MetricCollection compute group — e.g. NDCG + MAP over one query
        stream — pack once and each run only their own row kernel.
        """
        as_list = lambda s: s if isinstance(s, list) else [s]
        # heavily skewed query sizes make the [Q, Dmax] padding blow up (one
        # 50k-doc query among 100k small ones -> ~billions of padded slots);
        # past 16x expansion over the raw data the O(N) host loop wins
        packed = pack_queries_cached(
            as_list(self.indexes), as_list(self.preds), as_list(self.target), max_expand=16
        )
        if packed is None:
            return self._compute_host_loop()
        padded_preds, padded_target, mask = packed
        empty = self._empty_rows(padded_target, mask)
        if self.empty_target_action == "error" and bool(jnp.any(empty)):
            raise ValueError(self._empty_error_message())

        kernel = type(self)._padded_metric
        sorted_fn = getattr(kernel, "sorted_fn", None)
        if sorted_fn is not None:
            # shared-sort path: the per-row argsort is memoized per pack, so
            # every metric over this pack (a compute-group collection) sorts
            # once and runs only its own sorted kernel; NDCG's ideal ranking
            # is derived inside its compute jit from the raw target (the
            # other kernels' jits never touch that input)
            st, sm = sorted_row_layout(padded_preds, padded_target, mask)
            run = _padded_compute_fn(kernel, self._padded_k, self.empty_target_action)
            return run(st, sm, padded_target, jnp.asarray(empty))
        # user-supplied padded kernels without a sorted variant
        run = _padded_compute_fn_raw(kernel, self._padded_k, self.empty_target_action)
        return run(padded_preds, padded_target, mask, jnp.asarray(empty))

    def _compute_host_loop(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        res = []
        groups = get_group_indexes(indexes)

        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]

            if self._group_empty(mini_target):
                if self.empty_target_action == "error":
                    raise ValueError(self._empty_error_message())
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))

        if res:
            return jnp.mean(jnp.stack([jnp.asarray(x, dtype=preds.dtype) for x in res]))
        return jnp.asarray(0.0, dtype=preds.dtype)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Compute the metric for a single query's documents."""
