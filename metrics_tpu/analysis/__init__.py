"""tracelint — static analysis for the framework's trace-safety invariants.

The runtime enforces this codebase's contracts late: a host round-trip in
an ``update`` kernel surfaces as a failed ``eval_shape`` fusibility probe
(silent eager fallback), a Python scalar in a jitted-signature position as
a recompile storm the telemetry recorder warns about, a stray collective
as a multi-host hang. ``tracelint`` moves those checks to review time: an
AST-based engine with a pluggable rule registry, per-line suppression
pragmas (``# tracelint: disable=RULE-ID``), a checked-in baseline for
grandfathered violations, and text/JSON reporters.

Rule catalog (see ``docs/static_analysis.md`` for rationale + fix recipes):

* **TL-TRACE** — host round-trips (``float()``/``int()``/``bool()``/
  ``.item()``/``np.asarray``/``jax.device_get``/``.block_until_ready()``)
  and Python ``if``/``while`` on traced values inside ``update``/``compute``
  of metrics not declared ``__jit_unsafe__``, and inside functional kernels.
* **TL-RECOMPILE** — Python-scalar / ``.shape``-derived values flowing into
  jitted-signature positions (the hazard the fused-update 0-d-array
  coercion guards against).
* **TL-STATE** — registered-state attributes assigned outside
  update/reset/sync contexts, ``add_state`` with an unknown
  ``dist_reduce_fx``, and list-state / wrapper metrics missing an explicit
  ``__jit_unsafe__`` declaration.
* **TL-COLLECTIVE** — raw ``jax.lax.p*`` / ``process_allgather`` collectives
  outside ``metrics_tpu/parallel/`` and ``observability/aggregate.py``.
* **TL-PRINT** — raw ``print()`` / bare ``warnings.warn()`` in library code
  (absorbs ``scripts/check_no_print.py``; the script remains as an alias).
* **TL-DECL** — ``__jit_unsafe__`` declarations contradicted or made
  redundant by the abstract interpreter's verdict (``interp.py``): a stale
  ``True`` silently forces the eager path; a wrong ``False`` crashes the
  fused build instead of falling back.
* **TL-FLOW** — state-lifecycle dataflow (``stateflow.py``): a ``"sum"``-
  reduced leaf mutated by anything other than additive assignment, an
  overriding ``reset`` that misses a leaf, a registered-but-dead leaf.
* **TL-SHARD** — partition-rule coverage and spec/reducer agreement
  (``layout_rules.py``): a committed rule set that leaves state-leaf
  paths unmatched, a named-axis rule or spec on a leaf every registering
  class needs a cross-rank reduction for (the silently-skipped-reduction
  bug class), an unconditional sharded claim over every state leaf.
* **TL-MERGE** — fold-algebra soundness for ``merge_like``-tagged
  reducers: statically non-commutative fold steps, host-state reads, and
  ring folds that mix time-bucket slots, all of which break the
  collector's arrival-order-independence contract.
* **TL-WIRE** — checkpoint/wire coverage: every ``add_state`` leaf needs
  a wire-serializable dtype/shape/reducer triple — untagged callable
  reducers, statically wire-opaque defaults, and mixed device/cat-list
  classes without the ``__exact_mode_attr__`` escape hatch flag.
* **TL-LOCK** — guarded-by lock discipline for ``core/pipeline.py`` and
  ``observability/collector.py``: accesses of registered fields outside
  their lock's ``with`` scope (registry in ``layout_rules.GUARDED_FIELDS``;
  ``__init__`` and ``*_locked`` methods exempt).

v2 adds the **interprocedural abstract interpreter** (``interp.py``): calls
from metric updates resolve into ``metrics_tpu/functional/`` and ``utils/``,
a taint/None-ness/bool-ness lattice classifies every metric as ``fusible`` /
``unsafe(cat-growth | host-sync | data-dependent-shape)`` / ``unknown``, and
``scripts/tracelint.py --manifest`` serializes the verdicts plus per-leaf
shape/dtype/reduction abstractions to ``scripts/fusibility_manifest.json``
(``manifest.py``) — which ``core/fused.py`` consults at runtime to skip the
``eval_shape`` fusibility probe for ``fusible``-verdict metrics.

v3 adds the **layout/collective soundness pass**: the TL-SHARD / TL-MERGE /
TL-WIRE / TL-LOCK families above, and — from the same interp walk — the
schema-v1 **layout manifest** (``layout.py`` →
``scripts/layout_manifest.json``): per class, per leaf, the reducer class,
shard axis (``[S]`` slice / ``[R]`` ring / replicated), partition-spec
template, and reshard recipe (``fold`` for merge/sum leaves, ``reshape``
for slice axes). ``sliced/sharding.py`` answers partition specs from it
without probing live arrays (probe-skip counter observable,
``METRICS_TPU_VERIFY_MANIFEST=1`` cross-checks, stale manifests fall back
safely) and ``parallel/distributed.py`` audits sharded-claimed sync leaves
against it under the same flag. ``--manifest`` regenerates BOTH manifests;
``--manifest --check`` freshness-gates both in CI.

Run ``python scripts/tracelint.py`` (stdlib-only, no jax import) or
``python -m metrics_tpu.analysis``.

This package is deliberately stdlib-only so the CLI scripts can load it
without importing the (jax-heavy) parent package.
"""
from .engine import (  # noqa: F401
    FileContext,
    LintResult,
    Violation,
    analyze_paths,
    analyze_source,
    default_package_root,
    file_suppressed_rules,
    package_relpath,
    suppressed_rules,
)
from .baseline import load_baseline, save_baseline, split_by_baseline  # noqa: F401
from .reporters import render_github, render_json, render_text  # noqa: F401
from .rules import RULE_REGISTRY, Rule, all_rules, get_rules, register_rule  # noqa: F401
from .layout import (  # noqa: F401
    build_layout_manifest,
    layout_for_class,
    leaf_may_shard,
    leaf_shard_axes,
    load_layout_manifest,
    render_layout_manifest,
    runtime_layout,
    shard_path_universe,
)
from .interp import (  # noqa: F401
    Project,
    Signal,
    StateEntry,
    Verdict,
    classify,
    class_facts,
    summarize_function,
    verdict_from_signals,
)
from .manifest import (  # noqa: F401
    build_manifest,
    class_key,
    load_manifest,
    lookup_class,
    manifest_verdict,
    render_manifest,
    runtime_manifest,
)
from .stateflow import analyze_class as analyze_state_flows  # noqa: F401

__all__ = [
    "FileContext",
    "LintResult",
    "Project",
    "RULE_REGISTRY",
    "Rule",
    "Signal",
    "StateEntry",
    "Verdict",
    "Violation",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "analyze_state_flows",
    "build_layout_manifest",
    "build_manifest",
    "class_facts",
    "class_key",
    "classify",
    "default_package_root",
    "file_suppressed_rules",
    "get_rules",
    "layout_for_class",
    "leaf_may_shard",
    "leaf_shard_axes",
    "load_baseline",
    "load_layout_manifest",
    "load_manifest",
    "lookup_class",
    "manifest_verdict",
    "package_relpath",
    "register_rule",
    "render_github",
    "render_json",
    "render_layout_manifest",
    "render_manifest",
    "render_text",
    "runtime_layout",
    "runtime_manifest",
    "shard_path_universe",
    "save_baseline",
    "split_by_baseline",
    "suppressed_rules",
    "summarize_function",
    "verdict_from_signals",
]
