"""Modular TranslationEditRate.

Behavior parity with /root/reference/torchmetrics/text/ter.py:24-146.
"""
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update

Array = jax.Array


class TranslationEditRate(Metric):
    """Corpus Translation Edit Rate with Tercom shift search.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = TranslationEditRate()
        >>> float(metric(preds, target))  # doctest: +ELLIPSIS
        0.1538461...
    """

    is_differentiable = False
    higher_is_better = False
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        for name, value in [
            ("normalize", normalize),
            ("no_punctuation", no_punctuation),
            ("lowercase", lowercase),
            ("asian_support", asian_support),
        ]:
            if not isinstance(value, bool):
                raise ValueError(f"Expected argument `{name}` to be of type boolean but got {value}.")

        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total_tgt_len", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def _update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        num_edits, tgt_length, sentence_ter = _ter_update(preds, target, self.tokenizer)
        self.total_num_edits = self.total_num_edits + num_edits
        self.total_tgt_len = self.total_tgt_len + tgt_length
        if self.return_sentence_level_score:
            self.sentence_ter.extend(jnp.asarray(s, jnp.float32)[None] for s in sentence_ter)

    def _compute(self) -> Union[Array, Tuple[Array, List[Array]]]:
        score = _ter_compute(self.total_num_edits, self.total_tgt_len)
        if self.return_sentence_level_score:
            return score, self.sentence_ter
        return score
