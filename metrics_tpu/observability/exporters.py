"""Telemetry exporters: JSONL event log, Prometheus text exposition, a
human summary table, and a background :class:`PeriodicExporter` that keeps
file artifacts fresh on an interval.

All exporters are rank-zero-gated (multi-host jobs emit one copy) and read
a consistent snapshot of the recorder, so they can run concurrently with
metric updates. Every file write is atomic (tmp file + ``os.replace`` in
the target directory), so a concurrent scrape or a crash mid-write never
observes a truncated artifact.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Dict, List, Optional

from metrics_tpu.utils.prints import _process_index


def _resolve(recorder: Optional[Any]) -> Any:
    if recorder is None:
        from metrics_tpu.observability.recorder import _DEFAULT_RECORDER

        return _DEFAULT_RECORDER
    return recorder


# ---------------------------------------------------------------------------
# atomic file writes
# ---------------------------------------------------------------------------

def _atomic_write(path: str, text: str) -> None:
    """Write ``text`` to ``path`` atomically: a same-directory tmp file is
    fully written and fsynced, then ``os.replace``d over the target, so any
    concurrent reader sees either the old complete artifact or the new one
    — never a truncation. The tmp name is pid-distinct, so two processes
    racing the same target each land a complete (last-writer-wins) file."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(directory, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


#: rotation cap for appended line logs (alarm JSONL, env-var telemetry
#: appends): past it the current file moves to ``<path>.1`` (previous
#: ``.1`` overwritten) and appends continue on a fresh file — long-running
#: jobs keep bounded log disk, with the newest full generation retained
APPEND_ROTATE_BYTES = 64 * 1024 * 1024


def _atomic_append(path: str, text: str, max_bytes: Optional[int] = APPEND_ROTATE_BYTES) -> None:
    """Line-log append: ONE ``O_APPEND`` ``write`` of the new bytes.

    O(len(text)) per call whatever the file size — the previous
    read-whole-file-and-rewrite implementation made every append O(file),
    so a long-running alarm/telemetry log degraded quadratically (pinned
    by the multi-thousand-append test). ``O_APPEND`` + a single ``write``
    is atomic w.r.t. the file offset, so concurrent appenders (and
    multi-process env-var telemetry) interleave at line granularity, and
    a crash mid-call loses at most the tail of this one write — every
    previously appended line survives intact.

    ``max_bytes`` caps the file: when this append would push past it, the
    current file rotates to ``<path>.1`` first (previous ``.1``
    overwritten — one old generation retained) and the append lands on a
    fresh file. ``None`` disables rotation."""
    data = text.encode("utf-8")
    flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
    fd = os.open(path, flags, 0o644)
    try:
        if (
            max_bytes is not None
            and os.fstat(fd).st_size > 0
            and os.fstat(fd).st_size + len(data) > max_bytes
        ):
            os.close(fd)
            fd = -1
            os.replace(path, path + ".1")
            fd = os.open(path, flags, 0o644)
        os.write(fd, data)
    finally:
        if fd >= 0:
            os.close(fd)


def export_jsonl(path: str, recorder: Optional[Any] = None, append: bool = False) -> Optional[str]:
    """Write every recorded event as one JSON object per line.

    Returns the path written, or ``None`` on non-zero ranks (rank-zero
    gated). Events are plain dicts of JSON scalars/lists, so the artifact
    round-trips through ``json.loads`` line by line. Full writes are
    atomic (tmp + ``os.replace``); ``append=True`` is a single
    ``O_APPEND`` write (crash-safe up to the current write, size-cap
    rotated — see :func:`_atomic_append`).
    """
    if _process_index() != 0:
        return None
    rec = _resolve(recorder)
    text = "".join(json.dumps(event) + "\n" for event in rec.events())
    if append:
        _atomic_append(path, text)
    else:
        _atomic_write(path, text)
    return path


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(**kv: Any) -> str:
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in kv.items())
    return "{" + inner + "}" if inner else ""


#: default lookback for the windowed (time-series) Prometheus families
WINDOW_EXPORT_SECONDS = 60.0

#: quantiles rendered per distribution series on the Prometheus page
WINDOW_EXPORT_QUANTILES = (0.5, 0.95, 0.99)

#: fixed bucket edges (``le`` bounds) for the qsketch-backed exposition
#: histograms: log-spaced 1ms..5000s in base units, wide enough to cover
#: millisecond latencies and multi-minute staleness ages with one shared
#: grid — FIXED so the fleet merge and PromQL ``histogram_quantile`` see
#: the same ``le`` set from every rank
WINDOW_HISTOGRAM_EDGES = (
    0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 2.5, 10.0, 50.0, 250.0, 1000.0, 5000.0,
)


def _timeseries_lines(registry: Any, window_s: float = WINDOW_EXPORT_SECONDS) -> List[str]:
    """Windowed families from a TimeSeriesRegistry (or a registry rebuilt
    from a merged cross-host payload): per-series observation count and
    rate, plus p50/p95/p99 for distribution series. One merged-sketch
    query serves all quantiles of a series.

    Each sample carries a ``window_s`` label with the seconds ACTUALLY
    covered — the requested window clamped to the series' ring span
    (``n_buckets * bucket_seconds``): a short-ring registry must not
    publish numbers labeled as a longer lookback than it holds."""
    lines: List[str] = []
    names = registry.names()
    if not names:
        return lines

    def eff_window(s: Any) -> float:
        return min(float(window_s), s.n_buckets * s.bucket_seconds)

    lines.append(
        "# HELP metrics_tpu_window_count Observations recorded in the trailing window"
        " (window_s label = seconds covered) per series."
    )
    lines.append("# TYPE metrics_tpu_window_count gauge")
    for name in names:
        s = registry.get(name)
        w = eff_window(s)
        lines.append(
            f"metrics_tpu_window_count{_labels(series=name, window_s=f'{w:g}')} {s.count(w)}"
        )
    lines.append(
        "# HELP metrics_tpu_window_rate Summed values per second over the trailing window"
        " (window_s label = seconds covered) per series."
    )
    lines.append("# TYPE metrics_tpu_window_rate gauge")
    for name in names:
        s = registry.get(name)
        w = eff_window(s)
        lines.append(
            f"metrics_tpu_window_rate{_labels(series=name, window_s=f'{w:g}')} {s.rate(w):g}"
        )
    lines.append(
        "# HELP metrics_tpu_window_quantile Sketch-estimated quantiles over the trailing"
        " window (window_s label = seconds covered) per distribution series."
    )
    lines.append("# TYPE metrics_tpu_window_quantile gauge")
    for name in names:
        s = registry.get(name)
        if s.kind != "distribution":
            continue
        w = eff_window(s)
        vals = s.quantiles(WINDOW_EXPORT_QUANTILES, window_s=w)
        if vals is None:
            continue
        for q, v in zip(WINDOW_EXPORT_QUANTILES, vals):
            lines.append(
                f"metrics_tpu_window_quantile{_labels(series=name, q=q, window_s=f'{w:g}')} {v:g}"
            )
    lines.extend(_histogram_lines(registry, names, eff_window))
    return lines


def _histogram_lines(registry: Any, names: List[str], eff_window: Any) -> List[str]:
    """Real Prometheus histograms for the distribution series: cumulative
    ``_bucket{le=}`` counts from the window sketch's CDF at the fixed
    :data:`WINDOW_HISTOGRAM_EDGES`, plus ``_sum``/``_count`` from the
    series' exact windowed totals — so PromQL ``histogram_quantile`` and
    the existing quantile gauges answer from the same sketch. Sketch-
    estimated bucket counts are forced monotone non-decreasing and capped
    at the exact ``_count`` (a strict-parser requirement the CDF estimate
    alone cannot guarantee)."""
    samples: List[str] = []
    for name in names:
        s = registry.get(name)
        if s.kind != "distribution":
            continue
        w = eff_window(s)
        n = s.count(w)
        if not n:
            continue
        sketch = s.window_sketch(w)
        if sketch is None:
            continue
        import numpy as np

        from metrics_tpu.sketches.quantile import qsketch_cdf

        edges = np.asarray(WINDOW_HISTOGRAM_EDGES, np.float32)
        cdf = np.asarray(qsketch_cdf(sketch, edges))
        if np.any(np.isnan(cdf)):
            continue
        counts = np.minimum(np.maximum.accumulate(np.clip(cdf, 0.0, 1.0)) * n, n)
        labels = {"series": name, "window_s": f"{w:g}"}
        for edge, c in zip(WINDOW_HISTOGRAM_EDGES, counts):
            samples.append(
                f"metrics_tpu_window_hist_bucket{_labels(le=f'{edge:g}', **labels)} {c:g}"
            )
        samples.append(f"metrics_tpu_window_hist_bucket{_labels(le='+Inf', **labels)} {n}")
        samples.append(f"metrics_tpu_window_hist_sum{_labels(**labels)} {s.total(w):g}")
        samples.append(f"metrics_tpu_window_hist_count{_labels(**labels)} {n}")
    if not samples:
        return []
    return [
        "# HELP metrics_tpu_window_hist Sketch-backed distribution histogram over the"
        " trailing window (window_s label = seconds covered) per series.",
        "# TYPE metrics_tpu_window_hist histogram",
        *samples,
    ]


def render_prometheus(recorder: Optional[Any] = None, aggregate: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text-format rendering of the aggregate counters/gauges.

    Meant for a scrape endpoint or a textfile-collector drop: call counts
    and cumulative wall time per (metric, phase), sync/gather byte totals,
    distinct-signature gauges (the recompile detector's raw data),
    state-footprint high-water marks, and compile bills. Returns ``""`` on
    non-zero ranks.

    ``aggregate`` — a job-wide result from
    :func:`metrics_tpu.observability.aggregate_across_hosts`. When given,
    the page covers the WHOLE job instead of this process: call counts are
    the merged totals, and the families where per-rank detail matters
    (wall time for stragglers, sync bytes, signature skew, footprint and
    compile bills per host) carry a ``process`` label per rank.
    """
    if _process_index() != 0:
        return ""
    rec = _resolve(recorder)
    if aggregate is not None:
        counts = aggregate["call_counts"]
        per_proc = aggregate["processes"]
        dropped = aggregate["dropped_events"]
    else:
        counts = rec.call_counts()
        # single-process rendering reuses the per-process machinery with
        # this one recorder's payload, minus the process label
        from metrics_tpu.observability.aggregate import counter_payload

        per_proc = [counter_payload(rec)]
        dropped = rec.dropped_events()

    def proc_label(payload: Dict[str, Any]) -> Dict[str, Any]:
        if aggregate is None:
            return {}
        # per-host labelling for the federated (fleet-collector) view:
        # payloads carrying snapshot provenance get host (and, through a
        # collector, publisher) labels next to the process index — several
        # publishers on one host share a process index, so the publisher
        # id is what keeps the per-rank series distinct. Older payloads
        # without provenance stay process-only.
        labels: Dict[str, Any] = {"process": payload.get("process", 0)}
        if payload.get("host"):
            labels["host"] = payload["host"]
        if payload.get("publisher"):
            labels["publisher"] = payload["publisher"]
        return labels

    lines: List[str] = []
    lines.append("# HELP metrics_tpu_calls_total Metric lifecycle calls by metric and phase.")
    lines.append("# TYPE metrics_tpu_calls_total counter")
    for (metric, phase), n in sorted(counts.items()):
        lines.append(f"metrics_tpu_calls_total{_labels(metric=metric, phase=phase)} {n}")
    lines.append("# HELP metrics_tpu_call_seconds_total Cumulative wall time by metric and phase.")
    lines.append("# TYPE metrics_tpu_call_seconds_total counter")
    for payload in per_proc:
        for key, t in sorted(payload.get("call_times", {}).items()):
            metric, phase = key.split("|")
            lines.append(
                f"metrics_tpu_call_seconds_total"
                f"{_labels(metric=metric, phase=phase, **proc_label(payload))} {t:.6f}"
            )
    lines.append("# HELP metrics_tpu_sync_events_total Cross-device/process state synchronizations.")
    lines.append("# TYPE metrics_tpu_sync_events_total counter")
    for payload in per_proc:
        lines.append(
            f"metrics_tpu_sync_events_total{_labels(**proc_label(payload))}"
            f" {payload.get('sync_totals', {}).get('sync_events', 0)}"
        )
    lines.append("# HELP metrics_tpu_gather_bytes_total Bytes of synced state received per participant.")
    lines.append("# TYPE metrics_tpu_gather_bytes_total counter")
    for payload in per_proc:
        lines.append(
            f"metrics_tpu_gather_bytes_total{_labels(**proc_label(payload))}"
            f" {payload.get('sync_totals', {}).get('gather_bytes', 0)}"
        )
    lines.append("# HELP metrics_tpu_pad_waste_bytes_total Pad-to-max padding bytes moved by uneven gathers.")
    lines.append("# TYPE metrics_tpu_pad_waste_bytes_total counter")
    for payload in per_proc:
        lines.append(
            f"metrics_tpu_pad_waste_bytes_total{_labels(**proc_label(payload))}"
            f" {payload.get('sync_totals', {}).get('pad_waste_bytes', 0)}"
        )
    lines.append("# HELP metrics_tpu_distinct_signatures Distinct (shape, dtype) call signatures per entry point.")
    lines.append("# TYPE metrics_tpu_distinct_signatures gauge")
    for payload in per_proc:
        for entry, n in sorted(payload.get("signature_counts", {}).items()):
            lines.append(
                f"metrics_tpu_distinct_signatures{_labels(entry=entry, **proc_label(payload))} {n}"
            )
    lines.append("# HELP metrics_tpu_state_bytes_hwm State-footprint high-water mark per metric.")
    lines.append("# TYPE metrics_tpu_state_bytes_hwm gauge")
    for payload in per_proc:
        for metric, nbytes in sorted(payload.get("footprint_hwm", {}).items()):
            lines.append(
                f"metrics_tpu_state_bytes_hwm{_labels(metric=metric, **proc_label(payload))} {nbytes}"
            )
    lines.append("# HELP metrics_tpu_compiles_total Attributed XLA compilations per entry point.")
    lines.append("# TYPE metrics_tpu_compiles_total counter")
    for payload in per_proc:
        for entry, n in sorted(payload.get("compile_counts", {}).items()):
            lines.append(
                f"metrics_tpu_compiles_total{_labels(entry=entry, **proc_label(payload))} {n}"
            )
    lines.append("# HELP metrics_tpu_compile_seconds_total Cumulative trace+lower+compile wall time per entry point.")
    lines.append("# TYPE metrics_tpu_compile_seconds_total counter")
    for payload in per_proc:
        for entry, t in sorted(payload.get("compile_times", {}).items()):
            lines.append(
                f"metrics_tpu_compile_seconds_total{_labels(entry=entry, **proc_label(payload))} {t:.6f}"
            )
    # disjoint terminal outcomes only (applied + dropped): every accepted-or-
    # rejected batch lands in exactly one, so sum()/rate() over the family is
    # meaningful. Ingress (enqueued, a superset of applied) and flush
    # operations (not batches at all) get their own families.
    lines.append("# HELP metrics_tpu_async_batches_total Async-pipeline batches by terminal outcome (applied|dropped; disjoint).")
    lines.append("# TYPE metrics_tpu_async_batches_total counter")
    for payload in per_proc:
        totals = payload.get("async_totals", {})
        for outcome in ("applied", "dropped"):
            lines.append(
                f"metrics_tpu_async_batches_total"
                f"{_labels(outcome=outcome, **proc_label(payload))} {totals.get(outcome, 0)}"
            )
    lines.append("# HELP metrics_tpu_async_enqueued_total Batches accepted into the async update queue (ingress; applied is a subset).")
    lines.append("# TYPE metrics_tpu_async_enqueued_total counter")
    for payload in per_proc:
        totals = payload.get("async_totals", {})
        lines.append(
            f"metrics_tpu_async_enqueued_total{_labels(**proc_label(payload))}"
            f" {totals.get('enqueued', 0)}"
        )
    lines.append("# HELP metrics_tpu_async_flushes_total Deterministic drains (flush() calls and draining close()).")
    lines.append("# TYPE metrics_tpu_async_flushes_total counter")
    for payload in per_proc:
        totals = payload.get("async_totals", {})
        lines.append(
            f"metrics_tpu_async_flushes_total{_labels(**proc_label(payload))}"
            f" {totals.get('flushes', 0)}"
        )
    # each family's HELP/TYPE must sit directly above its own samples: the
    # exposition format requires all lines of a metric as one contiguous
    # group, and strict consumers (promtool, OpenMetrics scrapers) reject
    # interleaved headers
    for family, key, help_text in (
        ("metrics_tpu_async_queue_depth", "queue_depth",
         "Outstanding async batches: accepted but not yet applied, including"
         " the one in the worker's hand — may exceed the configured queue"
         " depth by one (last seen / high-water)."),
        ("metrics_tpu_async_staleness_steps", "staleness_steps",
         "Compute-snapshot staleness in unapplied batches (last seen / high-water)."),
        ("metrics_tpu_async_in_flight_bytes", "in_flight_bytes",
         "Bytes pinned by queued batches and donated in-flight state (last seen / high-water)."),
    ):
        lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} gauge")
        for payload in per_proc:
            totals = payload.get("async_totals", {})
            lines.append(
                f"{family}{_labels(window='last', **proc_label(payload))} {totals.get(key, 0)}"
            )
            lines.append(
                f"{family}{_labels(window='max', **proc_label(payload))} {totals.get('max_' + key, 0)}"
            )
    lines.append("# HELP metrics_tpu_sliced_scatter_total Slice-axis segment-scatter updates (eager: per update; fused: per compilation).")
    lines.append("# TYPE metrics_tpu_sliced_scatter_total counter")
    for payload in per_proc:
        totals = payload.get("sliced_totals", {})
        lines.append(
            f"metrics_tpu_sliced_scatter_total{_labels(**proc_label(payload))}"
            f" {totals.get('scatter_events', 0)}"
        )
    lines.append("# HELP metrics_tpu_sliced_rows_total Batch rows scattered into slice states.")
    lines.append("# TYPE metrics_tpu_sliced_rows_total counter")
    for payload in per_proc:
        totals = payload.get("sliced_totals", {})
        lines.append(
            f"metrics_tpu_sliced_rows_total{_labels(**proc_label(payload))}"
            f" {totals.get('rows', 0)}"
        )
    lines.append("# HELP metrics_tpu_sliced_slices Largest slice count seen on a sliced metric (high-water).")
    lines.append("# TYPE metrics_tpu_sliced_slices gauge")
    for payload in per_proc:
        totals = payload.get("sliced_totals", {})
        lines.append(
            f"metrics_tpu_sliced_slices{_labels(**proc_label(payload))}"
            f" {totals.get('max_slices', 0)}"
        )
    lines.append("# HELP metrics_tpu_sketch_merges_total Cross-rank/pairwise sketch-state merges performed.")
    lines.append("# TYPE metrics_tpu_sketch_merges_total counter")
    for payload in per_proc:
        totals = payload.get("sketch_totals", {})
        lines.append(
            f"metrics_tpu_sketch_merges_total{_labels(**proc_label(payload))}"
            f" {totals.get('merges', 0)}"
        )
    lines.append("# HELP metrics_tpu_sketch_fill_ratio Sketch capacity-fill ratio (occupied slots / capacity) reported at compute.")
    lines.append("# TYPE metrics_tpu_sketch_fill_ratio gauge")
    for payload in per_proc:
        totals = payload.get("sketch_totals", {})
        lines.append(
            f"metrics_tpu_sketch_fill_ratio{_labels(window='last', **proc_label(payload))}"
            f" {totals.get('fill_ratio', 0.0)}"
        )
        lines.append(
            f"metrics_tpu_sketch_fill_ratio{_labels(window='max', **proc_label(payload))}"
            f" {totals.get('max_fill_ratio', 0.0)}"
        )
    lines.append("# HELP metrics_tpu_ops_dispatch_total Kernel-registry dispatches by op and chosen backend (pallas|jnp|interpret; jitted traffic counts per compilation).")
    lines.append("# TYPE metrics_tpu_ops_dispatch_total counter")
    for payload in per_proc:
        for key, n in sorted(payload.get("ops_dispatch_totals", {}).items()):
            op, _, backend = key.partition("|")
            lines.append(
                f"metrics_tpu_ops_dispatch_total"
                f"{_labels(op=op, backend=backend, **proc_label(payload))} {n}"
            )
    # read-path telemetry plane: every compute/window/sliced/fleet read
    # emits a typed event; these families are its cumulative face. The two
    # cache outcomes are disjoint (hit + miss = reads), so sum()/rate()
    # over the family is meaningful.
    lines.append("# HELP metrics_tpu_read_total Metric reads by cache outcome (hit|miss; disjoint).")
    lines.append("# TYPE metrics_tpu_read_total counter")
    for payload in per_proc:
        totals = payload.get("read_totals", {})
        reads = totals.get("reads", 0)
        hits = totals.get("cache_hits", 0)
        lines.append(
            f"metrics_tpu_read_total{_labels(cache='hit', **proc_label(payload))} {hits}"
        )
        lines.append(
            f"metrics_tpu_read_total{_labels(cache='miss', **proc_label(payload))} {max(reads - hits, 0)}"
        )
    lines.append("# HELP metrics_tpu_read_seconds_total Cumulative wall time spent serving metric reads.")
    lines.append("# TYPE metrics_tpu_read_seconds_total counter")
    for payload in per_proc:
        totals = payload.get("read_totals", {})
        lines.append(
            f"metrics_tpu_read_seconds_total{_labels(**proc_label(payload))}"
            f" {totals.get('read_s_total', 0.0):.6f}"
        )
    lines.append("# HELP metrics_tpu_read_fanin Contributors folded by a single read (fleet-tier publisher fan-in; last window high-water).")
    lines.append("# TYPE metrics_tpu_read_fanin gauge")
    for payload in per_proc:
        totals = payload.get("read_totals", {})
        lines.append(
            f"metrics_tpu_read_fanin{_labels(window='max', **proc_label(payload))}"
            f" {totals.get('max_fanin', 0)}"
        )
    lines.append("# HELP metrics_tpu_read_folded_total State folded while serving reads, by unit (leaves|ring_buckets|table_rows).")
    lines.append("# TYPE metrics_tpu_read_folded_total counter")
    for payload in per_proc:
        totals = payload.get("read_totals", {})
        for unit, key in (
            ("leaves", "leaves_folded"),
            ("ring_buckets", "ring_buckets_folded"),
            ("table_rows", "table_rows_unpacked"),
        ):
            lines.append(
                f"metrics_tpu_read_folded_total"
                f"{_labels(unit=unit, **proc_label(payload))} {totals.get(key, 0)}"
            )
    lines.append("# HELP metrics_tpu_freshness_stamps_total Reads that carried an ingest-to-visible freshness stamp.")
    lines.append("# TYPE metrics_tpu_freshness_stamps_total counter")
    for payload in per_proc:
        fresh = payload.get("freshness", {})
        lines.append(
            f"metrics_tpu_freshness_stamps_total{_labels(**proc_label(payload))}"
            f" {fresh.get('stamps', 0)}"
        )
    lines.append("# HELP metrics_tpu_freshness_staleness_seconds Worst ingest-to-visible staleness observed at a read (high-water).")
    lines.append("# TYPE metrics_tpu_freshness_staleness_seconds gauge")
    for payload in per_proc:
        fresh = payload.get("freshness", {})
        lines.append(
            f"metrics_tpu_freshness_staleness_seconds{_labels(window='max', **proc_label(payload))}"
            f" {fresh.get('max_staleness_s', 0.0):g}"
        )
    # memory-observatory families (observability/memory.py): the ledger /
    # cache-plane / device / unaccounted byte gauges follow the async-gauge
    # contiguity pattern (window='last' + window='max' per family)
    lines.append("# HELP metrics_tpu_memory_boundaries_total Metric lifecycle memory boundaries by kind (update|compute|reset; disjoint).")
    lines.append("# TYPE metrics_tpu_memory_boundaries_total counter")
    for payload in per_proc:
        totals = payload.get("memory", {})
        for kind in ("update", "compute", "reset"):
            lines.append(
                f"metrics_tpu_memory_boundaries_total"
                f"{_labels(boundary=kind, **proc_label(payload))}"
                f" {totals.get(kind + '_boundaries', 0)}"
            )
    lines.append("# HELP metrics_tpu_memory_observations_total Full memory-observatory polls (ledger + cache planes + backend).")
    lines.append("# TYPE metrics_tpu_memory_observations_total counter")
    for payload in per_proc:
        totals = payload.get("memory", {})
        lines.append(
            f"metrics_tpu_memory_observations_total{_labels(**proc_label(payload))}"
            f" {totals.get('observations', 0)}"
        )
    for family, key, help_text in (
        ("metrics_tpu_memory_ledger_bytes", "ledger_bytes",
         "Live committed device bytes held by metric state pytrees, deduped"
         " by buffer identity (last seen / high-water)."),
        ("metrics_tpu_memory_cache_plane_bytes", "cache_plane_bytes",
         "Bytes held by registered cache planes (reader/fused executables,"
         " layout memo, value caches; last seen / high-water)."),
        ("metrics_tpu_memory_device_bytes_in_use", "device_bytes_in_use",
         "Allocator-reported bytes in use (backend memory_stats, or host RSS"
         " where the backend reports none; last seen / high-water)."),
        ("metrics_tpu_memory_unaccounted_bytes", "unaccounted_bytes",
         "In-use bytes minus ledger minus cache planes — the residue the"
         " memory_leak alarm watches (last seen / high-water)."),
        ("metrics_tpu_memory_bytes_per_tenant", "bytes_per_tenant",
         "Ledger bytes per sliced-state tenant — what the memory_budget"
         " alarm ceilings (last seen / high-water)."),
    ):
        lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} gauge")
        for payload in per_proc:
            totals = payload.get("memory", {})
            lines.append(
                f"{family}{_labels(window='last', **proc_label(payload))} {totals.get(key, 0)}"
            )
            lines.append(
                f"{family}{_labels(window='max', **proc_label(payload))}"
                f" {totals.get('max_' + key, 0)}"
            )
    lines.append("# HELP metrics_tpu_memory_plane_evictions_total Cache-plane entries evicted (layout memo LRU drops and finalizers).")
    lines.append("# TYPE metrics_tpu_memory_plane_evictions_total counter")
    for payload in per_proc:
        totals = payload.get("memory", {})
        lines.append(
            f"metrics_tpu_memory_plane_evictions_total{_labels(**proc_label(payload))}"
            f" {totals.get('plane_evictions', 0)}"
        )
    lines.append("# HELP metrics_tpu_memory_plane_evicted_bytes_total Bytes released by cache-plane evictions.")
    lines.append("# TYPE metrics_tpu_memory_plane_evicted_bytes_total counter")
    for payload in per_proc:
        totals = payload.get("memory", {})
        lines.append(
            f"metrics_tpu_memory_plane_evicted_bytes_total{_labels(**proc_label(payload))}"
            f" {totals.get('plane_evicted_bytes', 0)}"
        )
    lines.append("# HELP metrics_tpu_drift_score Last reference-vs-live drift score per watched source and statistic.")
    lines.append("# TYPE metrics_tpu_drift_score gauge")
    for payload in per_proc:
        for key, v in sorted(payload.get("drift_scores", {}).items()):
            source, _, stat = key.partition("|")
            lines.append(
                f"metrics_tpu_drift_score{_labels(metric=source, stat=stat, **proc_label(payload))} {v:g}"
            )
    lines.append("# HELP metrics_tpu_fleet_ingest_total Fleet-collector snapshot ingests by outcome (absorbed|duplicate|late_dropped|fold_error; disjoint).")
    lines.append("# TYPE metrics_tpu_fleet_ingest_total counter")
    for payload in per_proc:
        totals = payload.get("fleet_totals", {})
        for outcome, key in (
            ("absorbed", "absorbed"),
            ("duplicate", "duplicates"),
            ("late_dropped", "late_dropped"),
            ("fold_error", "fold_errors"),
        ):
            lines.append(
                f"metrics_tpu_fleet_ingest_total"
                f"{_labels(outcome=outcome, **proc_label(payload))} {totals.get(key, 0)}"
            )
    # the fleet gauges follow the async-gauge contiguity pattern: each
    # family's HELP/TYPE directly above its own samples
    for family, key, help_text in (
        ("metrics_tpu_fleet_backlog_snapshots", "backlog",
         "Unfolded snapshots at the collector (queued files + in-window"
         " pending deltas; last seen / high-water)."),
        ("metrics_tpu_fleet_worst_publisher_lag_seconds", "publisher_lag_s",
         "Worst per-publisher snapshot lag observed at a collector poll"
         " (last seen / high-water)."),
    ):
        lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} gauge")
        for payload in per_proc:
            totals = payload.get("fleet_totals", {})
            lines.append(
                f"{family}{_labels(window='last', **proc_label(payload))} {totals.get(key, 0)}"
            )
            lines.append(
                f"{family}{_labels(window='max', **proc_label(payload))}"
                f" {totals.get('max_' + key, 0)}"
            )
    lines.append("# HELP metrics_tpu_export_errors_total Exporter ticks that raised (artifacts may be stale).")
    lines.append("# TYPE metrics_tpu_export_errors_total counter")
    for payload in per_proc:
        lines.append(
            f"metrics_tpu_export_errors_total{_labels(**proc_label(payload))}"
            f" {payload.get('export_errors', 0)}"
        )
    lines.append("# HELP metrics_tpu_dropped_events_total Events discarded past the buffer cap.")
    lines.append("# TYPE metrics_tpu_dropped_events_total counter")
    lines.append(f"metrics_tpu_dropped_events_total {dropped}")
    # windowed (time-series) families — present only when the live layer is
    # attached (single-process: the recorder's registry; aggregate: the
    # cross-host merged payload rebuilt into a queryable registry)
    ts_registry = None
    if aggregate is not None:
        merged_ts = aggregate.get("timeseries")
        if merged_ts:
            from metrics_tpu.observability.timeseries import registry_from_payload

            ts_registry = registry_from_payload(merged_ts)
    else:
        ts_registry = rec.timeseries
    if ts_registry is not None:
        lines.extend(_timeseries_lines(ts_registry))
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, recorder: Optional[Any] = None, aggregate: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Atomically drop the Prometheus page as a textfile-collector artifact.
    Returns the path written, or ``None`` on non-zero ranks."""
    if _process_index() != 0:
        return None
    _atomic_write(path, render_prometheus(recorder, aggregate=aggregate))
    return path


# ---------------------------------------------------------------------------
# human summary
# ---------------------------------------------------------------------------

def summary(recorder: Optional[Any] = None) -> str:
    """Human-readable summary table of where metric time went.

    Returns ``""`` on non-zero ranks.
    """
    if _process_index() != 0:
        return ""
    rec = _resolve(recorder)
    counts = rec.call_counts()
    times = rec.call_times()
    sync = rec.sync_totals()
    sigs = rec.signature_counts()
    hwm = rec.footprint_high_water_marks()
    compiles = rec.compile_counts()
    compile_times = rec.compile_times()

    rows = []
    for (metric, phase), n in sorted(counts.items(), key=lambda kv: -times.get(kv[0], 0.0)):
        total_ms = times.get((metric, phase), 0.0) * 1e3
        rows.append((metric, phase, n, total_ms, total_ms / max(n, 1)))

    # clamp to the header's own width: all-short metric names must not
    # shrink the column below len("metric") and shear the header row
    width = max([len(r[0]) for r in rows] + [6])
    lines = [
        f"telemetry summary (recorder `{rec.name}`)",
        f"{'metric':<{width}}  {'phase':<8} {'calls':>7} {'total_ms':>10} {'mean_ms':>9}",
    ]
    for metric, phase, n, total_ms, mean_ms in rows:
        lines.append(f"{metric:<{width}}  {phase:<8} {n:>7} {total_ms:>10.3f} {mean_ms:>9.4f}")
    if not rows:
        lines.append("(no lifecycle calls recorded)")
    lines.append(
        f"sync: {sync['sync_events']} events, {sync['gather_bytes']} gather bytes,"
        f" {sync['pad_waste_bytes']} pad-waste bytes"
    )
    async_totals = rec.async_totals()
    if async_totals.get("enqueued") or async_totals.get("dropped"):
        lines.append(
            f"async pipeline: {async_totals['enqueued']} enqueued,"
            f" {async_totals['applied']} applied, {async_totals['dropped']} dropped,"
            f" {async_totals['flushes']} flushes; queue depth max"
            f" {async_totals['max_queue_depth']}, staleness max"
            f" {async_totals['max_staleness_steps']} steps, in-flight max"
            f" {async_totals['max_in_flight_bytes']} bytes"
        )
    sliced_totals = rec.sliced_totals()
    if sliced_totals.get("scatter_events"):
        lines.append(
            f"sliced scatter: {sliced_totals['scatter_events']} events,"
            f" {sliced_totals['rows']} rows, max {sliced_totals['max_slices']} slices"
        )
    drift = rec.drift_scores()
    if drift:
        lines.append("drift scores (reference vs live):")
        for key, v in sorted(drift.items()):
            source, _, stat = key.partition("|")
            lines.append(f"  {source} [{stat}]: {v:.4g}")
    dropped = rec.dropped_events()
    if dropped:
        lines.append(
            f"WARNING: {dropped} events dropped past the buffer cap"
            " (aggregate counters above still include them)"
        )
    export_errors = rec.export_errors()
    if export_errors:
        lines.append(
            f"WARNING: {export_errors} exporter tick(s) failed — telemetry"
            " artifacts may be stale (the exporter keeps retrying)"
        )
    registry = rec.timeseries
    if registry is not None and registry.names():
        # requested lookback clamped to what the ring actually holds — the
        # header must not claim a longer window than the series span
        window_s = min(
            WINDOW_EXPORT_SECONDS,
            min(
                s.n_buckets * s.bucket_seconds
                for s in (registry.get(n) for n in registry.names())
            ),
        )
        lines.append(f"windowed series (last {window_s:g}s):")
        for name in registry.names():
            s = registry.get(name)
            n = s.count(window_s)
            if not n:
                continue
            if s.kind == "distribution":
                qs = s.quantiles((0.5, 0.95, 0.99), window_s=window_s)
                q50, q95, q99 = (f"{v:.4g}" for v in qs) if qs else ("-", "-", "-")
                lines.append(f"  {name}: n={n} p50={q50} p95={q95} p99={q99}")
            else:
                lines.append(f"  {name}: n={n} rate={s.rate(window_s):.4g}/s")
    if sigs:
        lines.append("distinct call signatures per entry point:")
        for entry, n in sorted(sigs.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {entry}: {n}")
    if compiles:
        lines.append("compile bills per entry point (count, total ms):")
        for entry, n in sorted(compiles.items(), key=lambda kv: -compile_times.get(kv[0], 0.0)):
            lines.append(f"  {entry}: {n} compiles, {compile_times.get(entry, 0.0) * 1e3:.1f} ms")
    if hwm:
        slice_counts = rec.footprint_slice_counts()
        lines.append("state-footprint high-water marks:")
        for metric, nbytes in sorted(hwm.items(), key=lambda kv: -kv[1]):
            n_slices = slice_counts.get(metric)
            if n_slices:
                # sliced-state marks carry the per-slice average so slice-
                # count growth reads differently from per-slice state growth
                lines.append(
                    f"  {metric}: {nbytes} bytes"
                    f" ({nbytes / n_slices:.1f} B/slice over {n_slices} slices)"
                )
            else:
                lines.append(f"  {metric}: {nbytes} bytes")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# continuous export
# ---------------------------------------------------------------------------

class PeriodicExporter:
    """Background thread that re-exports telemetry artifacts on an interval.

    Long jobs should not need an explicit export call at every checkpoint:
    give the exporter a Prometheus textfile path and/or a JSONL path (both
    atomically re-rendered on ticks where anything new was recorded — a
    scraper or tail can read at any moment and never sees a truncation),
    then ``start()`` it. ``stop()`` — also registered via ``atexit`` —
    performs one final export, so events recorded between the last tick
    and interpreter exit still land.

    Rank-zero gated: on other ranks ``start()`` is a no-op, matching the
    exporters it drives. Restartable: ``start()`` after ``stop()`` begins
    a fresh thread.

    **Hardened against bad ticks**: an exception inside one export tick
    (ENOSPC, permissions, a non-serializable event field) is caught,
    counted (``export_errors`` here, ``record_export_error`` on the
    recorder — surfaced by ``summary()``, the
    ``metrics_tpu_export_errors_total`` Prometheus family, and the health
    snapshot), warned once, and the thread KEEPS ticking — continuous
    export must degrade to stale-but-recovering, never die silently.

    **Health integration**: pass a
    :class:`~metrics_tpu.observability.health.HealthMonitor` as
    ``health`` and every tick evaluates it (firing/clearing alarms on
    schedule even when no new events arrive — clearing is time passing)
    and appends its Prometheus families to the Prometheus artifact.

    **Fleet publishing**: pass a
    :class:`~metrics_tpu.observability.collector.SnapshotSink` as
    ``snapshot_sink`` and every tick also publishes one fleet snapshot —
    the recorder's counter payload (telemetry), plus the metric states
    returned by ``states_fn`` when given (a zero-arg callable returning
    the :func:`~metrics_tpu.observability.wire.snapshot_states` dict, or
    the metric/collection itself to snapshot — the latter also embeds
    the structural layout key the collector validates against; when
    ``states_fn`` returns a bare dict, pass the metric/collection as
    ``states_template`` so dict-publishing ticks do not bypass that
    validation). Published on EVERY tick, even idle ones: the snapshot
    is the publisher's heartbeat — the collector's ``publisher_stale``
    alarm watches for its absence. ``snapshot_mode`` is ``"state"``
    (cumulative, the default) or ``"delta"`` (the caller resets after
    each tick).
    """

    def __init__(
        self,
        interval_s: float = 30.0,
        prometheus_path: Optional[str] = None,
        jsonl_path: Optional[str] = None,
        recorder: Optional[Any] = None,
        health: Optional[Any] = None,
        snapshot_sink: Optional[Any] = None,
        states_fn: Optional[Any] = None,
        states_template: Optional[Any] = None,
        snapshot_mode: str = "state",
    ) -> None:
        if prometheus_path is None and jsonl_path is None and snapshot_sink is None:
            raise ValueError(
                "PeriodicExporter needs a prometheus_path, a jsonl_path, and/or a snapshot_sink"
            )
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        self.interval_s = float(interval_s)
        self.prometheus_path = prometheus_path
        self.jsonl_path = jsonl_path
        self.health = health
        self.snapshot_sink = snapshot_sink
        self.states_fn = states_fn
        self.states_template = states_template
        self.snapshot_mode = snapshot_mode
        self.export_errors = 0
        self._recorder = recorder
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        # (event count, dropped count) at the last export; every counter
        # mutation either appends an event or bumps the dropped tally, so
        # this pair is a complete change detector. None = never exported.
        self._exported_state: Optional[tuple] = None
        self._warned = False
        self._lock = threading.Lock()

    def start(self) -> "PeriodicExporter":
        if _process_index() != 0:
            return self
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_event = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="metrics-tpu-telemetry-export", daemon=True
            )
            self._thread.start()
        atexit.register(self.stop)
        return self

    def _run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.export_once()
            except Exception as err:  # noqa: BLE001
                # one bad tick (ENOSPC, a permissions hiccup, an event with
                # a non-serializable field) must not kill continuous export
                # for the rest of the job — count it (visible in summary(),
                # the Prometheus page, and the health snapshot), warn once,
                # and keep ticking
                self.export_errors += 1
                rec = _resolve(self._recorder)
                try:
                    rec.record_export_error(err)
                except Exception:  # noqa: BLE001 — counting must not re-raise
                    pass
                if not self._warned:
                    self._warned = True
                    from metrics_tpu.utils.prints import rank_zero_warn

                    rank_zero_warn(
                        f"Telemetry: a PeriodicExporter tick failed ({err!r});"
                        " the thread keeps running and will retry next tick."
                        " Further tick failures are counted (export_errors),"
                        " not re-warned.",
                        UserWarning,
                    )

    def export_once(self) -> None:
        """One export tick (also usable manually, without the thread).

        Both artifacts are re-rendered in FULL (the recorder holds every
        event in memory anyway, bounded by its event cap) and swapped in
        atomically — no read-modify-append cycle, and a reader always sees
        a complete artifact. A tick where nothing was recorded since the
        last one skips the writes entirely (after the first tick, which
        always materializes the artifacts) — UNLESS a health monitor or a
        time-series registry rides along: windowed stats and alarm states
        change with the clock, not only with new events, so those ticks
        always re-evaluate and re-render the Prometheus artifact."""
        rec = _resolve(self._recorder)
        events = rec.events()
        snapshot = None
        if self.health is not None:
            # evaluated OUTSIDE the exporter lock (rule evaluation does
            # sketch math) and unconditionally: alarms must clear on
            # schedule even when the job records nothing new
            snapshot = self.health.evaluate()
        if self.snapshot_sink is not None:
            # every tick, even idle ones: the snapshot doubles as the
            # publisher heartbeat the collector's liveness tracking needs
            self._publish_snapshot(rec)
        with self._lock:
            state = (len(events), rec.dropped_events())
            live_window = self.health is not None or rec.timeseries is not None
            if state == self._exported_state and not live_window:
                return
            if self.prometheus_path is not None:
                text = render_prometheus(rec)
                if snapshot is not None:
                    text += "\n".join(self.health.prometheus_lines(snapshot)) + "\n"
                _atomic_write(self.prometheus_path, text)
            if state != self._exported_state and self.jsonl_path is not None:
                _atomic_write(
                    self.jsonl_path, "".join(json.dumps(e) + "\n" for e in events)
                )
            self._exported_state = state

    def _publish_snapshot(self, rec: Any) -> None:
        """One fleet snapshot into the configured sink: the recorder's
        counter payload plus (when ``states_fn`` is set) the metric
        states. ``states_fn`` may return the canonical states dict or the
        metric/collection itself."""
        from metrics_tpu.observability.aggregate import counter_payload

        states = None
        template = self.states_template
        if self.states_fn is not None:
            obj = self.states_fn()
            if obj is not None:
                if isinstance(obj, dict):
                    # a bare dict carries no structure of its own — the
                    # explicit states_template (when given) supplies the
                    # layout key so these snapshots do not bypass the
                    # collector's validation
                    states = obj
                else:
                    from metrics_tpu.observability.wire import snapshot_states

                    states = snapshot_states(obj)
                    template = obj
        self.snapshot_sink.publish(
            states=states,
            states_template=template,
            telemetry=counter_payload(rec),
            mode=self.snapshot_mode,
        )

    def stop(self) -> None:
        """Stop the thread and perform one final export. Idempotent."""
        thread = self._thread
        self._stop_event.set()
        if thread is not None:
            thread.join(timeout=max(5.0, self.interval_s))
            self._thread = None
        if _process_index() == 0:
            try:
                self.export_once()
            except Exception:  # noqa: BLE001 — exit paths must not raise
                pass
        try:
            atexit.unregister(self.stop)
        except Exception:
            pass
