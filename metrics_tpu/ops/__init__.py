"""Pallas TPU kernels for hot ops (SURVEY §2.9 native-equivalents plan).

Every op routes through the shared dispatch registry
(:mod:`metrics_tpu.ops.dispatch`): a Pallas kernel where the route
predicate predicts a TPU win, a jnp fallback everywhere else (CPU CI,
exotic dtypes, the ``METRICS_TPU_NO_PALLAS`` kill switch), and interpret
mode for CPU parity tests. Dispatches are counted per ``(op, backend)``
on the telemetry recorder (``metrics_tpu_ops_dispatch_total``).

Registered ops: ``box_iou`` (tiled pairwise/batched IoU), ``bincount`` /
``segment_sum`` (the tiled one-hot MXU scatter serving confusion-matrix
metrics and the ``SlicedMetric`` slice axis), ``segment_max`` /
``segment_min`` (the masked-select extremum scatter), ``qsketch_compact``
(the fused sort->bucket->segment-merge t-digest compaction),
``row_topk`` (the fused per-row top-k + payload gather behind the
retrieval table's compaction and merge), and ``trace_sqrtm`` (the
jnp-only Newton–Schulz ``tr((Σ₁Σ₂)^{1/2})`` behind streaming FID's
device-side compute). See docs/ops_kernels.md.
"""
from metrics_tpu.ops.dispatch import (  # noqa: F401
    NO_PALLAS_ENV,
    KernelSpec,
    dispatch,
    dispatch_mode,
    forced_backend,
    get_kernel,
    kernel_names,
    pallas_disabled,
    register_kernel,
)
from metrics_tpu.ops.scatter_pallas import (  # noqa: F401
    bincount_dispatch,
    segment_extremum_tiled,
    segment_max_dispatch,
    segment_min_dispatch,
    segment_sum_dispatch,
    segment_sum_tiled,
)
from metrics_tpu.ops.qsketch_pallas import (  # noqa: F401
    qsketch_compact_dispatch,
    qsketch_sort_bucket_tiled,
)
from metrics_tpu.ops.topk_pallas import (  # noqa: F401
    row_topk_dispatch,
    row_topk_tiled,
)
from metrics_tpu.ops.box_iou_pallas import box_iou_dispatch, box_iou_tiled  # noqa: F401
from metrics_tpu.ops.sqrtm import (  # noqa: F401
    NEWTON_SCHULZ_ITERS,
    trace_sqrtm_dispatch,
)
