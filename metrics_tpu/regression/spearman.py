"""Modular SpearmanCorrCoef (rank-sketch streaming default; exact opt-in).

Behavior parity with /root/reference/torchmetrics/regression/spearman.py:25-92.
The default state is a fixed-capacity rank/co-moment sketch
(``metrics_tpu/sketches/rank.py``): O(``sketch_capacity``) memory, a
fixed-shape jit-safe update (fusible / bucketable / async-capable), and a
``"merge"``-reduced leaf that syncs across ranks in the existing
collective round. Inside the lossless window (stream fits the capacity)
compute runs the exact tie-averaged rank kernel bit-for-bit; beyond it the
weighted-midrank estimator takes over under the quantile sketch's
rank-error envelope. ``exact=True`` restores the reference's unbounded
cat-state path (and its large-memory warning — which is why the warning is
gated on that flag rather than fired unconditionally).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.rank import (
    ranksketch_init,
    ranksketch_insert,
    ranksketch_merge_fx,
    ranksketch_spearman,
)
from metrics_tpu.sketches.reservoir import reservoir_fill
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.exceptions import MetricsUserError

try:
    from metrics_tpu.utils.checks import _is_concrete
except ImportError:  # pragma: no cover
    def _is_concrete(*arrays):
        return True

Array = jax.Array

#: default rank-sketch capacity — (pred, target) pairs at 8192 rows are
#: ~96 KiB for <0.05% relative rank error; smaller streams stay bit-exact
DEFAULT_RANK_CAPACITY = 8192


class SpearmanCorrCoef(Metric):
    """Computes the Spearman rank correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> spearman = SpearmanCorrCoef()
        >>> spearman(preds, target)
        Array(0.9999992, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = False  # sketch default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"
    __fused_mask_valid__ = True

    def __init__(
        self,
        exact: bool = False,
        sketch_capacity: int = DEFAULT_RANK_CAPACITY,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self._exact = bool(exact)
        if self._exact:
            register_exact_list_states(self, ("preds", "target"))
            warn_exact_buffer("SpearmanCorrcoef", "targets and predictions")
        else:
            if not (isinstance(sketch_capacity, int) and sketch_capacity > 0):
                raise ValueError(
                    f"Argument `sketch_capacity` must be a positive int, got {sketch_capacity}"
                )
            self.add_state(
                "rsketch", default=ranksketch_init(sketch_capacity), dist_reduce_fx=ranksketch_merge_fx()
            )
            self.add_state("n_seen", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        # per-rank priority stream: identical seeds across ranks would draw
        # identical reservoir priorities and bias the cross-rank union
        self._key_seed = jax.process_index()

    def _update(self, preds: Array, target: Array, n_valid: Optional[Array] = None) -> None:
        preds, target = _spearman_corrcoef_update(preds, target)
        if self._exact:
            self.preds.append(preds)
            self.target.append(target)
            return
        self.rsketch = ranksketch_insert(
            self.rsketch, preds, target, self.n_seen, seed=self._key_seed, n_valid=n_valid
        )
        self.n_seen = self.n_seen + preds.reshape(-1).shape[0]

    def _compute(self) -> Array:
        if self._exact:
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
            return _spearman_corrcoef_compute(preds, target)
        leaf = jnp.asarray(self.rsketch)
        fill = reservoir_fill(leaf)
        n_seen = jnp.asarray(self.n_seen)
        if not _is_concrete(fill, n_seen):
            raise MetricsUserError(
                "sketch-backed SpearmanCorrCoef compute reads the occupancy on the host and"
                " cannot run under jit; compute eagerly (update_state/FusedUpdate stay jit-safe)"
            )
        n = int(fill)
        if n == int(n_seen):
            # lossless window: rows are the exact stream in arrival order
            rows = leaf[:n]
            return _spearman_corrcoef_compute(rows[:, 1], rows[:, 2])
        return ranksketch_spearman(leaf)
