"""Layout/collective soundness rules: TL-SHARD, TL-MERGE, TL-WIRE, TL-LOCK.

The distributed correctness of the whole library rests on per-leaf reducer
semantics: a partition spec claiming a replicated leaf sharded makes
``sync_pytree_in_mesh`` silently SKIP a required cross-rank reduction (the
bug class PR 8's review found twice at runtime), a non-commutative merge
fold breaks the fleet collector's arrival-order-independence contract, and
a state leaf without a wire-serializable dtype/shape/reducer triple cannot
ride the snapshot wire at all. These rules make those contracts static,
checked against the layout manifest (``analysis/layout.py``) derived from
the same interp walk — plus TL-LOCK, a guarded-by discipline check for the
two host-side concurrency planes (``core/pipeline.py``,
``observability/collector.py``; the PR 7 review-round race class).

Registered from ``rules.py`` (import at module bottom) so ``all_rules()``
and the CLI pick them up; same pragma and empty-baseline contract as every
other rule.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Violation
from .rules import (
    Rule,
    _attr_chain,
    _is_metric_like,
    _last_name,
    _shared_project,
    collect_classes,
    register_rule,
)

# ---------------------------------------------------------------------------
# shared layout universe (built once per process, like _shared_project)
# ---------------------------------------------------------------------------

_UNIVERSE: Optional[Dict[str, Set[str]]] = None


def _shared_universe() -> Dict[str, Set[str]]:
    """Path -> admissible-shard-axes map over the whole package, derived
    from a fresh in-memory layout-manifest build (never the committed
    file: the rules must see the CURRENT source, not a stale artifact)."""
    global _UNIVERSE
    if _UNIVERSE is None:
        from .layout import build_layout_manifest, shard_path_universe

        _UNIVERSE = shard_path_universe(build_layout_manifest(_shared_project()))
    return _UNIVERSE


# ---------------------------------------------------------------------------
# TL-SHARD
# ---------------------------------------------------------------------------

#: names whose ``re.escape(<name>)`` interpolation inside an f-string rule
#: pattern is statically resolvable (mirrors of the runtime constants —
#: see layout.py)
_PATTERN_CONSTANTS = {
    "SLICED_FOOTPRINT_PREFIX": "sliced/",
    "SKETCH_FOOTPRINT_PREFIX": "sketch/",
    "WINDOWED_FOOTPRINT_PREFIX": "windowed/",
    "SLICE_ROWS": "_slice_rows",
}

_SPEC_NAMES = {"PartitionSpec", "P"}


def _eval_pattern(node: ast.AST) -> Optional[str]:
    """Statically evaluate a partition-rule regex expression: a plain
    string constant, or an f-string whose interpolations are
    ``re.escape(<known constant>)``. None when beyond the lattice."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for value in node.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            elif isinstance(value, ast.FormattedValue):
                inner = value.value
                if (
                    isinstance(inner, ast.Call)
                    and _attr_chain(inner.func)[-1:] == ["escape"]
                    and len(inner.args) == 1
                ):
                    arg = inner.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                        parts.append(re.escape(arg.value))
                        continue
                    name = _last_name(arg)
                    if name in _PATTERN_CONSTANTS:
                        parts.append(re.escape(_PATTERN_CONSTANTS[name]))
                        continue
                return None
            else:
                return None
        return "".join(parts)
    return None


def _spec_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``PartitionSpec(...)`` call a rule-pair's second element is."""
    if isinstance(node, ast.Call) and _last_name(node.func) in _SPEC_NAMES:
        return node
    return None


def _spec_names_axis(call: ast.Call) -> bool:
    """True when the ``PartitionSpec`` call places a NAMED axis (any
    non-None argument)."""
    return any(
        not (isinstance(a, ast.Constant) and a.value is None) for a in call.args
    )


def _rule_pairs(node: ast.AST) -> Optional[List[Tuple[ast.AST, Optional[str], ast.Call]]]:
    """Extract a partition-rule set from a tuple/list literal of
    ``(pattern, PartitionSpec(...))`` pairs; None when the literal is not
    one. A pair's pattern slot is None when statically unevaluable."""
    if not isinstance(node, (ast.Tuple, ast.List)) or not node.elts:
        return None
    pairs = []
    for elt in node.elts:
        if not (isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) == 2):
            return None
        spec = _spec_call(elt.elts[1])
        if spec is None:
            return None
        pattern_node = elt.elts[0]
        if not isinstance(pattern_node, (ast.Constant, ast.JoinedStr)):
            return None
        pairs.append((elt, _eval_pattern(pattern_node), spec))
    return pairs


def _axis_claim(node: ast.AST) -> Optional[ast.Call]:
    """The named-axis ``PartitionSpec`` call a spec-producing expression
    bottoms out in, unwrapping ``.spec`` attributes and ``NamedSharding``
    wrappers; None when the expression routes through a helper call (the
    helper owns the divisibility guard) or places no axis."""
    while isinstance(node, ast.Attribute):
        node = node.value
    spec = _spec_call(node)
    if spec is not None:
        return spec if _spec_names_axis(spec) else None
    if isinstance(node, ast.Call) and _last_name(node.func) == "NamedSharding":
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            inner = _spec_call(arg)
            if inner is not None and _spec_names_axis(inner):
                return inner
    return None


_STATE_ITER_ATTRS = {"_defaults", "_reductions", "_state_names", "state_footprint"}


@register_rule
class ShardRule(Rule):
    """Partition-rule coverage and spec/reducer agreement, checked against
    the layout manifest's path universe (every footprint path any
    state-registering class can produce).

    A ``PartitionSpec`` naming a mesh axis tells ``sync_pytree_in_mesh``
    the leaf is owned DISJOINTLY across the axis, so the sync passes it
    through with no collective. That is only true for ``[S]`` slice rows
    (and ``[R]`` ring slots); on a replicated leaf the claim silently
    drops a REQUIRED cross-rank reduction and every rank keeps its local
    partial — the PR 8 bug class. Checked statically: committed rule sets
    must give every leaf path a first-match (the runtime raises on
    unmatched), named-axis rules must only ever first-match ``[S]``/``[R]``
    paths, spec dict literals must not claim replicated leaves sharded,
    and per-leaf spec comprehensions must route through a divisibility
    guard instead of claiming every leaf unconditionally.
    """

    id = "TL-SHARD"
    description = "partition spec/rule claims a shard layout the leaf's reducer cannot honor"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        universe = _shared_universe()
        seen_sets: Set[int] = set()
        for node in ast.walk(ctx.tree):
            pairs = _rule_pairs(node) if id(node) not in seen_sets else None
            if pairs is not None:
                seen_sets.update(id(p[0]) for p in pairs)
                yield from self._check_rule_set(ctx, node, pairs, universe)
            elif isinstance(node, ast.Dict):
                yield from self._check_spec_dict(ctx, node, universe)
            elif isinstance(node, ast.DictComp):
                yield from self._check_spec_comp(ctx, node)

    def _check_rule_set(self, ctx, node, pairs, universe) -> Iterator[Violation]:
        if any(pattern is None for _, pattern, _ in pairs):
            return  # an unevaluable pattern breaks first-match reasoning
        compiled = []
        for pair_node, pattern, spec in pairs:
            try:
                compiled.append((pair_node, re.compile(pattern), spec))
            except re.error:
                return
        unmatched: List[str] = []
        bad_by_pair: Dict[int, Tuple[ast.AST, List[str]]] = {}
        for path in sorted(universe):
            for pair_node, rx, spec in compiled:
                if rx.search(path) is None:
                    continue
                if _spec_names_axis(spec) and not universe[path]:
                    entry = bad_by_pair.setdefault(id(pair_node), (pair_node, []))
                    entry[1].append(path)
                break
            else:
                unmatched.append(path)
        if unmatched:
            sample = ", ".join(unmatched[:3])
            yield self.violation(
                ctx,
                node,
                f"partition-rule set leaves {len(unmatched)} state-leaf path(s) unmatched "
                f"(e.g. {sample}); match_partition_rules raises on the first one — add a "
                "catch-all replicate rule",
            )
        for pair_node, paths in bad_by_pair.values():
            sample = ", ".join(paths[:3])
            yield self.violation(
                ctx,
                pair_node,
                f"named-axis partition rule first-matches {len(paths)} leaf path(s) whose "
                f"reducer requires a cross-rank reduction (e.g. {sample}); the sync path "
                "would pass them through unreduced — scope the pattern to [S]/[R] paths "
                "or replicate",
            )

    def _check_spec_dict(self, ctx, node, universe) -> Iterator[Violation]:
        for key, value in zip(node.keys, node.values):
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                continue
            claim = _axis_claim(value)
            if claim is None:
                continue
            axes = universe.get(key.value)
            if axes is not None and not axes:
                yield self.violation(
                    ctx,
                    value,
                    f"spec claims state leaf `{key.value}` sharded, but every class "
                    "registering that leaf needs a cross-rank reduction for it "
                    "(replicated in the layout manifest); the sync path would skip "
                    "the reduction and keep per-rank partials",
                )

    def _check_spec_comp(self, ctx, node) -> Iterator[Violation]:
        claim = _axis_claim(node.value)
        if claim is None:
            return
        if any(gen.ifs for gen in node.generators):
            return
        if any(isinstance(sub, ast.IfExp) for sub in ast.walk(node.value)):
            return
        iters_states = any(
            isinstance(sub, ast.Attribute) and sub.attr in _STATE_ITER_ATTRS
            for gen in node.generators
            for sub in ast.walk(gen.iter)
        )
        if not iters_states:
            return
        yield self.violation(
            ctx,
            node,
            "claims EVERY state leaf sharded unconditionally; leaves the divisibility "
            "fallback leaves replicated would skip their required cross-rank reduction "
            "— route the spec through get_naive_slice_sharding (or an equivalent guard)",
        )


# ---------------------------------------------------------------------------
# TL-MERGE
# ---------------------------------------------------------------------------

_NONCOMMUTATIVE_OPS = (ast.Sub, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow, ast.MatMult)

_HOST_STATE_ROOTS = {"time", "random", "os", "datetime"}


def _merge_like_classes(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "merge_like" for t in stmt.targets
                )
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is True
            ):
                yield node
                break


def _class_attr_constant(node: ast.ClassDef, name: str) -> object:
    for stmt in node.body:
        if (
            isinstance(stmt, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == name for t in stmt.targets)
            and isinstance(stmt.value, ast.Constant)
        ):
            return stmt.value.value
    return None


def _tainted(node: ast.AST, taint: Set[str]) -> bool:
    return any(
        isinstance(sub, ast.Name) and sub.id in taint for sub in ast.walk(node)
    )


def _fold_taint(fn: ast.FunctionDef) -> Set[str]:
    """Names derived from the stacked-leaves argument of a merge fold
    (forward may-taint over simple assignments, fixed-point)."""
    args = [a.arg for a in fn.args.args if a.arg != "self"]
    taint: Set[str] = set(args[:1])
    changed = True
    while changed:
        changed = False
        for sub in ast.walk(fn):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AugAssign):
                targets, value = [sub.target], sub.value
            if value is None or not _tainted(value, taint):
                continue
            for target in targets:
                if isinstance(target, ast.Name) and target.id not in taint:
                    taint.add(target.id)
                    changed = True
    return taint


@register_rule
class MergeRule(Rule):
    """Fold-algebra soundness for ``merge_like``-tagged reducers.

    The fleet collector folds per-publisher snapshots through these
    callables in ARRIVAL order and pins the result byte-identical under
    any arrival permutation — so a fold step that subtracts/divides two
    stack-derived operands (non-commutative), reads host state (time,
    RNG, environment), or mutates the reducer instance breaks the
    contract invisibly until two fleets disagree. Ring folds
    (``windowed_kind = "ring"``) must additionally fold slot-aligned:
    a full reduce or flatten over the stacked rings mixes time buckets
    across ranks.
    """

    id = "TL-MERGE"
    description = "merge-tagged fold is order-dependent, host-stateful, or mixes ring slots"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for cls in _merge_like_classes(ctx.tree):
            call_fn = next(
                (
                    s
                    for s in cls.body
                    if isinstance(s, ast.FunctionDef) and s.name == "__call__"
                ),
                None,
            )
            if call_fn is None:
                continue
            taint = _fold_taint(call_fn)
            is_ring = _class_attr_constant(cls, "windowed_kind") == "ring"
            for node in ast.walk(call_fn):
                if (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, _NONCOMMUTATIVE_OPS)
                    and _tainted(node.left, taint)
                    and _tainted(node.right, taint)
                ):
                    yield self.violation(
                        ctx,
                        node,
                        f"`{cls.name}.__call__` folds stacked leaves through a "
                        f"non-commutative `{type(node.op).__name__}` step; the collector "
                        "folds snapshots in arrival order, so the merged result depends "
                        "on which rank arrived first",
                    )
                elif isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain and (
                        chain[0] in _HOST_STATE_ROOTS
                        or (len(chain) >= 2 and chain[1] == "random")
                    ):
                        yield self.violation(
                            ctx,
                            node,
                            f"`{cls.name}.__call__` reads host state "
                            f"(`{'.'.join(chain)}`); a merge fold must be a pure "
                            "function of the stacked leaves or two collectors folding "
                            "the same snapshots diverge",
                        )
                    elif (
                        is_ring
                        and chain
                        and chain[-1] in ("sum", "max", "min", "mean", "prod")
                        and node.args
                        and _tainted(node.args[0], taint)
                        and not any(kw.arg == "axis" for kw in node.keywords)
                        and len(node.args) < 2
                    ):
                        yield self.violation(
                            ctx,
                            node,
                            f"`{cls.name}.__call__` full-reduces the stacked rings "
                            f"(`{chain[-1]}` with no axis); ring folds must stay "
                            "slot-aligned — reduce over axis 0 or vmap the inner merge "
                            "over the slot axis",
                        )
                    elif (
                        is_ring
                        and chain
                        and chain[-1] in ("ravel", "flatten")
                        and isinstance(node.func, ast.Attribute)
                        and _tainted(node.func.value, taint)
                    ):
                        yield self.violation(
                            ctx,
                            node,
                            f"`{cls.name}.__call__` flattens stack-derived ring state "
                            f"(`.{chain[-1]}()`), mixing time-bucket slots across ranks",
                        )
                elif (
                    isinstance(node, (ast.Assign, ast.AugAssign))
                    and any(
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        for t in (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                    )
                ):
                    yield self.violation(
                        ctx,
                        node,
                        f"`{cls.name}.__call__` mutates the reducer instance; merge "
                        "folds are shared process-wide singletons and must stay "
                        "stateless",
                    )


# ---------------------------------------------------------------------------
# TL-WIRE
# ---------------------------------------------------------------------------

def _own_add_state_calls(cls: ast.ClassDef) -> List[Tuple[ast.Call, Optional[ast.FunctionDef]]]:
    """``self.add_state(...)`` calls in THIS class body, each with its
    enclosing method (for parameter-derived exemptions)."""
    out: List[Tuple[ast.Call, Optional[ast.FunctionDef]]] = []

    def walk(node: ast.AST, fn: Optional[ast.FunctionDef]) -> None:
        for child in ast.iter_child_nodes(node):
            child_fn = child if isinstance(child, ast.FunctionDef) else fn
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr == "add_state"
                and isinstance(child.func.value, ast.Name)
                and child.func.value.id == "self"
            ):
                out.append((child, fn))
            walk(child, child_fn)

    walk(cls, None)
    return out


def _fn_params(fn: Optional[ast.FunctionDef]) -> Set[str]:
    if fn is None:
        return set()
    args = list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
    out = {a.arg for a in args if a.arg != "self"}
    if fn.args.vararg:
        out.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        out.add(fn.args.kwarg.arg)
    return out


def _references_params(node: Optional[ast.AST], params: Set[str]) -> bool:
    if node is None or not params:
        return False
    return any(
        isinstance(sub, ast.Name) and sub.id in params for sub in ast.walk(node)
    )


def _locally_bound(node: Optional[ast.AST], fn: Optional[ast.FunctionDef]) -> bool:
    """True when the default expression is a bare local variable assigned
    in the enclosing method — the layout is derived at construction time
    and ``add_state`` validates it at registration."""
    if fn is None or not isinstance(node, ast.Name):
        return False
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == node.id for t in sub.targets
        ):
            return True
        if isinstance(sub, (ast.AugAssign, ast.AnnAssign)) and isinstance(
            sub.target, ast.Name
        ) and sub.target.id == node.id:
            return True
    return False


@register_rule
class WireRule(Rule):
    """Checkpoint/wire coverage: every ``add_state`` leaf needs a
    wire-serializable dtype/shape/reducer triple
    (``observability/wire.py``).

    The snapshot wire encodes array leaves dtype-stable (bit-exact) and
    folds them through the leaf's reducer under the ``states_key``
    contract; a leaf whose layout is statically opaque rides the wire as
    an untyped JSON value, a bare-callable reducer has no registered fold
    the collector can honor, and a class mixing device states with
    exact-mode cat lists must declare the ``__exact_mode_attr__`` escape
    hatch so consumers can tell the modes apart. Constructor-parameterized
    registrations (the reducer/default chosen by the caller) keep runtime
    authority — ``add_state`` validates them at registration.
    """

    id = "TL-WIRE"
    description = "state leaf lacks a wire-serializable dtype/shape/reducer contract"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        from . import interp

        classes = collect_classes(ctx)
        project = _shared_project()
        for info in classes.values():
            if not _is_metric_like(info, classes):
                continue
            facts = interp.class_facts(project, ctx, info.node)
            calls = _own_add_state_calls(info.node)
            names_count: Dict[str, int] = {}
            for call, _fn in calls:
                if call.args and isinstance(call.args[0], ast.Constant) and isinstance(call.args[0].value, str):
                    name = call.args[0].value
                    names_count[name] = names_count.get(name, 0) + 1
            for call, fn in calls:
                if not (call.args and isinstance(call.args[0], ast.Constant) and isinstance(call.args[0].value, str)):
                    continue
                name = call.args[0].value
                params = _fn_params(fn)
                default = call.args[1] if len(call.args) >= 2 else None
                fx: Optional[ast.AST] = call.args[2] if len(call.args) >= 3 else None
                for kw in call.keywords:
                    if kw.arg == "default":
                        default = kw.value
                    elif kw.arg == "dist_reduce_fx":
                        fx = kw.value
                # W2: a reducer with no registered fold for the states_key
                # contract — an untagged callable (not a known string, not a
                # tagged *merge_fx), unless constructor-parameterized
                if interp._reducer_of(call) == "custom" and not _references_params(fx, params):
                    yield self.violation(
                        ctx,
                        call,
                        f"state `{name}` registers an untagged callable reducer; the "
                        "wire fold and mesh sync only honor the known string reducers "
                        "and `merge_like`-tagged callables — tag the fold (see "
                        "sketches/quantile.py) or use a string reducer",
                    )
                # W1: statically wire-opaque layout — a single registration
                # whose container cannot be resolved and is not
                # config-parameterized; the leaf would ride the wire as an
                # untyped JSON value with no dtype-stable contract
                container, _shape, _dtype = interp._infer_default(default)
                if (
                    container == "unknown"
                    and names_count.get(name, 0) == 1
                    and not _references_params(default, params)
                    and not _locally_bound(default, fn)
                ):
                    yield self.violation(
                        ctx,
                        call,
                        f"state `{name}` has a statically wire-opaque default (neither "
                        "an array constructor, a list, nor constructor-parameterized); "
                        "the snapshot wire cannot guarantee a dtype-stable round-trip "
                        "for it",
                    )
            # W3: exact-mode cat lists without the declared escape hatch — a
            # class mixing fixed-shape device states with list states must
            # declare __exact_mode_attr__ (or __jit_unsafe__) so wire
            # consumers and the fused path can tell the modes apart
            own_entries = interp.state_entries_of(info.node)
            containers = {e.container for e in facts.entries}
            if (
                any(e.container == "list" for e in own_entries)
                and "array" in containers
                and "list" in containers
                and facts.declared is not True
                and facts.exact_attr is None
            ):
                yield self.violation(
                    ctx,
                    info.node,
                    f"`{info.name}` mixes fixed-shape device states with cat-list "
                    "states but declares neither `__exact_mode_attr__` nor "
                    "`__jit_unsafe__`; wire consumers cannot tell which mode a "
                    "snapshot carries",
                )


# ---------------------------------------------------------------------------
# TL-LOCK
# ---------------------------------------------------------------------------

#: guarded-by registry: relpath -> class -> lock attr -> fields whose every
#: read/write outside ``__init__``/``*_locked`` methods must sit inside a
#: lexical ``with self.<lock>:`` scope. Registered fields are VERIFIED
#: lock-clean — growing the registry is the way to pin a new field's
#: discipline; deliberately-unlocked fields (racy-but-benign reads like
#: ``watermark``'s ``_max_t``) stay out with the reason documented at the
#: read site.
GUARDED_FIELDS: Dict[str, Dict[str, Dict[str, Set[str]]]] = {
    "core/pipeline.py": {
        "AsyncUpdateHandle": {
            "_cond": {
                "_pending",
                "_in_flight_bytes",
                "_attempts",
                "_enqueued",
                "_applied",
                "_dropped",
                "_pending_wall",
                "_first_apply_wall",
                "_last_apply_wall",
                "_snapshot_waiters",
            },
        },
    },
    "observability/collector.py": {
        "FleetCollector": {
            "_lock": {
                "_pubs",
                "fold_errors",
                "fold_error_details",
                "clock_skew_clamps",
            },
        },
    },
}


@register_rule
class LockRule(Rule):
    """Guarded-by discipline for the host-side concurrency planes.

    ``AsyncUpdateHandle`` (producer threads + worker) and
    ``FleetCollector`` (ingest + readers) each document a lock that owns
    their counters and queues; a read or write that slips outside the
    ``with`` scope is exactly the torn-counter race class PR 7's review
    rounds caught by hand. The registry (:data:`GUARDED_FIELDS`) names the
    verified fields; ``__init__`` (construction happens-before publication)
    and ``*_locked``-suffixed methods (the documented called-with-lock-held
    convention) are exempt. Closures and nested functions inherit the
    lexical ``with`` scope they are defined in.
    """

    id = "TL-LOCK"
    description = "guarded field accessed outside its lock's `with` scope"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        registry = GUARDED_FIELDS.get(ctx.relpath)
        if not registry:
            return
        for node in ctx.tree.body:
            if not isinstance(node, ast.ClassDef) or node.name not in registry:
                continue
            locks = registry[node.name]
            field_to_lock = {
                field: lock for lock, fields in locks.items() for field in fields
            }
            for stmt in node.body:
                if not isinstance(stmt, ast.FunctionDef):
                    continue
                if stmt.name == "__init__" or stmt.name.endswith("_locked"):
                    continue
                yield from self._scan(ctx, stmt, frozenset(), field_to_lock, stmt.name)

    def _scan(
        self,
        ctx: FileContext,
        node: ast.AST,
        held: frozenset,
        field_to_lock: Dict[str, str],
        method: str,
    ) -> Iterator[Violation]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set(held)
            for item in node.items:
                expr = item.context_expr
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                ):
                    acquired.add(expr.attr)
                yield from self._scan(ctx, expr, held, field_to_lock, method)
            for stmt in node.body:
                yield from self._scan(ctx, stmt, frozenset(acquired), field_to_lock, method)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in field_to_lock
            and field_to_lock[node.attr] not in held
        ):
            yield self.violation(
                ctx,
                node,
                f"`{method}` accesses `self.{node.attr}` outside `with "
                f"self.{field_to_lock[node.attr]}:`; the field's guarded-by contract "
                "(GUARDED_FIELDS) makes unlocked access a torn read/lost update — "
                "take the lock, or rename the method `*_locked` if callers hold it",
            )
        for child in ast.iter_child_nodes(node):
            yield from self._scan(ctx, child, held, field_to_lock, method)
