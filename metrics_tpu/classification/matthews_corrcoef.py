"""Modular MatthewsCorrCoef.

Behavior parity with /root/reference/torchmetrics/classification/
matthews_corrcoef.py:26-102.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.matthews_corrcoef import (
    _matthews_corrcoef_compute,
    _matthews_corrcoef_update,
)

Array = jax.Array


class MatthewsCorrCoef(Metric):
    """Computes the Matthews correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> matthews_corrcoef = MatthewsCorrCoef(num_classes=2)
        >>> matthews_corrcoef(preds, target)
        Array(0.57735026, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: int,
        threshold: float = 0.5,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.threshold = threshold
        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        confmat = _matthews_corrcoef_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def _compute(self) -> Array:
        return _matthews_corrcoef_compute(self.confmat)
