"""Compiler-level profiling, trace spans, and job-wide aggregation tests
(ISSUE 3 tentpole): compiled-cost attribution, recompile billing, span
nesting + Perfetto export, cross-host counter merging, the Prometheus
text-format contract, and the continuous exporter."""
import json
import os
import re
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection, Precision, Recall
from metrics_tpu.aggregation import MeanMetric, SumMetric
from metrics_tpu.classification import ROC, ConfusionMatrix
from metrics_tpu.observability import (
    PeriodicExporter,
    aggregate_across_hosts,
    compiled_cost,
    counter_payload,
    current_span_id,
    export_perfetto,
    get_recorder,
    merge_payloads,
    metric_compile_cost,
    render_prometheus,
    span,
    summary,
)


@pytest.fixture
def recorder():
    """The default recorder, enabled for one test and ALWAYS disabled+reset
    after — the session-level conftest asserts nothing leaks."""
    rec = get_recorder()
    rec.reset()
    rec.enable(recompile_threshold=rec.DEFAULT_RECOMPILE_THRESHOLD, footprint_warn_bytes=None)
    try:
        yield rec
    finally:
        rec.disable()
        rec.footprint_warn_bytes = None
        rec.recompile_threshold = rec.DEFAULT_RECOMPILE_THRESHOLD
        rec.profile_compiles = False
        rec.reset()


# ---------------------------------------------------------------------------
# compiled-cost profiling
# ---------------------------------------------------------------------------

def test_compiled_cost_classification_entry_point(recorder):
    """Acceptance: compiled_cost returns flops/bytes estimates for a jitted
    classification entry point under JAX_PLATFORMS=cpu, and records a
    typed compile event with a non-empty cost payload."""
    from metrics_tpu.functional.classification.auroc import auroc_rank_multiclass

    preds = jnp.asarray(np.random.RandomState(0).rand(64, 10).astype(np.float32))
    target = jnp.asarray(np.random.RandomState(1).randint(0, 10, 64), dtype=jnp.int32)
    report = compiled_cost(
        lambda p, t: auroc_rank_multiclass(p, t, 10, average="macro"),
        preds,
        target,
        entry="auroc_rank_multiclass",
    )
    assert report["entry"] == "auroc_rank_multiclass"
    assert report["flops"] and report["flops"] > 0
    assert report["bytes_accessed"] and report["bytes_accessed"] > 0
    # the wall breakdown is a real measurement, not placeholders
    assert report["compile_s"] > 0
    assert report["lower_s"] >= 0 and report["trace_s"] >= 0
    assert report["cost_analysis"]["flops"] == report["flops"]
    # JSON-safe end to end (the event stream and BENCH artifacts embed it)
    json.dumps(report)

    compile_events = [e for e in recorder.events() if e["type"] == "compile"]
    assert len(compile_events) == 1
    assert compile_events[0]["entry"] == "auroc_rank_multiclass"
    assert compile_events[0]["cost_analysis"]["flops"] > 0
    assert recorder.compile_counts() == {"auroc_rank_multiclass": 1}
    assert recorder.compile_times()["auroc_rank_multiclass"] > 0


def test_recompile_billing_via_profile_compiles(recorder):
    """Acceptance: with profile_compiles on, every NEW (shape, dtype)
    signature a metric update sees — i.e. every recompile — logs a compile
    event carrying a non-empty cost-analysis payload; cache hits do not."""
    recorder.profile_compiles = True
    m = ConfusionMatrix(num_classes=4)
    preds = jnp.asarray(np.random.RandomState(0).randint(0, 4, 16), dtype=jnp.int32)
    target = jnp.asarray(np.random.RandomState(1).randint(0, 4, 16), dtype=jnp.int32)
    m.update(preds, target)          # signature 1 -> compile event
    m.update(preds, target)          # cache hit -> no new compile event
    m.update(preds[:8], target[:8])  # signature 2 -> compile event

    compile_events = [e for e in recorder.events() if e["type"] == "compile"]
    assert len(compile_events) == 2
    assert all(e["entry"] == "ConfusionMatrix.update" for e in compile_events)
    for event in compile_events:
        assert event["cost_analysis"], "recompile event must carry a non-empty cost payload"
        assert event["cost_analysis"]["flops"] >= 0
        assert event["compile_ms"] > 0
    assert recorder.compile_counts() == {"ConfusionMatrix.update": 2}


def test_metric_compile_cost_declines_list_state_metrics(recorder):
    """Cat-state (list) metrics — the `exact=True` opt-out since the sketch
    conversion — have no single compiled executable to bill; the hook must
    decline, never crash the hot path. (The sketch DEFAULT has a fixed-shape
    jit-safe update, so it IS billable now — an upgrade the previous
    default could never have.)"""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the exact-mode large-buffer warning
        roc = ROC(exact=True)
    roc.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 1]))
    assert metric_compile_cost(roc, (jnp.asarray([0.2]), jnp.asarray([1])), {}) is None
    sketched = ROC()
    sketched.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 1]))
    billed = metric_compile_cost(sketched, (jnp.asarray([0.2]), jnp.asarray([1])), {})
    assert billed is not None and billed["entry"] == "ROC.update"


# ---------------------------------------------------------------------------
# trace spans
# ---------------------------------------------------------------------------

def test_span_disabled_is_free():
    rec = get_recorder()
    assert not rec.enabled
    with span("noop") as sp:
        assert sp.span_id is None
        assert current_span_id() is None
    assert rec.events() == []


def test_span_nesting_and_event_attribution(recorder):
    m = SumMetric()
    with span("epoch", epoch=7) as outer:
        assert current_span_id() == outer.span_id
        m.update(jnp.asarray(1.0))
    assert current_span_id() is None

    events = recorder.events()
    spans = {e["span_id"]: e for e in events if e["type"] == "span"}
    outer_event = spans[outer.span_id]
    assert outer_event["name"] == "epoch"
    assert outer_event["parent_id"] is None
    assert outer_event["attributes"] == {"epoch": 7}
    update_span = next(e for e in spans.values() if e["name"] == "SumMetric.update")
    assert update_span["parent_id"] == outer.span_id
    # the flat update row re-attaches to the tree via span_id
    update_event = next(e for e in events if e["type"] == "update")
    assert update_event["span_id"] == update_span["span_id"]


def test_collection_metric_sync_span_tree_and_perfetto(recorder, tmp_path):
    """Acceptance: spans nest correctly across collection -> metric -> sync,
    and export_perfetto emits valid trace-event JSON."""
    col = MetricCollection(
        [Precision(num_classes=3, average="macro"), Recall(num_classes=3, average="macro")]
    )
    preds = jnp.asarray([2, 1, 2, 0])
    target = jnp.asarray([0, 2, 0, 2])
    col.update(preds, target)
    # a custom dist_sync_fn simulates a 2-rank world single-process, forcing
    # the full sync path (and its spans) inside compute
    for m in col.values():
        m.dist_sync_fn = lambda x, group=None: [x, x]
    col.compute()

    spans = [e for e in recorder.events() if e["type"] == "span"]
    by_id = {e["span_id"]: e for e in spans}

    def parents_of(name):
        return [
            by_id.get(e["parent_id"], {}).get("name")
            for e in spans
            if e["name"] == name
        ]

    assert parents_of("Precision.update") == ["MetricCollection.update"]
    assert parents_of("Recall.update") == ["MetricCollection.update"]
    assert parents_of("Precision.compute") == ["MetricCollection.compute"]
    assert parents_of("Precision.sync") == ["Precision.compute"]
    assert parents_of("Recall.sync") == ["Recall.compute"]

    path = str(tmp_path / "trace.json")
    assert export_perfetto(path, recorder) == path
    doc = json.loads(Path(path).read_text())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    # "M" rows are track-labeling metadata (process/thread names — the async
    # worker's labeled track); every non-metadata row is a complete event
    meta = [te for te in doc["traceEvents"] if te.get("ph") == "M"]
    assert any(te["name"] == "process_name" for te in meta)
    for te in doc["traceEvents"]:
        if te.get("ph") == "M":
            assert {"pid", "tid", "name", "args"} <= set(te)
            continue
        assert {"ph", "ts", "dur", "pid", "tid", "name"} <= set(te)
        assert te["ph"] == "X"
        assert te["ts"] >= 0 and te["dur"] >= 0
    # nesting survives the ts/dur rendering: each child span's interval sits
    # inside its parent's (same clock domain up to rounding jitter)
    eps_us = 2_000.0
    te_by_name = {}
    for te in doc["traceEvents"]:
        te_by_name.setdefault(te["name"], []).append(te)
    parent = te_by_name["Precision.compute"][0]
    child = te_by_name["Precision.sync"][0]
    assert child["ts"] >= parent["ts"] - eps_us
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + eps_us


# ---------------------------------------------------------------------------
# job-wide aggregation
# ---------------------------------------------------------------------------

def test_aggregate_across_hosts_single_process_is_local_noop(recorder):
    """Acceptance: in a single-process run the aggregate IS the local
    totals (world size 1, no collective touched)."""
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    float(m.compute())
    recorder.record_sync("gather_all_arrays", gather_bytes=512, world_size=2)

    agg = aggregate_across_hosts(recorder)
    assert agg["world_size"] == 1
    assert agg["call_counts"] == recorder.call_counts()
    assert agg["call_times"] == pytest.approx(recorder.call_times())
    assert agg["sync_totals"] == recorder.sync_totals()
    assert agg["signature_counts"] == recorder.signature_counts()
    assert len(agg["processes"]) == 1 and agg["processes"][0]["process"] == 0


def test_merge_payloads_sums_counts_and_maxes_hwm(recorder):
    m = SumMetric()
    m.update(jnp.ones((2,)))
    recorder.record_footprint(m, {"value": 128})
    p0 = counter_payload(recorder)
    p1 = json.loads(json.dumps(p0))  # an independent "rank 1" payload
    p1["process"] = 1
    p1["footprint_hwm"]["SumMetric"] = 512
    p1["sync_totals"]["gather_bytes"] = 100

    merged = merge_payloads([p0, p1])
    assert merged["world_size"] == 2
    assert merged["call_counts"][("SumMetric", "update")] == 2 * p0["call_counts"]["SumMetric|update"]
    assert merged["footprint_hwm"]["SumMetric"] == 512  # max, not sum
    assert merged["sync_totals"]["gather_bytes"] == p0["sync_totals"]["gather_bytes"] + 100
    assert merged["dropped_events"] == 0


# ---------------------------------------------------------------------------
# Prometheus text-format contract (satellite): minimal in-repo parser
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|NaN|[-+]?Inf)$"
)


def _parse_prometheus(text):
    """Minimal exposition-format parser: returns {name: {"type": ..., "help":
    ..., "samples": [(labels_dict, value)]}} and asserts structural rules
    (HELP/TYPE precede samples; every line parses)."""
    families = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert _NAME_RE.fullmatch(name), f"bad HELP name: {line!r}"
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"help": help_text, "type": None, "samples": []}
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_text = rest.partition(" ")
            assert name in families, f"TYPE before HELP for {name}"
            assert families[name]["type"] is None, f"duplicate TYPE for {name}"
            assert type_text in ("counter", "gauge", "histogram", "summary", "untyped")
            families[name]["type"] = type_text
        elif line.startswith("#"):
            continue  # free comment
        else:
            match = _SAMPLE_RE.match(line)
            assert match, f"unparseable sample line: {line!r}"
            name = match.group("name")
            assert name in families, f"sample {name} has no preceding HELP/TYPE"
            assert families[name]["type"] is not None, f"sample {name} precedes its TYPE"
            labels = {}
            if match.group("labels"):
                for pair in re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"', match.group("labels")):
                    labels[pair[0]] = pair[1]
            families[name]["samples"].append((labels, float(match.group("value"))))
    return families


def _assert_exposition_valid(text):
    families = _parse_prometheus(text)
    assert families, "empty exposition"
    for name, family in families.items():
        if family["type"] == "counter":
            assert name.endswith("_total"), f"counter {name} must end in _total"
    return families


def test_prometheus_exposition_parses_without_process_label(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    float(m.compute())
    recorder.record_sync("gather_all_arrays", gather_bytes=1024, world_size=4, pad_waste_bytes=16)
    recorder.record_compile("MeanMetric.update", compile_s=0.01, cost={"flops": 8.0})

    families = _assert_exposition_valid(render_prometheus(recorder))
    calls = families["metrics_tpu_calls_total"]["samples"]
    assert ({"metric": "MeanMetric", "phase": "update"}, 1.0) in calls
    assert all("process" not in labels for labels, _ in calls)
    assert families["metrics_tpu_compiles_total"]["samples"] == [({"entry": "MeanMetric.update"}, 1.0)]
    assert families["metrics_tpu_gather_bytes_total"]["samples"] == [({}, 1024.0)]


def test_prometheus_exposition_with_process_label(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    recorder.record_sync("gather_all_arrays", gather_bytes=64, world_size=2)
    p0 = counter_payload(recorder)
    p1 = json.loads(json.dumps(p0))
    p1["process"] = 1
    p1["sync_totals"]["gather_bytes"] = 96
    merged = merge_payloads([p0, p1])

    families = _assert_exposition_valid(render_prometheus(recorder, aggregate=merged))
    # merged call counts stay unlabelled; per-rank families carry process
    calls = families["metrics_tpu_calls_total"]["samples"]
    assert ({"metric": "MeanMetric", "phase": "update"}, 2.0) in calls
    gathers = dict(
        (labels["process"], value)
        for labels, value in families["metrics_tpu_gather_bytes_total"]["samples"]
    )
    assert gathers == {"0": 64.0, "1": 96.0}
    seconds = families["metrics_tpu_call_seconds_total"]["samples"]
    assert {labels["process"] for labels, _ in seconds} == {"0", "1"}


# ---------------------------------------------------------------------------
# continuous export
# ---------------------------------------------------------------------------

def test_periodic_exporter_writes_fresh_atomic_artifacts(recorder, tmp_path):
    m = SumMetric()
    m.update(jnp.asarray(1.0))
    prom_path = str(tmp_path / "metrics.prom")
    jsonl_path = str(tmp_path / "telemetry.jsonl")
    exporter = PeriodicExporter(
        interval_s=0.05, prometheus_path=prom_path, jsonl_path=jsonl_path, recorder=recorder
    )
    exporter.start()
    try:
        deadline = time.time() + 5.0
        while not (os.path.exists(prom_path) and os.path.exists(jsonl_path)):
            assert time.time() < deadline, "exporter never ticked"
            time.sleep(0.02)
        m.update(jnp.asarray(2.0))  # recorded after the first tick
    finally:
        exporter.stop()  # final export catches the late event

    lines = Path(jsonl_path).read_text().splitlines()
    events = [json.loads(line) for line in lines]  # every line round-trips
    assert len(events) == len(recorder.events())
    assert [e["type"] for e in events].count("update") == 2
    _assert_exposition_valid(Path(prom_path).read_text())
    # atomic writes leave no tmp droppings, and stop() is idempotent
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]
    exporter.stop()


def test_periodic_exporter_requires_a_path():
    with pytest.raises(ValueError):
        PeriodicExporter(interval_s=1.0)


# ---------------------------------------------------------------------------
# summary alignment (satellite)
# ---------------------------------------------------------------------------

def test_summary_header_aligns_with_short_metric_names(recorder):
    roc = ROC()  # 3-char name: shorter than the "metric" header itself
    roc.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 1]))
    lines = summary(recorder).splitlines()
    header, row = lines[1], lines[2]
    idx = header.index("phase")
    assert header.startswith("metric")
    assert row.startswith("ROC")
    assert row[idx:].startswith("update"), f"phase column sheared: {row!r}"
