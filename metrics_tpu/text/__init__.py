from metrics_tpu.text.bleu import BLEUScore  # noqa: F401
from metrics_tpu.text.cer import CharErrorRate  # noqa: F401
from metrics_tpu.text.mer import MatchErrorRate  # noqa: F401
from metrics_tpu.text.rouge import ROUGEScore  # noqa: F401
from metrics_tpu.text.sacre_bleu import SacreBLEUScore  # noqa: F401
from metrics_tpu.text.wer import WordErrorRate  # noqa: F401
from metrics_tpu.text.wil import WordInfoLost  # noqa: F401
from metrics_tpu.text.wip import WordInfoPreserved  # noqa: F401
