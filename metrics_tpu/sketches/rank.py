"""Rank/co-moment sketch: streaming Spearman over a pair reservoir.

Spearman needs the JOINT rank distribution of (pred, target). A quantile
sketch keyed on pred cannot carry it — collapsing rows adjacent in pred
averages their targets, which deletes the conditional spread of target
given pred and inflates the estimated correlation toward the correlation
of conditional means (measured: +0.18 on a ρ=0.8 stream). The sound
fixed-memory estimator is a UNIFORM SAMPLE of pairs: Spearman computed on
a k-row reservoir is unbiased with standard error ~(1 − ρ²)/√k (≈0.004 at
the default capacity 8192), and inside the lossless window (stream ≤ k)
the reservoir IS the stream, so the exact tie-averaged kernel applies
bit-for-bit.

State is a :mod:`.reservoir` leaf ``[capacity, 3]`` (priority, pred,
target); :func:`ranksketch_spearman` is the jit-safe fixed-shape query
(weighted midranks with occupancy weights — at unit weights it reduces to
the classic tie-averaged rank transform).
"""
import jax
import jax.numpy as jnp

from .reservoir import reservoir_init, reservoir_insert, reservoir_merge, reservoir_merge_fx

Array = jax.Array


def ranksketch_init(capacity: int) -> Array:
    """Fresh ``[capacity, 3]`` (priority, pred, target) reservoir leaf."""
    return reservoir_init(capacity, payload_cols=2)


def ranksketch_insert(
    sketch: Array, preds: Array, target: Array, seen, seed: int = 0, n_valid=None
) -> Array:
    """Insert (pred, target) pairs; pure and jit-safe. ``seen`` is the
    caller's monotone inserted-row counter (seeds the priority draw)."""
    preds = jnp.asarray(preds, jnp.float32).reshape(-1)
    target = jnp.asarray(target, jnp.float32).reshape(-1)
    rows = jnp.stack([preds, target], axis=1)
    return reservoir_insert(sketch, rows, seen, seed=seed, n_valid=n_valid)


ranksketch_merge = reservoir_merge
ranksketch_merge_fx = reservoir_merge_fx


def _weighted_midranks(values: Array, weights: Array) -> Array:
    """Weighted tie-averaged midranks: a value group with weight mass ``W``
    preceded by mass ``S`` ranks at ``S + (W + 1) / 2`` — for unit weights
    this is exactly the classic average-rank convention the unbounded
    ``_rank_data`` kernel implements."""
    n = values.shape[0]
    order = jnp.lexsort((jnp.arange(n, dtype=jnp.int32), jnp.where(weights > 0, values, jnp.inf)))
    sv, sw = values[order], weights[order]
    cum = jnp.cumsum(sw)
    is_start = jnp.concatenate([jnp.ones(1, bool), sv[1:] != sv[:-1]])
    group_id = jnp.cumsum(is_start) - 1
    group_w = jax.ops.segment_sum(sw, group_id, num_segments=n)
    group_end = jax.ops.segment_max(cum, group_id, num_segments=n)
    midrank = (group_end - group_w + (group_w + 1.0) / 2.0)[group_id]
    return jnp.zeros(n, jnp.float32).at[order].set(midrank)


def ranksketch_spearman(sketch: Array, eps: float = 1e-6) -> Array:
    """Spearman correlation of the sampled pairs (jit-safe, fixed-shape);
    occupancy-weighted midranks + the exact kernel's eps-regularized,
    clipped Pearson-of-ranks formula."""
    w = (sketch[:, 0] > -jnp.inf).astype(jnp.float32)
    preds, target = sketch[:, 1], sketch[:, 2]
    total = jnp.clip(jnp.sum(w), 1e-12, None)
    rp = _weighted_midranks(preds, w)
    rt = _weighted_midranks(target, w)
    mp = jnp.sum(w * rp) / total
    mt = jnp.sum(w * rt) / total
    dp = jnp.where(w > 0, rp - mp, 0.0)
    dt = jnp.where(w > 0, rt - mt, 0.0)
    cov = jnp.sum(w * dp * dt) / total
    sp = jnp.sqrt(jnp.sum(w * dp * dp) / total)
    st = jnp.sqrt(jnp.sum(w * dt * dt) / total)
    return jnp.clip(cov / (sp * st + eps), -1.0, 1.0)
