"""Object-detection mAP walkthrough (analog of the reference's
tm_examples/detection_map.py): per-image prediction/target dicts in, full
COCO summary out."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

import jax.numpy as jnp

from metrics_tpu.detection import MeanAveragePrecision


def main() -> None:
    # two images: one near-perfect detection, one with a shifted box and a
    # spurious low-confidence detection
    preds = [
        dict(
            boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
            scores=jnp.asarray([0.536]),
            labels=jnp.asarray([0]),
        ),
        dict(
            boxes=jnp.asarray([[12.0, 8.0, 92.0, 110.0], [300.0, 300.0, 320.0, 330.0]]),
            scores=jnp.asarray([0.715, 0.121]),
            labels=jnp.asarray([1, 1]),
        ),
    ]
    target = [
        dict(boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]), labels=jnp.asarray([0])),
        dict(boxes=jnp.asarray([[10.0, 10.0, 90.0, 105.0]]), labels=jnp.asarray([1])),
    ]

    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(preds, target)
    results = metric.compute()
    for key, value in results.items():
        print(f"{key}: {value}")


if __name__ == "__main__":
    main()
