"""ROUGE with a custom normalizer and tokenizer (analog of the reference's
tm_examples/rouge_score-own_normalizer_and_tokenizer.py)."""
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

from metrics_tpu.functional.text import rouge_score


def normalizer(text: str) -> str:
    """Keep digits and letters only, lowercase (the default drops digits)."""
    return re.sub(r"[^a-z0-9]+", " ", text.lower())


def tokenizer(text: str):
    return text.split()


def main() -> None:
    preds = "Version 2 of the model scored 95 points"
    target = "version 2 of the model scored 95"
    scores = rouge_score(preds, target, normalizer=normalizer, tokenizer=tokenizer)
    for key in sorted(scores):
        print(f"{key}: {float(scores[key]):.4f}")


if __name__ == "__main__":
    main()
