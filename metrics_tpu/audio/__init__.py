"""Audio metrics.

Coverage decision: SNR, SI-SNR, SDR, SI-SDR, and PIT are implemented
TPU-native (reference audio/{snr,sdr,pit}.py). PESQ and STOI are
deliberately deferred: both wrap external native DSP packages (the C
``pesq`` library and ``pystoi`` — reference audio/pesq.py:25,
audio/stoi.py:25 / SURVEY §2.9) that are not installed in this
environment, and their per-utterance host DSP offers no TPU win; they gate
cleanly behind optional-import errors when attempted.
"""
from metrics_tpu.audio.pit import PermutationInvariantTraining  # noqa: F401
from metrics_tpu.audio.sdr import ScaleInvariantSignalDistortionRatio, SignalDistortionRatio  # noqa: F401
from metrics_tpu.audio.snr import ScaleInvariantSignalNoiseRatio, SignalNoiseRatio  # noqa: F401
