"""Sketch primitives: lossless window, error bounds, merge laws, jit parity.

The accuracy contract of ``metrics_tpu/sketches/`` (docs/sketch_states.md):

* inside the lossless window the sketch IS the stream (order and weights);
* beyond it, quantile rank error stays under the advertised
  :func:`rank_error_bound` envelope across ADVERSARIAL orderings;
* ``merge`` is exact below combined capacity and multiset-commutative
  always;
* every transform is pure and jit-safe, bit-identical eager vs jitted.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.sketches import (
    hist_bin_index,
    hist_init,
    hist_insert,
    hist_merge,
    qsketch_fill,
    qsketch_init,
    qsketch_insert,
    qsketch_merge,
    qsketch_quantile,
    qsketch_rank,
    qsketch_total_weight,
    rank_error_bound,
    ranksketch_init,
    ranksketch_insert,
    ranksketch_merge,
    ranksketch_spearman,
    reservoir_fill,
    reservoir_init,
    reservoir_insert,
    reservoir_merge,
    reservoir_rows,
)

_rng = np.random.default_rng(1234)


# ---------------------------------------------------------------------------
# lossless window
# ---------------------------------------------------------------------------


def test_qsketch_lossless_window_preserves_stream_and_order():
    sk = qsketch_init(64, payload_cols=1)
    keys = _rng.random(50).astype(np.float32)
    payload = _rng.random((50, 1)).astype(np.float32)
    for lo in range(0, 50, 13):
        sk = qsketch_insert(sk, jnp.asarray(keys[lo : lo + 13]), jnp.asarray(payload[lo : lo + 13]))
    assert int(qsketch_fill(sk)) == 50
    rows = np.asarray(sk)
    np.testing.assert_array_equal(rows[:50, 0], 1.0)  # unit weights
    np.testing.assert_array_equal(rows[:50, 1], keys)  # arrival order, bit-exact
    np.testing.assert_array_equal(rows[:50, 2:], payload)
    np.testing.assert_array_equal(rows[50:, 0], 0.0)


def test_reservoir_lossless_window_preserves_stream_and_order():
    rs = reservoir_init(32, 3)
    rows = _rng.random((20, 3)).astype(np.float32)
    seen = jnp.asarray(0, jnp.int32)
    for lo in range(0, 20, 7):
        chunk = rows[lo : lo + 7]
        rs = reservoir_insert(rs, jnp.asarray(chunk), seen, seed=9)
        seen = seen + chunk.shape[0]
    assert int(reservoir_fill(rs)) == 20
    np.testing.assert_array_equal(np.asarray(reservoir_rows(rs))[:20], rows)


# ---------------------------------------------------------------------------
# quantile rank error: adversarial orderings vs the advertised epsilon
# ---------------------------------------------------------------------------


def _orderings(n):
    base = _rng.random(n).astype(np.float32)
    organ = np.sort(base)
    organ = np.concatenate([organ[::2], organ[1::2][::-1]])  # organ pipe
    inter = np.empty_like(np.sort(base))
    srt = np.sort(base)
    inter[0::2], inter[1::2] = srt[: (n + 1) // 2], srt[(n + 1) // 2:][::-1][: n // 2]
    ties = np.round(base * 16) / 16  # heavy ties
    return {
        "random": base,
        "sorted": np.sort(base),
        "reversed": np.sort(base)[::-1],
        "organ_pipe": organ,
        "interleaved": inter,
        "ties": ties.astype(np.float32),
    }


@pytest.mark.parametrize("capacity,batch", [(256, 64), (512, 200)])
def test_qsketch_rank_error_within_advertised_bound(capacity, batch):
    n = 8192
    for name, data in _orderings(n).items():
        sk = qsketch_init(capacity)
        for lo in range(0, n, batch):
            sk = qsketch_insert(sk, jnp.asarray(data[lo : lo + batch]))
        # weight conservation is exact whatever the ordering
        np.testing.assert_allclose(float(qsketch_total_weight(sk)), n, rtol=1e-6)
        qs = np.quantile(data, [0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99]).astype(np.float32)
        est = np.asarray(qsketch_rank(sk, jnp.asarray(qs)))
        true = np.array([(data <= q).sum() for q in qs])
        err = np.max(np.abs(est - true))
        bound = rank_error_bound(n, capacity)
        assert err <= bound, (name, capacity, err, bound)


def test_rank_error_bound_zero_inside_window():
    assert rank_error_bound(100, 256) == 0.0
    assert rank_error_bound(10_000, 256) > 0.0


def test_qsketch_quantile_query_accuracy():
    n, capacity = 20000, 1024
    data = _rng.standard_normal(n).astype(np.float32)
    sk = qsketch_init(capacity)
    for lo in range(0, n, 500):
        sk = qsketch_insert(sk, jnp.asarray(data[lo : lo + 500]))
    for q in (0.1, 0.5, 0.9):
        est = float(qsketch_quantile(sk, q)[0])
        lo_ref, hi_ref = np.quantile(data, [max(q - 0.02, 0), min(q + 0.02, 1)])
        assert lo_ref - 1e-3 <= est <= hi_ref + 1e-3, (q, est, lo_ref, hi_ref)


# ---------------------------------------------------------------------------
# merge laws
# ---------------------------------------------------------------------------


def _sorted_rows(leaf):
    rows = np.asarray(leaf)
    return rows[np.lexsort(rows.T[::-1])]


def test_qsketch_merge_exact_below_capacity_and_commutative():
    a = qsketch_insert(qsketch_init(64), jnp.asarray(_rng.random(20).astype(np.float32)))
    b = qsketch_insert(qsketch_init(64), jnp.asarray(_rng.random(30).astype(np.float32)))
    m = qsketch_merge(a, b)
    assert int(qsketch_fill(m)) == 50  # exact: no row lost
    np.testing.assert_allclose(
        _sorted_rows(qsketch_merge(a, b)), _sorted_rows(qsketch_merge(b, a)), atol=1e-6
    )


def test_qsketch_merge_commutative_past_capacity():
    a = qsketch_init(32)
    b = qsketch_init(32)
    for lo in range(0, 512, 32):
        a = qsketch_insert(a, jnp.asarray(_rng.random(32).astype(np.float32)))
        b = qsketch_insert(b, jnp.asarray(_rng.random(32).astype(np.float32)))
    np.testing.assert_allclose(
        _sorted_rows(qsketch_merge(a, b)), _sorted_rows(qsketch_merge(b, a)), atol=1e-6
    )
    np.testing.assert_allclose(
        float(qsketch_total_weight(qsketch_merge(a, b))),
        float(qsketch_total_weight(a)) + float(qsketch_total_weight(b)),
        rtol=1e-6,
    )


def test_reservoir_merge_commutative():
    a = reservoir_init(16, 2)
    b = reservoir_init(16, 2)
    a = reservoir_insert(a, jnp.asarray(_rng.random((40, 2)).astype(np.float32)), jnp.asarray(0), seed=3)
    b = reservoir_insert(b, jnp.asarray(_rng.random((40, 2)).astype(np.float32)), jnp.asarray(0), seed=4)
    np.testing.assert_allclose(
        _sorted_rows(reservoir_merge(a, b)), _sorted_rows(reservoir_merge(b, a)), atol=1e-6
    )


def test_ranksketch_merge_commutative():
    x = _rng.standard_normal(100).astype(np.float32)
    y = (x + _rng.standard_normal(100)).astype(np.float32)
    a = ranksketch_insert(ranksketch_init(32), jnp.asarray(x[:50]), jnp.asarray(y[:50]), jnp.asarray(0), seed=1)
    b = ranksketch_insert(ranksketch_init(32), jnp.asarray(x[50:]), jnp.asarray(y[50:]), jnp.asarray(0), seed=2)
    np.testing.assert_allclose(
        _sorted_rows(ranksketch_merge(a, b)), _sorted_rows(ranksketch_merge(b, a)), atol=1e-6
    )


def test_histogram_merge_commutative_and_exact():
    edges = jnp.linspace(0, 1, 9)
    xa = _rng.random(100).astype(np.float32)
    xb = _rng.random(77).astype(np.float32)
    a = hist_insert(hist_init(8), hist_bin_index(edges, jnp.asarray(xa)), jnp.ones(100))
    b = hist_insert(hist_init(8), hist_bin_index(edges, jnp.asarray(xb)), jnp.ones(77))
    np.testing.assert_array_equal(np.asarray(hist_merge(a, b)), np.asarray(hist_merge(b, a)))
    assert float(jnp.sum(hist_merge(a, b))) == 177.0


# ---------------------------------------------------------------------------
# jit parity + pad masking
# ---------------------------------------------------------------------------


def test_qsketch_insert_jit_bit_parity():
    data = _rng.random(100).astype(np.float32)
    eager = qsketch_insert(qsketch_init(32), jnp.asarray(data))
    jitted = jax.jit(qsketch_insert)(qsketch_init(32), jnp.asarray(data))
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))


def test_n_valid_masks_pad_rows():
    data = jnp.arange(10, dtype=jnp.float32)
    sk = qsketch_insert(qsketch_init(16), data, n_valid=jnp.asarray(6))
    assert int(qsketch_fill(sk)) == 6
    np.testing.assert_array_equal(np.asarray(sk[:6, 1]), np.arange(6, dtype=np.float32))
    rs = reservoir_insert(
        reservoir_init(16, 1), data[:, None], jnp.asarray(0), seed=1, n_valid=jnp.asarray(4)
    )
    assert int(reservoir_fill(rs)) == 4


def test_ranksketch_spearman_matches_scipy_on_large_stream():
    scipy_stats = pytest.importorskip("scipy.stats")
    n, capacity = 20000, 1024
    x = _rng.standard_normal(n).astype(np.float32)
    y = (0.7 * x + 0.5 * _rng.standard_normal(n)).astype(np.float32)
    sk = ranksketch_init(capacity)
    for lo in range(0, n, 500):
        sk = ranksketch_insert(
            sk, jnp.asarray(x[lo : lo + 500]), jnp.asarray(y[lo : lo + 500]), jnp.asarray(lo), seed=5
        )
    got = float(ranksketch_spearman(sk))
    want = scipy_stats.spearmanr(x, y)[0]
    # the pair reservoir is an unbiased sample estimator: se ~ (1-rho^2)/sqrt(k)
    assert abs(got - want) < 0.05, (got, want)


def test_histogram_bin_convention_matches_calibration_kernel():
    from metrics_tpu.functional.classification.calibration_error import _binning_bucketize

    conf = jnp.asarray(_rng.random(200).astype(np.float32))
    acc = jnp.asarray((_rng.random(200) < 0.5).astype(np.float32))
    edges = jnp.linspace(0, 1, 16, dtype=jnp.float32)
    h = hist_init(15, n_stats=3)
    idx = hist_bin_index(edges, conf)
    h = hist_insert(h, idx, jnp.stack([jnp.ones_like(conf), conf, acc]))
    acc_bin, conf_bin, prop_bin = _binning_bucketize(conf, acc, edges)
    count = np.asarray(h[0])
    safe = np.where(count == 0, 1.0, count)
    np.testing.assert_allclose(np.where(count == 0, 0.0, np.asarray(h[1]) / safe), np.asarray(conf_bin), atol=1e-6)
    np.testing.assert_allclose(np.where(count == 0, 0.0, np.asarray(h[2]) / safe), np.asarray(acc_bin), atol=1e-6)
    np.testing.assert_allclose(count / count.sum(), np.asarray(prop_bin), atol=1e-6)


def test_empty_sketch_quantile_and_cdf_return_nan_sentinel():
    """ISSUE 12 satellite: a zero-weight sketch has no distribution — the
    queries return the documented NaN sentinel instead of a confidently
    wrong 0.0 (the un-guarded arithmetic's answer), and the guard is
    explicit rather than an accident of clipping."""
    from metrics_tpu.sketches.quantile import qsketch_cdf, qsketch_init, qsketch_quantile

    empty = qsketch_init(16)
    q = qsketch_quantile(empty, jnp.asarray([0.1, 0.5, 0.9]))
    assert bool(jnp.all(jnp.isnan(q)))
    c = qsketch_cdf(empty, jnp.asarray([0.0, 0.5]))
    assert bool(jnp.all(jnp.isnan(c)))
    # a sketch whose rows were masked to weight 0 is empty too
    masked = qsketch_insert(
        qsketch_init(16), jnp.asarray([1.0, 2.0]), n_valid=jnp.asarray(0, jnp.int32)
    )
    assert bool(jnp.isnan(qsketch_quantile(masked, 0.5)).all())
    # and a NON-empty sketch still answers real values
    live = qsketch_insert(qsketch_init(16), jnp.asarray([1.0, 2.0, 3.0]))
    assert float(qsketch_quantile(live, 0.5)[0]) == 2.0
    assert not bool(jnp.isnan(qsketch_cdf(live, jnp.asarray([2.0]))).any())
