"""Functional detection kernels (reference: torchvision.ops + detection/map.py)."""
from metrics_tpu.functional.detection.box_ops import box_area, box_convert, box_iou  # noqa: F401

__all__ = ["box_area", "box_convert", "box_iou"]
