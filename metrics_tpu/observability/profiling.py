"""Compiler-level cost profiling: what a compiled metric *costs*.

PR 1's recorder counts recompiles but prices nothing. This module asks the
compiler itself: :func:`compiled_cost` lowers and compiles a function
through the AOT pipeline (``jax.jit(...).trace().lower().compile()``),
times each stage, and reads back XLA's ``cost_analysis()`` (flops, bytes
accessed) plus ``memory_analysis()`` (argument/output/temp bytes) where the
backend provides it. The result is a flat JSON-safe dict, and — when the
default recorder is enabled — a typed ``compile`` event in the telemetry
stream.

The recorder's recompile detector hooks in here too: with
``get_recorder().enable(profile_compiles=True)``, every NEW call signature
a ``Metric.update``/``forward`` sees (i.e. every signature that retriggers
XLA compilation of the metric's jitted kernels) bills the compile by
lowering the metric's pure ``update_state`` on the offending arguments —
the recompile warning's count becomes an attributed bill.

Profiling never breaks the hot path: metrics whose update cannot be traced
(``__jit_unsafe__``, list states, host-side numerics) silently decline.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER

__all__ = ["compiled_cost", "metric_compile_cost"]

#: memory_analysis fields worth surfacing (CompiledMemoryStats attributes)
_MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def _normalize_cost(raw: Any) -> Dict[str, float]:
    """XLA's cost_analysis comes back as a dict (or a 1-list of dicts, one
    per computation) keyed by strings like ``"flops"`` / ``"bytes
    accessed"`` / ``"bytes accessed0{}"``; normalize to a flat JSON-safe
    dict with the two headline keys guaranteed present when reported."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else {}
    if not isinstance(raw, dict):
        return {}
    out: Dict[str, float] = {}
    for key, value in raw.items():
        try:
            out[str(key)] = float(value)
        except (TypeError, ValueError):
            continue
    if "bytes accessed" in out and "bytes_accessed" not in out:
        out["bytes_accessed"] = out["bytes accessed"]
    return out


def _normalize_memory(stats: Any) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for field in _MEMORY_FIELDS:
        value = getattr(stats, field, None)
        if isinstance(value, int):
            out[field] = value
    return out


def compiled_cost(
    fn: Callable,
    *args: Any,
    entry: Optional[str] = None,
    static_argnums: Tuple[int, ...] = (),
    recorder: Optional[Any] = None,
    **kwargs: Any,
) -> Dict[str, Any]:
    """Compile ``fn`` on ``args``/``kwargs`` ahead-of-time and return its
    compiler-estimated cost.

    ``fn`` may be a plain callable (jitted here) or an already-jitted
    function (used as-is, so its static_argnums/donation survive). Returns
    a JSON-safe dict::

        {
          "entry": "...",                  # fn name, or the `entry` override
          "trace_s": ..., "lower_s": ..., "compile_s": ...,
          "flops": ...,                    # None when the backend reports none
          "bytes_accessed": ...,
          "cost_analysis": {...},          # the full normalized XLA dict
          "memory_analysis": {...},        # {} where unsupported
        }

    With the (resolved) recorder enabled, a typed ``compile`` event with
    the same payload lands in the event stream. The AOT pipeline compiles
    regardless of the jit cache, so calling this on an already-warm
    function re-measures compile time rather than reading a cache hit —
    that is the point: the bill is reproducible.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, static_argnums=static_argnums)
    label = entry or getattr(fn, "__name__", None) or type(fn).__name__

    t0 = time.perf_counter()
    try:
        traced = jitted.trace(*args, **kwargs)
        t1 = time.perf_counter()
        lowered = traced.lower()
    except AttributeError:  # older jax: no .trace(); .lower() traces too
        t1 = t0
        lowered = jitted.lower(*args, **kwargs)
    t2 = time.perf_counter()
    compiled = lowered.compile()
    t3 = time.perf_counter()

    cost = _normalize_cost(_try(compiled.cost_analysis))
    memory = _normalize_memory(_try(compiled.memory_analysis))

    report: Dict[str, Any] = {
        "entry": label,
        "trace_s": round(t1 - t0, 6),
        "lower_s": round(t2 - t1, 6),
        "compile_s": round(t3 - t2, 6),
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes_accessed"),
        "cost_analysis": cost,
        "memory_analysis": memory,
    }

    rec = recorder if recorder is not None else _DEFAULT_RECORDER
    if rec.enabled:
        rec.record_compile(
            label,
            trace_s=report["trace_s"],
            lower_s=report["lower_s"],
            compile_s=report["compile_s"],
            cost=cost,
            memory=memory,
        )
    return report


def _try(method: Callable) -> Any:
    """cost_analysis/memory_analysis raise on backends that don't implement
    them (and on some executables); absence of an estimate is data, not an
    error."""
    try:
        return method()
    except Exception:
        return None


def metric_compile_cost(
    metric: Any,
    args: Tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    phase: str = "update",
    recorder: Optional[Any] = None,
) -> Optional[Dict[str, Any]]:
    """Bill one metric (re)compile: lower the metric's pure
    ``update_state(state, *batch)`` on the actual offending arguments and
    record the ``compile`` event under ``"<MetricClass>.<phase>"``.

    This is the ``profile_compiles`` hook ``core/metric.py`` fires when the
    signature tracker reports a NEW signature. Returns the
    :func:`compiled_cost` report, or ``None`` when the metric declines
    (untraceable update, list/host states) or profiling itself fails —
    telemetry must never take down the hot path it observes.
    """
    if getattr(metric, "__jit_unsafe__", False):
        return None
    try:
        state = {name: getattr(metric, name) for name in metric._defaults}
        if any(isinstance(v, list) for v in state.values()):
            # list ("cat") states grow the pytree per update; their update
            # is host-driven and has no single compiled executable to bill
            return None
        entry = f"{type(metric).__name__}.{phase}"

        def _step(state: Dict[str, Any], *batch: Any, **batch_kw: Any) -> Dict[str, Any]:
            return metric.update_state(state, *batch, **batch_kw)

        return compiled_cost(_step, state, *args, entry=entry, recorder=recorder, **(kwargs or {}))
    except Exception:
        return None
