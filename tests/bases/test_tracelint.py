"""tracelint static-analyzer tests: per-rule positive/negative fixtures,
suppression pragmas, baseline round-trip, JSON reporter schema, and the
tier-1 package gate (the whole of ``metrics_tpu/`` must be clean against
the checked-in baseline).
"""
import json
import pathlib
import subprocess
import sys
from collections import Counter

import pytest

from metrics_tpu.analysis import (
    RULE_REGISTRY,
    analyze_paths,
    analyze_source,
    default_package_root,
    get_rules,
    load_baseline,
    render_json,
    save_baseline,
    split_by_baseline,
    suppressed_rules,
)
from metrics_tpu.analysis.cli import DEFAULT_BASELINE, main as cli_main

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

_METRIC_PREAMBLE = """
import numpy as np
import jax
import jax.numpy as jnp
from metrics_tpu.core.metric import Metric
"""


def _check(source, relpath="classification/fixture.py", rules=None):
    kept, suppressed = analyze_source(
        _METRIC_PREAMBLE + source, relpath, rules=get_rules(rules) if rules else None
    )
    return kept, suppressed


def _rules_of(violations):
    return {v.rule for v in violations}


# ---------------------------------------------------------------------------
# TL-TRACE
# ---------------------------------------------------------------------------

class TestTraceRule:
    def test_float_on_traced_update_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(jnp.sum(preds))
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_item_in_compute_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total.item()
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_np_asarray_on_param_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        host = np.asarray(preds)
        self.total = self.total + host.sum()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_if_on_traced_value_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        if jnp.max(preds) > 1:
            preds = preds / jnp.max(preds)
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_shape_checks_and_clean_update_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds, target):
        if preds.ndim == 2 and preds.shape[0] > 0:
            preds = preds.reshape(-1)
        self.total = self.total + jnp.sum(preds * target)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_is_concrete_guard_exempts(self):
        """The eager-only guard pattern (utils/checks.py) must not flag."""
        kept, _ = _check(
            """
from metrics_tpu.utils.checks import _is_concrete
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        if _is_concrete(preds):
            if bool(jnp.any(jnp.isnan(preds))):
                raise RuntimeError("nan")
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        return self.total
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_jit_unsafe_class_exempt(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = True  # host-side reference implementation
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + float(np.asarray(preds).sum())
    def _compute(self):
        return float(self.total)
"""
        )
        assert "TL-TRACE" not in _rules_of(kept)

    def test_functional_kernel_item_flags(self):
        kept, _ = _check(
            """
def kernel_update(state, preds):
    return state + jnp.sum(preds).item()
""",
            relpath="functional/classification/fixture.py",
        )
        assert "TL-TRACE" in _rules_of(kept)

    def test_functional_kernel_clean_passes(self):
        kept, _ = _check(
            """
def kernel_update(state, preds):
    return state + jnp.sum(preds)
""",
            relpath="functional/classification/fixture.py",
        )
        assert "TL-TRACE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-RECOMPILE
# ---------------------------------------------------------------------------

class TestRecompileRule:
    def test_shape_arg_in_static_position_flags(self):
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
def run(x):
    return fn(x, x.shape[0])
"""
        )
        assert "TL-RECOMPILE" in _rules_of(kept)

    def test_len_and_int_args_flag(self):
        kept, _ = _check(
            """
from functools import partial
@partial(jax.jit, static_argnums=(1,))
def fn(x, n):
    return x * n
def run(x, items):
    return fn(x, len(items)) + fn(x, int(x.sum()))
"""
        )
        assert sum(v.rule == "TL-RECOMPILE" for v in kept) == 2

    def test_static_argnames_maps_to_positional_call(self):
        """The stoi idiom: static_argnames args passed positionally."""
        kept, _ = _check(
            """
from functools import partial
@partial(jax.jit, static_argnames=("bucket",))
def fn(x, bucket):
    return x[:bucket]
def run(x):
    return fn(x, int(x.sum())) + fn(x, bucket=len(x))
"""
        )
        assert sum(v.rule == "TL-RECOMPILE" for v in kept) == 2

    def test_dynamic_scalar_arg_passes(self):
        """Without static_argnums, a Python scalar traces as a weak 0-d
        array and shares ONE compilation — no hazard, no flag."""
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n)
def run(x, items):
    return fn(x, x.shape[0]) + fn(x, len(items))
"""
        )
        assert "TL-RECOMPILE" not in _rules_of(kept)

    def test_coerced_scalar_passes(self):
        """jnp.asarray-wrapped values in dynamic positions never flag."""
        kept, _ = _check(
            """
fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
def run(x):
    return fn(x, jnp.asarray(x.shape[0]))
"""
        )
        assert "TL-RECOMPILE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-STATE
# ---------------------------------------------------------------------------

class TestStateRule:
    def test_unknown_reducer_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="avg")
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_known_reducers_and_callable_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("a", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("b", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("c", default=jnp.asarray(0.0), dist_reduce_fx=jnp.sum)
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_state_write_in_compute_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def _compute(self):
        self.total = self.total * 2
        return self.total
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_state_write_in_update_and_reset_pass(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
    def _update(self, preds):
        self.total = self.total + jnp.sum(preds)
    def reset(self):
        self.total = jnp.asarray(0.0)
        super().reset()
    def _compute(self):
        return self.total
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_list_state_without_declaration_flags(self):
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
"""
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_list_state_with_declaration_passes(self):
        kept, _ = _check(
            """
class M(Metric):
    __jit_unsafe__ = False  # append-only update traces
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
"""
        )
        assert "TL-STATE" not in _rules_of(kept)

    def test_wrapper_without_declaration_flags(self):
        kept, _ = _check(
            """
class W(Metric):
    def __init__(self, base):
        super().__init__()
        self.metric = base
""",
            relpath="wrappers/fixture.py",
        )
        assert "TL-STATE" in _rules_of(kept)

    def test_instance_level_declaration_counts(self):
        """The _capacity.py idiom: self.__dict__["__jit_unsafe__"] = ..."""
        kept, _ = _check(
            """
class M(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.__dict__["__jit_unsafe__"] = False
"""
        )
        assert "TL-STATE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-COLLECTIVE
# ---------------------------------------------------------------------------

class TestCollectiveRule:
    def test_raw_psum_outside_transport_flags(self):
        kept, _ = _check(
            """
def my_sync(x):
    return jax.lax.psum(x, "rank")
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_from_import_collective_flags(self):
        kept, _ = _check(
            """
from jax.lax import all_gather
def my_sync(x):
    return all_gather(x, "rank")
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_process_allgather_flags(self):
        kept, _ = _check(
            """
from jax.experimental import multihost_utils
def my_sync(x):
    return multihost_utils.process_allgather(x)
"""
        )
        assert "TL-COLLECTIVE" in _rules_of(kept)

    def test_transport_layer_allowed(self):
        kept, _ = _check(
            """
def sync_impl(x):
    return jax.lax.psum(x, "rank")
""",
            relpath="parallel/fixture.py",
        )
        assert "TL-COLLECTIVE" not in _rules_of(kept)

    def test_aggregate_module_allowed(self):
        kept, _ = _check(
            """
from jax.experimental import multihost_utils
def agg(x):
    return multihost_utils.process_allgather(x)
""",
            relpath="observability/aggregate.py",
        )
        assert "TL-COLLECTIVE" not in _rules_of(kept)


# ---------------------------------------------------------------------------
# TL-PRINT
# ---------------------------------------------------------------------------

class TestPrintRule:
    def test_print_flags(self):
        kept, _ = _check("""
def f():
    print("hello")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_warnings_warn_flags(self):
        kept, _ = _check("""
import warnings
def f():
    warnings.warn("x")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_from_import_warn_flags(self):
        kept, _ = _check("""
from warnings import warn
def f():
    warn("x")
""")
        assert "TL-PRINT" in _rules_of(kept)

    def test_rank_zero_helpers_pass(self):
        kept, _ = _check("""
from metrics_tpu.utils.prints import rank_zero_warn
def f():
    rank_zero_warn("x")
""")
        assert "TL-PRINT" not in _rules_of(kept)

    def test_prints_module_allowed(self):
        kept, _ = _check("""
def rank_zero_print(*args):
    print(*args)
""", relpath="utils/prints.py")
        assert "TL-PRINT" not in _rules_of(kept)

    def test_doctest_print_never_flags(self):
        """AST-based: print inside a docstring example is not a call site."""
        kept, _ = _check('''
def f():
    """Example:
        >>> print("hello")
    """
    return 1
''')
        assert "TL-PRINT" not in _rules_of(kept)

    def test_check_no_print_alias_still_works(self):
        """The legacy script invocation is an alias over TL-PRINT."""
        result = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "check_no_print.py")],
            capture_output=True,
            text=True,
        )
        assert result.returncode == 0, result.stderr


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------

class TestSuppression:
    def test_pragma_parses(self):
        assert suppressed_rules("x = 1  # tracelint: disable=TL-TRACE") == {"TL-TRACE"}
        assert suppressed_rules("x = 1  # tracelint: disable=tl-trace, TL-STATE") == {
            "TL-TRACE",
            "TL-STATE",
        }
        assert suppressed_rules("x = 1  # tracelint: disable=all") == {"ALL"}
        assert suppressed_rules("x = 1  # a normal comment") == set()

    def test_pragma_suppresses_on_violation_line(self):
        kept, suppressed = _check(
            """
def f():
    print("hello")  # tracelint: disable=TL-PRINT — CLI surface
"""
        )
        assert "TL-PRINT" not in _rules_of(kept)
        assert "TL-PRINT" in _rules_of(suppressed)

    def test_pragma_for_other_rule_does_not_suppress(self):
        kept, suppressed = _check(
            """
def f():
    print("hello")  # tracelint: disable=TL-TRACE
"""
        )
        assert "TL-PRINT" in _rules_of(kept)

    def test_disable_all_suppresses_everything(self):
        kept, suppressed = _check(
            """
def f(x):
    print(jax.lax.psum(x, "rank"))  # tracelint: disable=all
"""
        )
        assert kept == []
        assert {"TL-PRINT", "TL-COLLECTIVE"} <= _rules_of(suppressed)


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

class TestBaseline:
    def _violations(self):
        kept, _ = _check(
            """
def f():
    print("a")
    print("a")
    print("b")
"""
        )
        return [v for v in kept if v.rule == "TL-PRINT"]

    def test_round_trip_is_clean(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        new, grandfathered, stale = split_by_baseline(violations, loaded)
        assert new == []
        assert len(grandfathered) == len(violations)
        assert not stale

    def test_duplicate_lines_tracked_by_count(self, tmp_path):
        violations = self._violations()
        assert len(violations) == 3  # two identical `print("a")` lines + one "b"
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        assert sum(loaded.values()) == 3
        # dropping one duplicate from the baseline surfaces exactly one NEW
        short = Counter(loaded)
        key = next(k for k in short if 'print("a")' in k[2])
        short[key] -= 1
        new, grandfathered, _ = split_by_baseline(violations, short)
        assert len(new) == 1

    def test_new_violation_not_masked(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations[:1])
        loaded = load_baseline(baseline_file)
        new, _, _ = split_by_baseline(violations, loaded)
        assert len(new) == len(violations) - 1

    def test_fixed_violation_reported_stale(self, tmp_path):
        violations = self._violations()
        baseline_file = tmp_path / "baseline.json"
        save_baseline(baseline_file, violations)
        loaded = load_baseline(baseline_file)
        _, _, stale = split_by_baseline(violations[:1], loaded)
        assert sum(stale.values()) == 2

    def test_missing_baseline_is_empty(self, tmp_path):
        assert load_baseline(tmp_path / "nope.json") == Counter()

    def test_version_mismatch_raises(self, tmp_path):
        bad = tmp_path / "baseline.json"
        bad.write_text(json.dumps({"version": 99, "entries": []}))
        with pytest.raises(ValueError, match="version"):
            load_baseline(bad)


# ---------------------------------------------------------------------------
# JSON reporter schema
# ---------------------------------------------------------------------------

class TestJsonReporter:
    def test_schema(self):
        kept, suppressed = _check(
            """
def f():
    print("a")
"""
        )
        payload = json.loads(
            render_json(kept, [], suppressed_count=len(suppressed), n_files=1, rules=["TL-PRINT"])
        )
        assert payload["version"] == 1
        assert payload["tool"] == "tracelint"
        assert isinstance(payload["violations"], list) and payload["violations"]
        entry = payload["violations"][0]
        for field in ("rule", "path", "line", "col", "message", "snippet", "baselined"):
            assert field in entry
        assert entry["baselined"] is False
        summary = payload["summary"]
        for field in ("files", "new", "baselined", "suppressed", "rules"):
            assert field in summary
        assert summary["new"] == len(kept)

    def test_cli_json_mode(self, tmp_path, capsys):
        src = tmp_path / "mod.py"
        src.write_text("print('x')\n")
        rc = cli_main([str(src), "--json", "--no-baseline"])
        out = capsys.readouterr().out
        payload = json.loads(out)
        assert rc == 1
        assert payload["summary"]["new"] == 1


# ---------------------------------------------------------------------------
# CLI baseline scoping: partial-path runs must not clobber or mis-report
# entries belonging to files outside the analyzed set
# ---------------------------------------------------------------------------

class TestCliBaselineScoping:
    def _two_files(self, tmp_path):
        dirty_a = tmp_path / "a.py"
        dirty_a.write_text("print('a')\n")
        dirty_b = tmp_path / "b.py"
        dirty_b.write_text("print('b')\n")
        return dirty_a, dirty_b

    def test_partial_baseline_update_carries_other_files(self, tmp_path, capsys):
        dirty_a, dirty_b = self._two_files(tmp_path)
        baseline = tmp_path / "baseline.json"
        # baseline both files, then re-update from only a.py
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--baseline-update"]) == 0
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--baseline-update"]) == 0
        capsys.readouterr()
        loaded = load_baseline(baseline)
        # b.py's grandfathered entry survived the a.py-only rewrite
        assert any(path == "b.py" for (_, path, _) in loaded)
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--check"]) == 0
        capsys.readouterr()

    def test_partial_check_ignores_other_files_staleness(self, tmp_path, capsys):
        dirty_a, dirty_b = self._two_files(tmp_path)
        baseline = tmp_path / "baseline.json"
        assert cli_main([str(dirty_a), str(dirty_b), "--baseline", str(baseline), "--baseline-update"]) == 0
        capsys.readouterr()
        # checking only a.py: b.py's unconsumed entry is NOT stale
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--check"]) == 0
        out = capsys.readouterr().out
        assert "stale" not in out
        # but a genuinely fixed violation in an ANALYZED file still is
        dirty_a.write_text("x = 1\n")
        assert cli_main([str(dirty_a), "--baseline", str(baseline), "--check"]) == 1
        assert "stale" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# package gate (tier-1): the whole library must be clean vs the baseline
# ---------------------------------------------------------------------------

class TestPackageGate:
    def test_package_has_no_new_violations(self):
        result = analyze_paths([default_package_root()])
        baseline = load_baseline(REPO_ROOT / DEFAULT_BASELINE)
        new, grandfathered, _ = split_by_baseline(result.violations, baseline)
        assert not result.parse_errors
        details = "\n".join(v.render() for v in new)
        assert new == [], f"new tracelint violations in metrics_tpu/:\n{details}"

    def test_baseline_is_small(self):
        """Acceptance gate: at most 15 grandfathered entries, every one
        carrying the auditable (rule, path, snippet) key."""
        baseline = load_baseline(REPO_ROOT / DEFAULT_BASELINE)
        assert sum(baseline.values()) <= 15

    def test_every_rule_registered(self):
        assert set(RULE_REGISTRY) == {
            "TL-TRACE",
            "TL-RECOMPILE",
            "TL-STATE",
            "TL-COLLECTIVE",
            "TL-PRINT",
        }

    def test_cli_script_exits_zero_on_package(self):
        result = subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts" / "tracelint.py"), "--check"],
            capture_output=True,
            text=True,
        )
        assert result.returncode == 0, result.stdout + result.stderr
