"""Root pytest configuration — applies to doctest runs over ``metrics_tpu/``
(``pytest --doctest-modules metrics_tpu``), which don't see tests/conftest.py.

Doctest expected values are generated on CPU; the axon TPU backend produces
floats differing in the last ulp, so doctests must run on the same forced-CPU
virtual-device config the test suite uses (single source:
tests/helpers/force_cpu.py).
"""
from tests.helpers.force_cpu import setup_forced_cpu

setup_forced_cpu()
