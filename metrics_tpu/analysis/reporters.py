"""tracelint reporters: human text, machine JSON, GitHub annotations.

The JSON schema is stable (version-tagged) so CI annotators and editors can
consume it:

```json
{
  "version": 2,
  "tool": "tracelint",
  "violations": [
    {"rule": "TL-TRACE", "path": "a.py", "file": "metrics_tpu/a.py",
     "line": 3, "col": 4, "message": "...", "snippet": "...",
     "baselined": false}
  ],
  "summary": {"files": 10, "new": 1, "baselined": 0, "suppressed": 0,
              "stale_baseline_entries": 0,
              "rules": ["TL-COLLECTIVE", "..."],
              "by_rule": {"TL-TRACE": 1}}
}
```

Schema history:

- **v2** — every violation gains ``file``, the REPO-relative path
  (``metrics_tpu/<path>``) matching what ``--format=github`` annotates and
  what CI diff views key on; ``path`` stays the package-relative form the
  baseline and pragma machinery use. No fields were removed, so v1
  consumers that ignore unknown keys keep working; consumers that pin
  ``version == 1`` must accept 2.
- **v1** — initial schema.

``by_rule`` counts NEW violations per rule id (omitting zero-count rules),
so CI annotators can tell WHICH invariant regressed without walking the
violation list.

``render_github`` emits GitHub Actions workflow commands (``::error
file=...,line=...,col=...``) so lint failures land inline on the PR diff;
baselined violations surface as ``::warning`` (visible but non-blocking,
matching their exit-status semantics).
"""
from __future__ import annotations

import json
from collections import Counter
from typing import List, Sequence

from .engine import PACKAGE_NAME, Violation

JSON_SCHEMA_VERSION = 2


def _repo_relative(path: str) -> str:
    """Violation paths are package-relative; CI annotations and the v2
    ``file`` field need the repo-relative form."""
    return f"{PACKAGE_NAME}/{path}"


def render_text(
    new: Sequence[Violation],
    baselined: Sequence[Violation] = (),
    suppressed_count: int = 0,
    n_files: int = 0,
    stale_count: int = 0,
) -> str:
    """Human report: new violations with fix hints, then a summary line."""
    out: List[str] = []
    if new:
        out.append("tracelint: NEW violations (fix, suppress with a justified")
        out.append("`# tracelint: disable=RULE-ID` pragma, or re-baseline):")
        for v in new:
            out.append(f"  {v.render()}")
            if v.snippet:
                out.append(f"      {v.snippet}")
    summary = (
        f"tracelint: {n_files} files, {len(new)} new, {len(baselined)} baselined,"
        f" {suppressed_count} suppressed"
    )
    if new:
        by_rule = Counter(v.rule for v in new)
        summary += " (" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items())) + ")"
    if stale_count:
        summary += f", {stale_count} stale baseline entr{'y' if stale_count == 1 else 'ies'} (run --baseline-update)"
    out.append(summary)
    return "\n".join(out) + "\n"


def render_json(
    new: Sequence[Violation],
    baselined: Sequence[Violation] = (),
    suppressed_count: int = 0,
    n_files: int = 0,
    rules: Sequence[str] = (),
    stale_count: int = 0,
) -> str:
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "tracelint",
        "violations": [
            {**v.to_dict(), "file": _repo_relative(v.path), "baselined": False}
            for v in new
        ] + [
            {**v.to_dict(), "file": _repo_relative(v.path), "baselined": True}
            for v in baselined
        ],
        "summary": {
            "files": n_files,
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": suppressed_count,
            "stale_baseline_entries": stale_count,
            "rules": sorted(rules),
            "by_rule": dict(sorted(Counter(v.rule for v in new).items())),
        },
    }
    return json.dumps(payload, indent=2) + "\n"


def _gh_escape(value: str, *, property_value: bool = False) -> str:
    """GitHub workflow-command escaping: ``%``/newlines always; ``:`` and
    ``,`` additionally inside property values (file=..., title=...)."""
    out = value.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if property_value:
        out = out.replace(":", "%3A").replace(",", "%2C")
    return out


def render_github(
    new: Sequence[Violation],
    baselined: Sequence[Violation] = (),
) -> str:
    """GitHub Actions annotation report: one ``::error`` workflow command
    per new violation (``::warning`` per baselined one), each anchored to
    the repo-relative file/line/col so it lands inline on the PR diff."""
    out: List[str] = []
    for level, violations in (("error", new), ("warning", baselined)):
        for v in violations:
            props = (
                f"file={_gh_escape(_repo_relative(v.path), property_value=True)},"
                f"line={v.line},col={v.col},"
                f"title={_gh_escape('tracelint ' + v.rule, property_value=True)}"
            )
            out.append(f"::{level} {props}::{_gh_escape(v.message)}")
    return "\n".join(out) + "\n" if out else ""
