"""End-to-end serving-loop observatory test (ISSUE 11/12 acceptance): the
fault-injection demo trips and clears EVERY alarm class — queue,
staleness, drop-rate, recompile, fill, hot-slice, score-drift — while
publishing telemetry + health artifacts the whole run.

Real wall clock (the loop paces itself and alarm clearing IS time
passing), so this is the suite's one deliberately slow-ish test (~15s);
every injected fault is deterministic (bounded drop-policy queue vs an
unpaced producer, a held snapshot lock, ragged shapes, an 85%-hot tenant,
a sketch smaller than the burst) so the assertions do not race the box.
"""
import json
import sys
from pathlib import Path

import pytest

from metrics_tpu.observability import get_recorder

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO_ROOT / "examples"))

ALARM_CLASSES = (
    "queue_saturation",
    "staleness",
    "drop_rate",
    "recompile_storm",
    "sketch_fill",
    "hot_slice_skew",
    "score_drift",
)


def test_fault_injection_trips_and_clears_every_alarm_class(tmp_path):
    import serving_loop

    report = serving_loop.run(
        duration=8.0,
        inject="all",
        out_dir=str(tmp_path),
        qps=60.0,
        batch_size=64,
        queue_depth=8,
        sketch_capacity=8192,
        tenants=64,
        bucket_seconds=0.5,
        window_s=3.0,
        export_interval_s=0.5,
        seed=0,
        verbose=False,
    )
    for cls in ALARM_CLASSES:
        assert cls in report["alarms_fired"], (cls, report["alarms_fired"])
        assert cls in report["alarms_fired_and_cleared"], (
            cls,
            report["alarms_fired_and_cleared"],
            report["transitions"],
        )
    assert report["final_status"] == "ok"
    assert report["async"]["dropped"] > 0  # the burst really shed load
    assert report["async"]["max_queue_depth"] >= 8
    assert report["export_errors"] == 0
    assert 0.0 <= report["final_values"]["auroc"] <= 1.0

    # the observatory's artifacts all materialized
    rows = [json.loads(l) for l in (tmp_path / "health_alarms.jsonl").read_text().splitlines()]
    fired = {r["alarm"] for r in rows if r["event"] == "fired"}
    cleared = {r["alarm"] for r in rows if r["event"] == "cleared"}
    for cls in ALARM_CLASSES:
        assert cls in fired and cls in cleared
    page = (tmp_path / "metrics.prom").read_text()
    assert "metrics_tpu_health_status" in page
    assert "metrics_tpu_window_quantile" in page
    assert "metrics_tpu_async_batches_total" in page
    assert 'metrics_tpu_drift_score{metric="scores",stat="psi"' in page
    assert "health:" in (tmp_path / "health.txt").read_text()
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert any(e.get("ph") == "M" for e in trace["traceEvents"])
    assert (tmp_path / "telemetry.jsonl").stat().st_size > 0
    assert json.loads((tmp_path / "report.json").read_text())["inject"] == "all"

    # the demo leaves the default recorder exactly as it found it
    rec = get_recorder()
    assert not rec.enabled and rec.timeseries is None and rec.events() == []
