"""Telemetry exporters: JSONL event log, Prometheus text exposition, and a
human summary table.

All three are rank-zero-gated (multi-host jobs emit one copy) and read a
consistent snapshot of the recorder, so they can run concurrently with
metric updates.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from metrics_tpu.utils.prints import _process_index


def _resolve(recorder: Optional[Any]) -> Any:
    if recorder is None:
        from metrics_tpu.observability.recorder import _DEFAULT_RECORDER

        return _DEFAULT_RECORDER
    return recorder


def export_jsonl(path: str, recorder: Optional[Any] = None, append: bool = False) -> Optional[str]:
    """Write every recorded event as one JSON object per line.

    Returns the path written, or ``None`` on non-zero ranks (rank-zero
    gated). Events are plain dicts of JSON scalars/lists, so the artifact
    round-trips through ``json.loads`` line by line.
    """
    if _process_index() != 0:
        return None
    rec = _resolve(recorder)
    mode = "a" if append else "w"
    with open(path, mode) as fh:
        for event in rec.events():
            fh.write(json.dumps(event) + "\n")
    return path


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_prometheus(recorder: Optional[Any] = None) -> str:
    """Prometheus text-format rendering of the aggregate counters/gauges.

    Meant for a scrape endpoint or a textfile-collector drop: call counts
    and cumulative wall time per (metric, phase), sync/gather byte totals,
    distinct-signature gauges (the recompile detector's raw data), and
    state-footprint high-water marks. Returns ``""`` on non-zero ranks.
    """
    if _process_index() != 0:
        return ""
    rec = _resolve(recorder)
    counts = rec.call_counts()
    times = rec.call_times()
    sync = rec.sync_totals()
    sigs = rec.signature_counts()
    hwm = rec.footprint_high_water_marks()

    lines = []
    lines.append("# HELP metrics_tpu_calls_total Metric lifecycle calls by metric and phase.")
    lines.append("# TYPE metrics_tpu_calls_total counter")
    for (metric, phase), n in sorted(counts.items()):
        lines.append(
            f'metrics_tpu_calls_total{{metric="{_escape_label(metric)}",phase="{_escape_label(phase)}"}} {n}'
        )
    lines.append("# HELP metrics_tpu_call_seconds_total Cumulative wall time by metric and phase.")
    lines.append("# TYPE metrics_tpu_call_seconds_total counter")
    for (metric, phase), t in sorted(times.items()):
        lines.append(
            f'metrics_tpu_call_seconds_total{{metric="{_escape_label(metric)}",phase="{_escape_label(phase)}"}} {t:.6f}'
        )
    lines.append("# HELP metrics_tpu_sync_events_total Cross-device/process state synchronizations.")
    lines.append("# TYPE metrics_tpu_sync_events_total counter")
    lines.append(f"metrics_tpu_sync_events_total {sync['sync_events']}")
    lines.append("# HELP metrics_tpu_gather_bytes_total Bytes of synced state received per participant.")
    lines.append("# TYPE metrics_tpu_gather_bytes_total counter")
    lines.append(f"metrics_tpu_gather_bytes_total {sync['gather_bytes']}")
    lines.append("# HELP metrics_tpu_pad_waste_bytes_total Pad-to-max padding bytes moved by uneven gathers.")
    lines.append("# TYPE metrics_tpu_pad_waste_bytes_total counter")
    lines.append(f"metrics_tpu_pad_waste_bytes_total {sync['pad_waste_bytes']}")
    lines.append("# HELP metrics_tpu_distinct_signatures Distinct (shape, dtype) call signatures per entry point.")
    lines.append("# TYPE metrics_tpu_distinct_signatures gauge")
    for entry, n in sorted(sigs.items()):
        lines.append(f'metrics_tpu_distinct_signatures{{entry="{_escape_label(entry)}"}} {n}')
    lines.append("# HELP metrics_tpu_state_bytes_hwm State-footprint high-water mark per metric.")
    lines.append("# TYPE metrics_tpu_state_bytes_hwm gauge")
    for metric, nbytes in sorted(hwm.items()):
        lines.append(f'metrics_tpu_state_bytes_hwm{{metric="{_escape_label(metric)}"}} {nbytes}')
    lines.append("# HELP metrics_tpu_dropped_events_total Events discarded past the buffer cap.")
    lines.append("# TYPE metrics_tpu_dropped_events_total counter")
    lines.append(f"metrics_tpu_dropped_events_total {rec.dropped_events()}")
    return "\n".join(lines) + "\n"


def summary(recorder: Optional[Any] = None) -> str:
    """Human-readable summary table of where metric time went.

    Returns ``""`` on non-zero ranks.
    """
    if _process_index() != 0:
        return ""
    rec = _resolve(recorder)
    counts = rec.call_counts()
    times = rec.call_times()
    sync = rec.sync_totals()
    sigs = rec.signature_counts()
    hwm = rec.footprint_high_water_marks()

    rows = []
    for (metric, phase), n in sorted(counts.items(), key=lambda kv: -times.get(kv[0], 0.0)):
        total_ms = times.get((metric, phase), 0.0) * 1e3
        rows.append((metric, phase, n, total_ms, total_ms / max(n, 1)))

    width = max([len(r[0]) for r in rows], default=6)
    lines = [
        f"telemetry summary (recorder `{rec.name}`)",
        f"{'metric':<{width}}  {'phase':<8} {'calls':>7} {'total_ms':>10} {'mean_ms':>9}",
    ]
    for metric, phase, n, total_ms, mean_ms in rows:
        lines.append(f"{metric:<{width}}  {phase:<8} {n:>7} {total_ms:>10.3f} {mean_ms:>9.4f}")
    if not rows:
        lines.append("(no lifecycle calls recorded)")
    lines.append(
        f"sync: {sync['sync_events']} events, {sync['gather_bytes']} gather bytes,"
        f" {sync['pad_waste_bytes']} pad-waste bytes"
    )
    dropped = rec.dropped_events()
    if dropped:
        lines.append(
            f"WARNING: {dropped} events dropped past the buffer cap"
            " (aggregate counters above still include them)"
        )
    if sigs:
        lines.append("distinct call signatures per entry point:")
        for entry, n in sorted(sigs.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {entry}: {n}")
    if hwm:
        lines.append("state-footprint high-water marks:")
        for metric, nbytes in sorted(hwm.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {metric}: {nbytes} bytes")
    return "\n".join(lines)
