"""Import helper for using the reference implementation as a test oracle.

The reference tree at /root/reference is pure Python over torch (CPU build
available in this environment), so domains whose usual PyPI oracle is absent
(e.g. jiwer for the WER family) can be checked against the reference itself
— the same pattern tests/detection/test_map.py uses for mAP.
"""
import sys
import types

import pytest


def load_reference_module(dotted: str):
    """Import ``torchmetrics...`` submodule from /root/reference, or skip."""
    pytest.importorskip("torch")
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    if "pkg_resources" not in sys.modules:
        # this env's setuptools no longer ships pkg_resources; the reference
        # only needs these two names for optional-dependency probing
        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub
    try:
        __import__(dotted)
    except Exception as err:  # pragma: no cover
        pytest.skip(f"reference torchmetrics unavailable: {err}")
    return sys.modules[dotted]
