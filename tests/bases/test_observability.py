"""Telemetry subsystem tests: recorder on/off invariants, recompile-signature
warnings, mesh sync byte accounting, state footprints, and exporter round
trips (ISSUE 1 tentpole)."""
import json
import os
import subprocess
import sys
import warnings
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection, Precision, Recall
from metrics_tpu.aggregation import MeanMetric, SumMetric
from metrics_tpu.classification import ROC
from metrics_tpu.observability import (
    export_jsonl,
    get_recorder,
    render_prometheus,
    summary,
    telemetry_enabled,
)
from metrics_tpu.wrappers import MetricTracker

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


@pytest.fixture
def recorder():
    """The default recorder, enabled for one test and ALWAYS disabled+reset
    after — the session-level conftest asserts nothing leaks."""
    rec = get_recorder()
    rec.reset()
    rec.enable(recompile_threshold=rec.DEFAULT_RECOMPILE_THRESHOLD, footprint_warn_bytes=None)
    try:
        yield rec
    finally:
        rec.disable()
        rec.footprint_warn_bytes = None
        rec.recompile_threshold = rec.DEFAULT_RECOMPILE_THRESHOLD
        rec.reset()


def test_disabled_by_default_and_zero_event_invariant():
    """The on/off overhead invariant's observable half: with the recorder
    disabled (the default), NO events, counts, or signatures accumulate no
    matter how much metric traffic runs — the hot path allocates nothing."""
    rec = get_recorder()
    assert not rec.enabled
    assert not telemetry_enabled()
    m = MeanMetric()
    for i in range(1, 20):
        m.update(jnp.ones((i,)))  # shape-varying: would trip every subsystem
    float(m.compute())
    m2 = SumMetric()
    m2(jnp.asarray(2.0))  # forward path
    assert rec.events() == []
    assert rec.call_counts() == {}
    assert rec.signature_counts() == {}
    assert rec.sync_totals() == {"sync_events": 0, "gather_bytes": 0, "pad_waste_bytes": 0}


def test_enabled_records_typed_lifecycle_events(recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    float(m.compute())
    m(jnp.ones((4,)))  # forward: own event + its double update's events
    types = [e["type"] for e in recorder.events()]
    assert "update" in types and "compute" in types and "forward" in types
    update_events = [e for e in recorder.events() if e["type"] == "update"]
    assert update_events[0]["metric"] == "MeanMetric"
    assert update_events[0]["dur_ms"] >= 0
    assert update_events[0]["signature"] == [[[4], "float32"]]
    counts = recorder.call_counts()
    assert counts[("MeanMetric", "update")] == 3  # 1 direct + forward's double update
    assert counts[("MeanMetric", "forward")] == 1


def test_recompile_signature_warning_fires_exactly_once(recorder):
    """A shape-varying update loop (the unpadded-batch recompile bug) must
    warn exactly once per entry point when crossing the threshold."""
    recorder.recompile_threshold = 3
    m = MeanMetric()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for n in range(1, 10):  # 9 distinct (shape, dtype) signatures
            m.update(jnp.ones((n,)))
    recompile_warnings = [w for w in caught if "distinct (shape, dtype)" in str(w.message)]
    assert len(recompile_warnings) == 1
    assert "MeanMetric.update" in str(recompile_warnings[0].message)
    assert recorder.signature_counts()["MeanMetric.update"] == 9
    events = [e for e in recorder.events() if e["type"] == "recompile_warning"]
    assert len(events) == 1
    assert events[0]["distinct_signatures"] == 4  # fired when crossing 3
    # a stable-shape loop on another metric must NOT warn
    m2 = SumMetric()
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        for _ in range(20):
            m2.update(jnp.ones((4,)))
    assert not [w for w in caught2 if "distinct (shape, dtype)" in str(w.message)]


def test_sync_byte_accounting_on_mesh(recorder):
    """sync_in_mesh on the 8-virtual-device mesh records exact gather bytes:
    cat states count world_size shards, reduced states one payload."""
    from metrics_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.distributed import sync_in_mesh

    n_dev = 8
    per_dev = 16
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    xs = jnp.arange(n_dev * per_dev, dtype=jnp.float32)

    def body(x):
        synced = sync_in_mesh({"v": x, "s": jnp.sum(x)}, {"v": "cat", "s": "sum"}, "rank")
        return jnp.sum(synced["v"]) + synced["s"]

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("rank"),), out_specs=P()))
    expected = float(np.sum(np.arange(n_dev * per_dev)) * 2)
    assert float(fn(xs)) == pytest.approx(expected)
    float(fn(xs))  # second execution: cached compile, no second trace event

    sync_events = [e for e in recorder.events() if e["type"] == "sync"]
    assert len(sync_events) == 1  # one per TRACE, not per step
    ev = sync_events[0]
    assert ev["source"] == "sync_in_mesh"
    assert ev["world_size"] == n_dev
    assert ev["axis"] == "rank"
    # v: 16 f32 per device gathered from 8 ranks; s: one f32 all-reduced
    assert ev["state_bytes"] == {"v": per_dev * 4 * n_dev, "s": 4}
    assert ev["gather_bytes"] == per_dev * 4 * n_dev + 4
    totals = recorder.sync_totals()
    assert totals["gather_bytes"] == ev["gather_bytes"]
    assert totals["sync_events"] == 1


def test_state_footprint_growth_and_high_water_warning(recorder):
    """Cat-state curve metrics (the `exact=True` opt-out since the sketch
    conversion) grow per update; state_footprint sees it and the opt-in
    high-water mark warns once. The sketch DEFAULT is the fix: its bytes
    stay constant across updates."""
    sketched = ROC()
    sk0 = sketched.total_state_bytes()
    sketched.update(jnp.asarray([0.2, 0.8, 0.5]), jnp.asarray([0, 1, 1]))
    assert sketched.total_state_bytes() == sk0  # O(capacity), not O(N)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the exact-mode large-buffer warning
        roc = ROC(exact=True)
    fp0 = sum(roc.state_footprint().values())
    roc.update(jnp.asarray([0.2, 0.8, 0.5]), jnp.asarray([0, 1, 1]))
    fp1 = sum(roc.state_footprint().values())
    roc.update(jnp.asarray([0.3, 0.9]), jnp.asarray([1, 0]))
    fp2 = sum(roc.state_footprint().values())
    assert fp0 < fp1 < fp2
    assert roc.total_state_bytes() == fp2
    per_state = roc.state_footprint()
    assert per_state["preds"] == per_state["target"] > 0

    recorder.footprint_warn_bytes = 1  # opt in: any growth crosses it
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        roc.update(jnp.asarray([0.1]), jnp.asarray([1]))
        roc.update(jnp.asarray([0.7]), jnp.asarray([0]))
    hwm_warnings = [w for w in caught if "state footprint" in str(w.message)]
    assert len(hwm_warnings) == 1  # once per metric, not per update
    assert recorder.footprint_high_water_marks()["ROC"] >= fp2


def test_collection_footprint_and_group_attribution(recorder):
    """Compute-group members share state: the dedup total counts leaders
    once, and leader update events carry the group attribution."""
    col = MetricCollection(
        [Precision(num_classes=3, average="macro"), Recall(num_classes=3, average="macro")]
    )
    preds = jnp.asarray([2, 1, 2, 0])
    target = jnp.asarray([0, 2, 0, 2])
    col.update(preds, target)  # discovery pass: both metrics update
    assert len(col.compute_groups) == 1  # Precision/Recall share tp/fp/tn/fn
    naive = sum(sum(fp.values()) for fp in col.state_footprint().values())
    assert col.total_state_bytes() * 2 == naive  # leader counted once

    col.update(preds, target)  # grouped pass: leader only, attributed
    grouped = [e for e in recorder.events() if e.get("compute_group")]
    assert len(grouped) == 1
    assert sorted(grouped[0]["compute_group"]) == ["Precision", "Recall"]


def test_tracker_increment_events_and_footprint(recorder):
    tracker = MetricTracker(SumMetric())
    for epoch in range(3):
        tracker.increment()
        tracker.update(jnp.asarray(float(epoch)))
    incs = [e for e in recorder.events() if e["type"] == "tracker_increment"]
    assert [e["n_steps"] for e in incs] == [1, 2, 3]
    assert tracker.total_state_bytes() == sum(
        sum(fp.values()) for fp in tracker.state_footprint().values()
    )
    assert set(tracker.state_footprint()) == {"step0", "step1", "step2"}


def test_jsonl_round_trip_and_text_exporters(tmp_path, recorder):
    m = MeanMetric()
    m.update(jnp.ones((4,)))
    float(m.compute())
    recorder.record_sync("gather_all_arrays", gather_bytes=1024, world_size=4, pad_waste_bytes=128)

    path = tmp_path / "telemetry.jsonl"
    assert export_jsonl(str(path), recorder) == str(path)
    lines = path.read_text().splitlines()
    events = [json.loads(line) for line in lines]  # every line round-trips
    assert len(events) == len(recorder.events())
    assert {"update", "compute", "sync"} <= {e["type"] for e in events}
    sync = [e for e in events if e["type"] == "sync"][0]
    assert sync["gather_bytes"] == 1024 and sync["pad_waste_bytes"] == 128

    # append mode (the subprocess artifact contract) extends, not truncates
    export_jsonl(str(path), recorder, append=True)
    assert len(path.read_text().splitlines()) == 2 * len(lines)

    prom = render_prometheus(recorder)
    assert 'metrics_tpu_calls_total{metric="MeanMetric",phase="update"} 1' in prom
    assert "metrics_tpu_gather_bytes_total 1024" in prom
    assert "metrics_tpu_pad_waste_bytes_total 128" in prom

    text = summary(recorder)
    assert "MeanMetric" in text and "1024 gather bytes" in text


def test_named_recorders_are_independent(recorder):
    other = get_recorder("side-channel")
    assert other is not recorder
    assert not other.enabled  # enabling the default does not enable others
    assert get_recorder("side-channel") is other


def test_no_raw_print_in_package():
    """CI guard: library code must use the rank-zero print helpers."""
    result = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "check_no_print.py")],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr


# ---------------------------------------------------------------------------
# _atomic_append O(1)-per-call line log (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def test_atomic_append_many_thousand_appends_complete_and_ordered(tmp_path):
    """The O(n^2) regression pin: each append is ONE O_APPEND write of the
    new bytes — NOT a read-whole-file-and-rewrite — so a multi-thousand-
    line log stays complete, in order, and linear-time. (The quadratic
    implementation re-read ~25 MB cumulatively for this workload; the
    content assertion is what pins correctness, the wall bound below is a
    generous canary for the complexity class.)"""
    import time as _time

    from metrics_tpu.observability.exporters import _atomic_append

    path = tmp_path / "alarms.jsonl"
    n = 5000
    t0 = _time.perf_counter()
    for i in range(n):
        _atomic_append(str(path), json.dumps({"i": i}) + "\n")
    elapsed = _time.perf_counter() - t0
    lines = path.read_text().splitlines()
    assert len(lines) == n
    assert [json.loads(line)["i"] for line in lines] == list(range(n))
    # ~5k one-line O_APPEND writes take well under a second on any disk;
    # the quadratic path took tens of seconds — 30s is a pure complexity
    # canary, never a flake
    assert elapsed < 30.0


def test_atomic_append_rotation_caps_file_size(tmp_path):
    from metrics_tpu.observability.exporters import _atomic_append

    path = tmp_path / "log.jsonl"
    line = "x" * 99 + "\n"
    for _ in range(10):
        _atomic_append(str(path), line, max_bytes=450)
    # rotation kicked in: the live file stays under cap + one line, the
    # previous generation survives at .1
    assert os.path.getsize(path) <= 450 + len(line)
    assert (tmp_path / "log.jsonl.1").exists()
    total = len(path.read_text()) + sum(
        len(p.read_text()) for p in [tmp_path / "log.jsonl.1"]
    )
    # at most one generation is discarded (double rotation overwrote .1)
    assert total % len(line) == 0 and total >= 2 * len(line)


def test_atomic_append_multi_line_payload_lands_contiguously(tmp_path):
    from metrics_tpu.observability.exporters import _atomic_append

    path = tmp_path / "log.jsonl"
    _atomic_append(str(path), "a\nb\n")
    _atomic_append(str(path), "c\n")
    assert path.read_text() == "a\nb\nc\n"
