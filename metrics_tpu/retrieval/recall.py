"""RetrievalRecall.

Behavior parity with /root/reference/torchmetrics/retrieval/recall.py:22-112.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval.recall import retrieval_recall
from metrics_tpu.functional.retrieval.padded import recall_row
from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k

Array = jax.Array


class RetrievalRecall(RetrievalMetric):
    """Mean recall@k over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(recall_row)

    @property
    def _padded_k(self):
        return self.k

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _check_retrieval_k(k)
        self.k = k

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_recall(preds, target, k=self.k)
