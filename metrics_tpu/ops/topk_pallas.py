"""Pallas TPU kernel: fused per-row top-k + payload gather.

The retrieval table's hot path — overflow compaction and cross-rank
merges (``retrieval/table.py``) — selects each query row's top-``k``
documents by score and carries the target (and validity) payloads through
the permutation. XLA lowers ``lax.top_k`` + two ``take_along_axis``
gathers as separate HBM round-trips; this kernel keeps the whole
select-and-gather resident in VMEM:

* **Sort** — a row-parallel bitonic compare-exchange network over the
  padded power-of-two column count (pure reshape + ``where`` stages, the
  same machinery as the qsketch compaction kernel's sort, lifted to a
  leading row-tile axis). Each element carries its column index as a
  tiebreak, so the output order is EXACTLY the fallback's stable
  descending sort — bitonic networks are not stable, but the index
  tiebreak makes every composite key distinct.
* **Gather** — the target and validity payloads ride the same
  compare-exchange swaps; no index materialization, no second pass.

Invalid slots sort last (their key is ``-inf``); valid scores are clipped
to the finite f32 range by the CALLER (``retrieval/table.py``) so a real
document always beats an empty slot.

Parity contract (pinned in ``tests/ops/test_topk_pallas.py``): the
kernel's (keys, payload, validity) triple is BIT-identical to the jnp
fallback (`stable_sort_with_payloads` descending + slice) for every
input — selection and permutation are value-exact operations, so unlike
the segment-sum kernel there is no summation-order caveat.
"""
import functools
from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from metrics_tpu.ops.dispatch import dispatch, register_kernel

try:  # TPU-specific memory spaces; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

Array = jax.Array
ArrayLike = Union[Array, np.ndarray]

#: rows sorted per grid step (sublane-aligned)
_TILE_R = 8
#: widest padded column count the network accepts: 4 resident
#: [_TILE_R, n_pad] f32 buffers plus swap temporaries stay well under the
#: VMEM budget, and the unrolled network depth stays compile-friendly
_MAX_SORT_COLS = 1 << 11
#: below this the sort is too small for a kernel launch to matter
_MIN_SORT_COLS = 1 << 7


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _row_bitonic_desc(key: Array, idx: Array, payloads, n_pad: int):
    """Descending row-parallel bitonic network on composite
    ``(key desc, idx asc)``; every array in ``payloads`` rides the swaps.
    ``key``/``idx``/payloads are ``[rows, n_pad]``. Static Python loops —
    the network fully unrolls at trace time."""
    rows = key.shape[0]
    payloads = list(payloads)
    k = 2
    while k <= n_pad:
        j = k // 2
        while j >= 1:
            m = n_pad // (2 * j)

            def _r(x):
                return x.reshape(rows, m, 2, j)

            kr, ir = _r(key), _r(idx)
            klo, khi = kr[:, :, 0, :], kr[:, :, 1, :]
            ilo, ihi = ir[:, :, 0, :], ir[:, :, 1, :]
            # descending by key, ascending index on ties
            lt = (klo < khi) | ((klo == khi) & (ilo > ihi))
            gt = (klo > khi) | ((klo == khi) & (ilo < ihi))
            blk = jax.lax.broadcasted_iota(jnp.int32, (1, m, 1), 1)
            desc = ((blk * (2 * j)) & k) == 0
            swap = jnp.where(desc, lt, gt)  # [1|rows, m, j]

            def _apply(x):
                xr = _r(x)
                xlo, xhi = xr[:, :, 0, :], xr[:, :, 1, :]
                return jnp.stack(
                    [jnp.where(swap, xhi, xlo), jnp.where(swap, xlo, xhi)], axis=2
                ).reshape(rows, n_pad)

            key = _apply(key)
            idx = _apply(idx)
            payloads = [_apply(p) for p in payloads]
            j //= 2
        k *= 2
    return key, payloads


def _make_topk_kernel(n_pad: int):
    def kernel(keys_ref, pay_ref, val_ref, out_k_ref, out_p_ref, out_v_ref):
        keys = keys_ref[:, :]
        idx = jax.lax.broadcasted_iota(jnp.float32, keys.shape, 1)
        skey, (spay, sval) = _row_bitonic_desc(
            keys, idx, (pay_ref[:, :], val_ref[:, :]), n_pad
        )
        out_k_ref[:, :] = skey
        out_p_ref[:, :] = spay
        out_v_ref[:, :] = sval

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def row_topk_tiled(
    preds: ArrayLike, payload: ArrayLike, valid: ArrayLike, k: int, interpret: bool = False
) -> Tuple[Array, Array, Array]:
    """Per-row top-``k`` by ``preds`` with the payload and validity rows
    gathered through the same permutation:
    ``[R, N] x3 -> ([R, k] keys, [R, k] payload, [R, k] validity)``.
    Invalid slots (``valid <= 0``) key as ``-inf`` and sort last; pad
    rows/columns are sliced back off."""
    preds = jnp.asarray(preds, jnp.float32)
    payload = jnp.asarray(payload, jnp.float32)
    valid = jnp.asarray(valid, jnp.float32)
    r, n = preds.shape
    n_pad = _next_pow2(max(n, 2))
    r_pad = -(-max(r, 1) // _TILE_R) * _TILE_R

    def _pad(x, fill):
        return jnp.full((r_pad, n_pad), fill, jnp.float32).at[:r, :n].set(x)

    keys = _pad(jnp.where(valid > 0, preds, -jnp.inf), -jnp.inf)
    pay = _pad(payload, 0.0)
    val = _pad(valid, 0.0)

    ms = {"memory_space": _VMEM} if (not interpret and _VMEM is not None) else {}
    spec = pl.BlockSpec((_TILE_R, n_pad), lambda i: (i, 0), **ms)
    out_k, out_p, out_v = pl.pallas_call(
        _make_topk_kernel(n_pad),
        out_shape=tuple(
            jax.ShapeDtypeStruct((r_pad, n_pad), jnp.float32) for _ in range(3)
        ),
        grid=(r_pad // _TILE_R,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec, spec),
        interpret=interpret,
    )(keys, pay, val)
    kk = min(k, n)
    return out_k[:r, :kk], out_p[:r, :kk], out_v[:r, :kk]


# ---------------------------------------------------------------------------
# registry-routed entry point
# ---------------------------------------------------------------------------


def _row_topk_jnp(preds, payload, valid, k):
    from metrics_tpu.utils.data import stable_sort_with_payloads

    preds = jnp.asarray(preds, jnp.float32)
    payload = jnp.asarray(payload, jnp.float32)
    valid = jnp.asarray(valid, jnp.float32)
    keys = jnp.where(valid > 0, preds, -jnp.inf)
    sk, sp, sv = stable_sort_with_payloads(keys, payload, valid, descending=True)
    kk = min(k, preds.shape[-1])
    return sk[:, :kk], sp[:, :kk], sv[:, :kk]


def _row_topk_pallas(preds, payload, valid, k, interpret=False):
    return row_topk_tiled(preds, payload, valid, k, interpret=interpret)


def _row_topk_route(preds, payload, valid, k) -> bool:
    r, n = preds.shape
    return (
        jnp.dtype(preds.dtype) == jnp.dtype(jnp.float32)
        and _MIN_SORT_COLS <= n
        and _next_pow2(n) <= _MAX_SORT_COLS
        and r >= 64  # tiny tables: launch overhead beats the fused gather
        # unrolled network work is r_pad * n_pad * log^2(n_pad); cap where
        # the XLA sort + gathers would win back on sheer bandwidth
        and r * _next_pow2(n) <= 1 << 24
    )


register_kernel(
    "row_topk",
    pallas_fn=_row_topk_pallas,
    jnp_fn=_row_topk_jnp,
    route=_row_topk_route,
)


def row_topk_dispatch(
    preds: ArrayLike, payload: ArrayLike, valid: ArrayLike, k: int
) -> Tuple[Array, Array, Array]:
    """Registry-routed per-row top-``k`` + payload gather (see module
    docstring for the bit-parity contract). ``k`` must be a positive
    static int; rows with fewer than ``k`` valid entries pad with
    ``(-inf, 0, 0)`` slots — callers mask on the returned validity."""
    if not isinstance(k, int) or isinstance(k, bool) or k <= 0:
        raise ValueError(f"`k` must be a positive static int, got {k!r}")
    preds = jnp.asarray(preds)
    if preds.ndim != 2:
        raise ValueError(f"`preds` must be [rows, cols], got shape {preds.shape}")
    return dispatch("row_topk", preds, jnp.asarray(payload), jnp.asarray(valid), k)
