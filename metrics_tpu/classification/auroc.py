"""Modular AUROC (cat-state, exact sorted mode).

Behavior parity with /root/reference/torchmetrics/classification/auroc.py:27-181,
including the memory-footprint warning (auroc.py:146-149) and mode locking.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auroc import (
    _auroc_compute,
    _auroc_update,
    auroc_rank_multiclass_masked,
)
from metrics_tpu.functional.classification.exact_curve import binary_auroc_fixed
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import AverageMethod
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class AUROC(CapacityCurveMixin, Metric):
    """Computes the Area Under the Receiver Operating Characteristic Curve.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> auroc = AUROC(pos_label=1)
        >>> auroc(preds, target)
        Array(0.5, dtype=float32)
    """

    __jit_unsafe__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.MICRO, AverageMethod.NONE)
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )

        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode = None
        if capacity is not None:
            # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
            # Binary (num_classes None/1) uses the curve-buffer triple;
            # multiclass (num_classes >= 2) keeps [capacity, C] score rows and
            # computes the exact one-vs-rest rank AUROC with a validity mask.
            if max_fpr is not None:
                raise ValueError("`capacity` mode does not support `max_fpr`")
            if num_classes is not None and num_classes >= 2:
                if average == AverageMethod.MICRO:
                    raise ValueError(
                        "`capacity` multiclass mode supports average in"
                        " ('macro', 'weighted', 'none'); 'micro' is not defined for the"
                        " one-vs-rest rank kernel"
                    )
                self._init_capacity(capacity, num_cols=num_classes)
                self._multiclass_capacity = True
            else:
                self._init_capacity(capacity)
                self._multiclass_capacity = False
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

            rank_zero_warn(
                "Metric `AUROC` will save all targets and predictions in buffer."
                " For large datasets this may lead to large memory footprint."
            )

    _multiclass_capacity: bool = False

    def _update(self, preds: Array, target: Array) -> None:
        if self._capacity is not None:
            self._capacity_update(
                preds, target, pos_label=None if self._multiclass_capacity else self.pos_label
            )
            return
        preds, target, mode = _auroc_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def _compute(self) -> Array:
        if self._capacity is not None:
            if self._multiclass_capacity:
                preds, target, valid = self._capacity_buffers_2d()
                return auroc_rank_multiclass_masked(
                    preds, target, valid, self.num_classes, average=self.average
                )
            return binary_auroc_fixed(*self._capacity_buffers())
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds,
            target,
            self.mode,
            self.num_classes,
            self.pos_label,
            self.average,
            self.max_fpr,
        )
