"""Fused qsketch compaction kernel vs the jnp reference path.

The parity tiers the module advertises (ops/qsketch_pallas.py docstring):

* integer-valued keys/weights — prefix sums and centroid moments are
  order-independent-exact in f32, so sorted order, bucket ids, and merged
  rows are BIT-identical to ``_compact_rows_jnp``;
* arbitrary float keys — summation-order rounding can flip a bucket
  boundary, so parity is pinned at the sketch level: element tolerance on
  the compacted rows and quantile queries within the advertised
  ``rank_error_bound``.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import ops
from metrics_tpu.ops.qsketch_pallas import (
    _qsketch_compact_pallas,
    _qsketch_route,
    qsketch_sort_bucket_tiled,
)
from metrics_tpu.sketches.quantile import (
    _compact_rows_jnp,
    qsketch_init,
    qsketch_insert,
    qsketch_merge,
    qsketch_quantile,
    qsketch_total_weight,
    rank_error_bound,
)


def _int_rows(rng, n, n_occ, cols, weighted=False):
    rows = np.zeros((n, cols), np.float32)
    rows[:n_occ, 0] = rng.integers(1, 5, n_occ) if weighted else 1.0
    rows[:n_occ, 1] = rng.integers(-500, 500, n_occ)
    if cols > 2:
        rows[:n_occ, 2:] = rng.integers(0, 3, (n_occ, cols - 2))
    return jnp.asarray(rows)


@pytest.mark.parametrize(
    "cap,n,n_occ,cols",
    [
        (16, 33, 33, 2),  # minimum-ish capacity, just past overflow
        (64, 128, 128, 3),  # power-of-two rows
        (64, 777, 500, 4),  # ragged row count, unoccupied tail interleaved
        (256, 512, 512, 2),
    ],
)
def test_compact_interpret_bit_identical_on_integer_rows(cap, n, n_occ, cols):
    rng = np.random.default_rng(cap + n + cols)
    rows = _int_rows(rng, n, n_occ, cols, weighted=True)
    want = _compact_rows_jnp(rows, cap)
    got = _qsketch_compact_pallas(rows, cap, interpret=True)
    assert jnp.array_equal(got, want)


def test_compact_float_keys_within_tolerance():
    rng = np.random.default_rng(0)
    cap, n = 128, 256
    rows = np.zeros((n, 3), np.float32)
    rows[:, 0] = 1.0
    rows[:, 1] = rng.standard_normal(n)
    rows[:, 2] = rng.integers(0, 2, n)
    rows = jnp.asarray(rows)
    want = np.asarray(_compact_rows_jnp(rows, cap))
    got = np.asarray(_qsketch_compact_pallas(rows, cap, interpret=True))
    # same centroid count, same total mass, elementwise tolerance
    assert (got[:, 0] > 0).sum() == (want[:, 0] > 0).sum()
    np.testing.assert_allclose(got[:, 0].sum(), want[:, 0].sum(), rtol=1e-6)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_sort_bucket_stage_matches_lexsort():
    """The bitonic network with the index tiebreak reproduces the stable
    ``lexsort((arange, key))`` permutation exactly — duplicate keys
    included."""
    rng = np.random.default_rng(2)
    n, cap = 96, 64
    rows = np.zeros((n, 2), np.float32)
    rows[:80, 0] = 1.0
    rows[:80, 1] = rng.integers(0, 10, 80)  # heavy duplication
    rows = jnp.asarray(rows)
    wvals, bucket = qsketch_sort_bucket_tiled(rows, cap, interpret=True)
    # reference: the jnp path's stable sort, then w and w*key columns
    key = np.where(np.asarray(rows[:, 0]) > 0, np.asarray(rows[:, 1]), np.inf)
    order = np.lexsort((np.arange(n), key))
    srt = np.asarray(rows)[order]
    want_w = srt[:, 0]
    want_wkey = srt[:, 0] * srt[:, 1]
    got = np.asarray(wvals)
    assert got.shape[0] >= n  # padded to the next power of two
    np.testing.assert_array_equal(got[:n, 0], want_w)
    np.testing.assert_array_equal(got[:n, 1], want_wkey)
    assert np.all(got[n:, 0] == 0)  # pads carry no weight
    b = np.asarray(bucket)[:n]
    assert np.all(np.diff(b) >= 0)  # k1 buckets non-decreasing in key order


def test_insert_overflow_through_interpret_kernel_bit_identical():
    """The real consumer path: qsketch_insert past capacity triggers
    _absorb -> _compact_rows -> the dispatched kernel. Integer keys keep
    both backends bit-identical through MULTIPLE compaction rounds, and
    the dispatch-mode jit key must not let a stale jnp trace shadow the
    forced interpret mode."""
    rng = np.random.default_rng(4)
    keys = [jnp.asarray(rng.integers(0, 1000, 40).astype(np.float32)) for _ in range(8)]
    plain = qsketch_init(64)
    for k in keys:
        plain = qsketch_insert(plain, k)
    with ops.forced_backend("interpret"):
        forced = qsketch_init(64)
        for k in keys:
            forced = qsketch_insert(forced, k)
    assert jnp.array_equal(plain, forced)
    assert float(qsketch_total_weight(forced)) == 8 * 40


def test_merge_through_interpret_kernel_bit_identical():
    rng = np.random.default_rng(5)
    a = qsketch_insert(qsketch_init(32), jnp.asarray(rng.integers(0, 99, 32).astype(np.float32)))
    b = qsketch_insert(qsketch_init(32), jnp.asarray(rng.integers(0, 99, 32).astype(np.float32)))
    want = qsketch_merge(a, b)
    with ops.forced_backend("interpret"):
        got = qsketch_merge(a, b)
    assert jnp.array_equal(got, want)


def test_float_stream_quantiles_within_advertised_bound():
    """Adversarial float stream: per-row structure may differ across
    backends at bucket boundaries, but quantile queries must agree within
    the advertised rank-error envelope."""
    rng = np.random.default_rng(6)
    cap, total = 64, 640
    stream = rng.standard_normal(total).astype(np.float32)
    plain = qsketch_init(cap)
    with ops.forced_backend("interpret"):
        forced = qsketch_init(cap)
        for lo in range(0, total, 40):
            forced = qsketch_insert(forced, jnp.asarray(stream[lo : lo + 40]))
    for lo in range(0, total, 40):
        plain = qsketch_insert(plain, jnp.asarray(stream[lo : lo + 40]))
    qs = jnp.asarray([0.05, 0.25, 0.5, 0.75, 0.95])
    pv = np.asarray(qsketch_quantile(plain, qs))
    fv = np.asarray(qsketch_quantile(forced, qs))
    srt = np.sort(stream)
    bound = rank_error_bound(total, cap)
    for backend_vals in (pv, fv):
        for q, v in zip(np.asarray(qs), backend_vals):
            true_rank = np.searchsorted(srt, v)
            assert abs(true_rank - q * total) <= bound + 1


def test_route_bounds():
    small = jnp.zeros((128, 3), jnp.float32)
    big = jnp.zeros((1 << 16, 3), jnp.float32)
    ok = jnp.zeros((4096, 3), jnp.float32)
    wide = jnp.zeros((4096, 32), jnp.float32)
    assert not _qsketch_route(small, 64)  # below the win floor
    assert not _qsketch_route(big, 8192)  # past the VMEM budget
    assert not _qsketch_route(wide, 2048)  # too many payload columns
    assert _qsketch_route(ok, 2048)


def test_windowed_sketch_leaves_compose_through_dispatch():
    """Ring-of-sketches composition (the WindowedMetric + telemetry
    shape): per-slot sketches that compact under the forced interpret
    kernel fold to the same result as the jnp path."""
    rng = np.random.default_rng(7)
    slots_data = [rng.integers(0, 50, 48).astype(np.float32) for _ in range(4)]
    plain_slots = [qsketch_insert(qsketch_init(32), jnp.asarray(d)) for d in slots_data]
    plain = plain_slots[0]
    for s in plain_slots[1:]:
        plain = qsketch_merge(plain, s)
    with ops.forced_backend("interpret"):
        forced_slots = [qsketch_insert(qsketch_init(32), jnp.asarray(d)) for d in slots_data]
        forced = forced_slots[0]
        for s in forced_slots[1:]:
            forced = qsketch_merge(forced, s)
    assert jnp.array_equal(plain, forced)
