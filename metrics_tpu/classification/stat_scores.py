"""Modular StatScores — the base of the classification metric family.

Behavior parity with /root/reference/torchmetrics/classification/
stat_scores.py:24-260: tp/fp/tn/fn accumulators of static shape (``[]`` for
micro, ``[num_classes]`` for macro) with sum reduction, or list states when
``reduce='samples'`` / ``mdmc_reduce='samplewise'``.
"""
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.stat_scores import _stat_scores_compute, _stat_scores_update

Array = jax.Array


class StatScores(Metric):
    """Computes the number of true/false positives/negatives and support.

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([1, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> stat_scores = StatScores(reduce='macro', num_classes=3)
        >>> stat_scores(preds, target)
        Array([[0, 1, 2, 1, 1],
               [1, 1, 1, 1, 2],
               [1, 0, 3, 0, 1]], dtype=int32)
    """

    is_differentiable = False

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        default: Callable = list
        reduce_fn: Optional[str] = "cat"
        if mdmc_reduce != "samplewise" and reduce != "samples":
            zeros_shape = [] if reduce == "micro" else [num_classes]
            default = lambda: jnp.zeros(zeros_shape, dtype=jnp.int32)
            reduce_fn = "sum"

        for s in ("tp", "fp", "tn", "fn"):
            self.add_state(s, default=default(), dist_reduce_fx=reduce_fn)

    def _update(self, preds: Array, target: Array) -> None:
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        if self.reduce != "samples" and self.mdmc_reduce != "samplewise":
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn
        else:
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        """Concatenate list states if necessary. Reference stat_scores.py:221-227."""
        tp = jnp.concatenate(self.tp) if isinstance(self.tp, list) else self.tp
        fp = jnp.concatenate(self.fp) if isinstance(self.fp, list) else self.fp
        tn = jnp.concatenate(self.tn) if isinstance(self.tn, list) else self.tn
        fn = jnp.concatenate(self.fn) if isinstance(self.fn, list) else self.fn
        return tp, fp, tn, fn

    def _compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)
