"""Execute every docstring example in the package (reference Makefile:23 runs
pytest with doctests over torchmetrics; same discipline here, as a single
explicit runner so the skip list is visible)."""
import doctest
import importlib
import pkgutil

import pytest

import metrics_tpu

_MODULES = [info.name for info in pkgutil.walk_packages(metrics_tpu.__path__, "metrics_tpu.")]


@pytest.mark.parametrize("module_name", _MODULES)
def test_module_doctests(module_name):
    try:
        module = importlib.import_module(module_name)
    except ImportError as err:  # compiled extensions (e.g. native/_lsap.so)
        pytest.skip(f"not a python module: {err}")
    result = doctest.testmod(
        module, optionflags=doctest.ELLIPSIS | doctest.NORMALIZE_WHITESPACE, verbose=False
    )
    assert result.failed == 0, f"{result.failed} doctest failure(s) in {module_name}"


def test_doctest_volume():
    """The example corpus must not silently evaporate (regression guard)."""
    total = 0
    for name in _MODULES:
        try:
            module = importlib.import_module(name)
        except ImportError:  # compiled extensions (e.g. native/_lsap.so)
            continue
        finder = doctest.DocTestFinder()
        total += sum(len(t.examples) for t in finder.find(module))
    assert total > 400, f"only {total} doctest examples discovered"
