"""Fixed-capacity streaming sketch states (``metrics_tpu.sketches``).

The subsystem that retires cat-state: pure, fixed-shape, trace-safe
streaming structures with a common contract —

* ``*_init(capacity, ...) -> state leaf`` (plain float32 array)
* ``*_insert(state, ...) -> state``  — pure, jit-safe, ``n_valid``-maskable
* ``*_merge(a, b) -> state``         — the ``dist_reduce_fx`` operation
* per-sketch queries (quantiles/CDF/histogram, sample rows, Spearman)

Three families:

* :mod:`.quantile` — mergeable weighted quantile/stream sketch (packed
  ``[capacity, 2+P]`` leaf, pair-collapse compaction). Powers the sketched
  threshold curves (AUROC / ROC / PRC / AveragePrecision).
* :mod:`.reservoir` — weighted reservoir (``[k, 1+P]`` leaf, top-k
  replacement) with counter-seeded Gumbel or deterministic hash-key
  priorities. Powers KID subset selection and the detection mAP
  per-image matching table.
* :mod:`.histogram` — static-edge weighted histogram (exact sufficient
  statistics for binned metrics). Powers CalibrationError.
* :mod:`.rank` — (pred, target) quantile sketch + weighted midrank
  Spearman, for streaming SpearmanCorrCoef.
* :mod:`.moments` — exact streaming sum / outer-product-sum / count
  leaves (element-wise summable; cross-rank merge is addition). Powers
  streaming FID and InceptionScore.

See ``docs/sketch_states.md`` for the accuracy contract, the lossless
window, capacity tuning, and the mergeability story.
"""
from .histogram import hist_bin_index, hist_init, hist_insert, hist_merge
from .quantile import (
    QSKETCH_RANK_EPS,
    qsketch_absorb_rows,
    qsketch_cdf,
    qsketch_fill,
    qsketch_histogram,
    qsketch_init,
    qsketch_insert,
    qsketch_merge,
    qsketch_merge_into,
    qsketch_quantile,
    qsketch_rank,
    qsketch_total_weight,
    rank_error_bound,
    sketch_merge_fx,
)
from .rank import (
    ranksketch_init,
    ranksketch_insert,
    ranksketch_merge,
    ranksketch_merge_fx,
    ranksketch_spearman,
)
from .moments import (
    mean_cov_from_moments,
    moments_init,
    moments_merge_fx,
    moments_update,
)
from .reservoir import (
    detection_table_init,
    reservoir_fill,
    reservoir_init,
    reservoir_insert,
    reservoir_insert_keyed,
    reservoir_key,
    reservoir_merge,
    reservoir_merge_fx,
    reservoir_rows,
)
from .compat import register_exact_list_states, warn_exact_buffer

__all__ = [
    "QSKETCH_RANK_EPS",
    "detection_table_init",
    "hist_bin_index",
    "hist_init",
    "hist_insert",
    "hist_merge",
    "mean_cov_from_moments",
    "moments_init",
    "moments_merge_fx",
    "moments_update",
    "qsketch_absorb_rows",
    "qsketch_cdf",
    "qsketch_fill",
    "qsketch_histogram",
    "qsketch_init",
    "qsketch_insert",
    "qsketch_merge",
    "qsketch_merge_into",
    "qsketch_quantile",
    "qsketch_rank",
    "qsketch_total_weight",
    "rank_error_bound",
    "ranksketch_init",
    "ranksketch_insert",
    "ranksketch_merge",
    "ranksketch_merge_fx",
    "ranksketch_spearman",
    "register_exact_list_states",
    "reservoir_fill",
    "reservoir_init",
    "reservoir_insert",
    "reservoir_insert_keyed",
    "reservoir_key",
    "reservoir_merge",
    "reservoir_merge_fx",
    "reservoir_rows",
    "sketch_merge_fx",
    "warn_exact_buffer",
]
