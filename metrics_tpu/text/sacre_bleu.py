"""Modular SacreBLEUScore.

Behavior parity with /root/reference/torchmetrics/text/sacre_bleu.py:32-122:
BLEUScore subclass swapping in the sacrebleu-compatible tokenizer family
(13a / char / intl / none / zh).
"""
from typing import Any

from metrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from metrics_tpu.text.bleu import BLEUScore
from metrics_tpu.utils.imports import _REGEX_AVAILABLE


class SacreBLEUScore(BLEUScore):
    """Calculate BLEU score with sacrebleu-compatible tokenization.

    Args:
        n_gram: Gram value ranged from 1 to 4 (default 4).
        smooth: Whether to apply add-one smoothing.
        tokenize: Tokenization technique: one of ``'none'``, ``'13a'``,
            ``'zh'``, ``'intl'``, ``'char'``.
        lowercase: If True, BLEU is case-insensitive.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = SacreBLEUScore()
        >>> metric(preds, target)
        Array(0.75983566, dtype=float32)
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError(
                "`'intl'` tokenization requires that `regex` is installed. Use `pip install regex`."
            )
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
