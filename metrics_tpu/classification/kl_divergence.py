"""Modular KLDivergence.

Behavior parity with /root/reference/torchmetrics/classification/kl_divergence.py:24-105.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.kl_divergence import _kld_compute, _kld_update
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class KLDivergence(Metric):
    """Computes the KL divergence between distributions p and q.

    Example:
        >>> import jax.numpy as jnp
        >>> p = jnp.array([[0.36, 0.48, 0.16]])
        >>> q = jnp.array([[1/3, 1/3, 1/3]])
        >>> kl_divergence = KLDivergence()
        >>> kl_divergence(p, q)
        Array(0.0852996, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    #: list-append update traces; the cat states exclude it from fusion anyway
    __jit_unsafe__ = False

    def __init__(
        self,
        log_prob: bool = False,
        reduction: str = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        self.log_prob = log_prob
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction

        if self.reduction in ("mean", "sum"):
            self.add_state("measures", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("measures", default=[], dist_reduce_fx="cat")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, p: Array, q: Array) -> None:
        measures, total = _kld_update(p, q, self.log_prob)
        if self.reduction is None or self.reduction == "none":
            self.measures.append(measures)
        else:
            self.measures = self.measures + jnp.sum(measures)
        self.total = self.total + total

    def _compute(self) -> Array:
        measures = dim_zero_cat(self.measures) if isinstance(self.measures, list) else self.measures
        return _kld_compute(measures, self.total, self.reduction)
