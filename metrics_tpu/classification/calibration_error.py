"""Modular CalibrationError (binned streaming default; exact opt-in).

Behavior parity with /root/reference/torchmetrics/classification/
calibration_error.py:24-110. The exact compute bins confidences into
``n_bins`` anyway, so the per-bin weighted sums (count / confidence /
accuracy — ``metrics_tpu/sketches/histogram.py``) are SUFFICIENT
statistics: the default streaming state is O(n_bins), exact for every
stream length (up to float summation order; bit-exact when scores align
to bin boundaries — pinned in tests), and made of plain ``"sum"``-reduced
leaves, so it fuses, buckets with the exact ``k * delta`` pad correction,
slices per-tenant, and mesh-syncs in the fused all-reduce round with zero
new plumbing. ``exact=True`` restores the unbounded cat-state path.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.calibration_error import _ce_compute, _ce_update
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.sketches.histogram import hist_bin_index
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CalibrationError(Metric):
    """Computes the top-label calibration error ('l1'=ECE, 'l2'=RMSCE, 'max'=MCE).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.9, 0.8, 0.3, 0.2])
        >>> target = jnp.array([1, 1, 0, 0])
        >>> metric = CalibrationError(n_bins=2)
        >>> bool(metric(preds, target) < 0.3)
        True
    """

    DISTANCES = {"l1", "l2", "max"}
    is_differentiable = False
    __jit_unsafe__ = False  # binned default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        exact: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        if norm not in self.DISTANCES:
            raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
        if not isinstance(n_bins, int) or n_bins <= 0:
            raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")

        self.n_bins = n_bins
        self.bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
        self.norm = norm
        self._exact = bool(exact)

        if self._exact:
            register_exact_list_states(self, ("confidences", "accuracies"))
            warn_exact_buffer("CalibrationError", "confidences and accuracies")
        else:
            self.add_state("bin_count", default=jnp.zeros(n_bins, jnp.float32), dist_reduce_fx="sum")
            self.add_state("bin_conf", default=jnp.zeros(n_bins, jnp.float32), dist_reduce_fx="sum")
            self.add_state("bin_acc", default=jnp.zeros(n_bins, jnp.float32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        confidences, accuracies = _ce_update(preds, target)
        if self._exact:
            self.confidences.append(confidences)
            self.accuracies.append(accuracies)
            return
        idx = hist_bin_index(self.bin_boundaries, confidences)
        ones = jnp.ones_like(confidences)
        self.bin_count = self.bin_count.at[idx].add(ones)
        self.bin_conf = self.bin_conf.at[idx].add(confidences)
        self.bin_acc = self.bin_acc.at[idx].add(accuracies)

    def _compute(self) -> Array:
        if self._exact:
            confidences = dim_zero_cat(self.confidences)
            accuracies = dim_zero_cat(self.accuracies)
            return _ce_compute(confidences, accuracies, self.bin_boundaries, norm=self.norm)
        # the exact compute's per-bin means/proportions from the streamed sums
        count = self.bin_count
        safe = jnp.where(count == 0, 1.0, count)
        conf_bin = jnp.where(count == 0, 0.0, self.bin_conf / safe)
        acc_bin = jnp.where(count == 0, 0.0, self.bin_acc / safe)
        prop_bin = count / jnp.clip(jnp.sum(count), 1.0, None)
        if self.norm == "l1":
            return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
        if self.norm == "max":
            return jnp.max(jnp.abs(acc_bin - conf_bin))
        ce = jnp.sum(jnp.square(acc_bin - conf_bin) * prop_bin)
        return jnp.where(ce > 0, jnp.sqrt(jnp.where(ce > 0, ce, 1.0)), 0.0)
    # NOTE: the binned path is exact — the sums are sufficient statistics
    # for every norm — so there is no `sketch_capacity` knob here; `exact`
    # only exists to reproduce the reference's storage behavior bit-for-bit.
