"""Modular UniversalImageQualityIndex.

Behavior parity with /root/reference/torchmetrics/image/uqi.py:25-110.
"""
from typing import Any, Optional, Sequence

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.image.uqi import _uqi_compute, _uqi_update
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class UniversalImageQualityIndex(Metric):
    """Computes UQI over accumulated batches.

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> uqi = UniversalImageQualityIndex()
        >>> bool(uqi(preds, target) > 0.9)
        True
    """

    is_differentiable = True
    higher_is_better = True
    #: list-append update traces; the cat states exclude it from fusion anyway
    __jit_unsafe__ = False

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.data_range = data_range
        self.reduction = reduction

    def _update(self, preds: Array, target: Array) -> None:
        preds, target = _uqi_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def _compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _uqi_compute(preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range)
