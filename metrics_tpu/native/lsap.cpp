// Linear sum assignment (square matrices) via the shortest-augmenting-path
// Hungarian algorithm with row/column potentials — the same O(n^3) family
// scipy's C++ solver implements. Host-side native component for PIT's
// large-speaker path (metrics_tpu/functional/audio/pit.py; the reference
// delegates this to scipy, SURVEY §2.9).
//
// Built on demand by metrics_tpu/native/__init__.py:
//   g++ -O3 -shared -fPIC lsap.cpp -o _lsap.so

#include <cstddef>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

// Assign each row of the n x n cost matrix `a` (row-major) to a distinct
// column minimizing total cost; writes the column of each row.
void solve_one(const double* a, int n, int32_t* col_of_row) {
    const double INF = std::numeric_limits<double>::infinity();
    std::vector<double> u(n, 0.0);       // row potentials
    std::vector<double> v(n + 1, 0.0);   // column potentials (n = virtual col)
    std::vector<int> p(n + 1, -1);       // p[j]: row matched to column j
    std::vector<int> way(n + 1, -1);     // predecessor column on the path

    for (int i = 0; i < n; ++i) {
        std::vector<double> minv(n + 1, INF);
        std::vector<char> used(n + 1, 0);
        int j0 = n;
        p[n] = i;
        do {
            used[j0] = 1;
            const int i0 = p[j0];
            double delta = INF;
            int j1 = -1;
            for (int j = 0; j < n; ++j) {
                if (used[j]) continue;
                const double cur = a[static_cast<size_t>(i0) * n + j] - u[i0] - v[j];
                if (cur < minv[j]) {
                    minv[j] = cur;
                    way[j] = j0;
                }
                if (minv[j] < delta) {
                    delta = minv[j];
                    j1 = j;
                }
            }
            for (int j = 0; j <= n; ++j) {
                if (used[j]) {
                    if (p[j] >= 0) u[p[j]] += delta;
                    v[j] -= delta;
                } else {
                    minv[j] -= delta;
                }
            }
            j0 = j1;
        } while (p[j0] != -1);

        while (j0 != n) {  // augment along the stored path
            const int j1 = way[j0];
            p[j0] = p[j1];
            j0 = j1;
        }
        p[n] = -1;
    }

    for (int j = 0; j < n; ++j) col_of_row[p[j]] = j;
}

}  // namespace

extern "C" {

// costs: [batch, n, n] row-major doubles; out: [batch, n] int32 column of
// each row. Returns 0 on success.
int lsap_batch(const double* costs, int batch, int n, int32_t* out) {
    if (n <= 0 || batch < 0) return 1;
    for (int b = 0; b < batch; ++b) {
        solve_one(costs + static_cast<size_t>(b) * n * n, n,
                  out + static_cast<size_t>(b) * n);
    }
    return 0;
}

}  // extern "C"
