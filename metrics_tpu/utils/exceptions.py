"""User-facing exceptions.

Parity with the reference's ``TorchMetricsUserError``
(/root/reference/torchmetrics/utilities/exceptions.py:17).
"""


class MetricsUserError(Exception):
    """Error raised when user misuses the metric API (e.g. illegal sync ordering)."""


class MetricsUserWarning(UserWarning):
    """Warning category for metric API usage issues (e.g. memory-heavy list states)."""
