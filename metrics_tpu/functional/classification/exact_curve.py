"""Exact curve metrics with STATIC shapes: fixed-capacity buffer + valid mask.

The reference's exact curve family (AUROC/ROC/PRC/AveragePrecision) keeps
unbounded cat-states and dedupes thresholds with data-dependent shapes
(/root/reference/torchmetrics/functional/classification/
precision_recall_curve.py:23-62), which cannot trace under jit. This module
is the SURVEY §7 design-3 alternative: a user-declared capacity buffer with a
validity mask, and curve kernels whose outputs are static-shape.

The tie/dedup problem is solved without dynamic shapes: after sorting by
descending score, each position gathers the cumulative tp/fp values at the
END of its equal-score run (reverse-cummin of run boundaries). Consecutive
positions inside a run then carry identical curve points, so trapezoidal
integration and the step-wise AP sum are EXACTLY the deduped values — ties
included — while every array stays ``[capacity]``.

Everything here is jit-traceable, vmap-able, and mesh-syncable: the buffer
triple (preds, target, valid) composes with ``lax.all_gather`` by simple
concatenation along the buffer axis.
"""
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.data import stable_sort_with_payloads

Array = jax.Array


# ---------------------------------------------------------------------------
# fixed-capacity buffer
# ---------------------------------------------------------------------------


def curve_buffer_init(capacity: int) -> Dict[str, Array]:
    """Fresh (preds, target, valid) buffer state."""
    return {
        "preds": jnp.zeros((capacity,), jnp.float32),
        "target": jnp.zeros((capacity,), jnp.int32),
        "valid": jnp.zeros((capacity,), bool),
    }


def curve_buffer_update(state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
    """Append a batch into the first free slots (jit-safe).

    The write positions come from the valid mask itself (first ``len(preds)``
    unset slots), NOT from an offset at ``sum(valid)`` — so updating a buffer
    produced by :func:`curve_buffer_merge` / an all_gather (partially-filled
    shards concatenated, non-contiguous fill) never overwrites valid entries.
    Writes past capacity are dropped silently under jit (XLA scatter
    ``mode='drop'``); the stateful wrapper raises eagerly on overflow.
    """
    capacity = state["valid"].shape[0]
    idx = jnp.nonzero(~state["valid"], size=preds.shape[0], fill_value=capacity)[0].astype(jnp.int32)
    return {
        "preds": state["preds"].at[idx].set(preds.astype(jnp.float32), mode="drop"),
        "target": state["target"].at[idx].set(target.astype(jnp.int32), mode="drop"),
        "valid": state["valid"].at[idx].set(True, mode="drop"),
    }


def curve_buffer_merge(*states: Dict[str, Array]) -> Dict[str, Array]:
    """Concatenate buffers (e.g. per-rank shards after an all_gather)."""
    return {
        "preds": jnp.concatenate([s["preds"] for s in states]),
        "target": jnp.concatenate([s["target"] for s in states]),
        "valid": jnp.concatenate([s["valid"] for s in states]),
    }


# ---------------------------------------------------------------------------
# masked static-shape curve kernels
# ---------------------------------------------------------------------------


def _masked_sorted_cumulants(
    preds: Array, target: Array, valid: Array
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Sort by descending score (invalid last) and return run-end cumulants.

    Returns ``(sorted_key, sorted_valid, tps, fps, run_end, run_start)``
    where ``tps``/``fps`` are cumulative counts and ``run_end[i]`` /
    ``run_start[i]`` are the last/first index sharing ``sorted_key[i]`` —
    the tie run that position belongs to. The run boundaries are derived
    ONCE here; every tie/key convention lives in this helper.
    """
    key = jnp.where(valid, preds.astype(jnp.float32), -jnp.inf)
    # one stable multi-operand sort carries target and validity through the
    # permutation (the round-5 minor-axis layout lesson: measured 3-6x over
    # argsort + gathers in the AUROC/retrieval kernels; identical order)
    sorted_key, sorted_tgt, sorted_valid = stable_sort_with_payloads(
        key, jnp.where(valid, target, 0).astype(jnp.float32), valid, descending=True
    )

    tps = jnp.cumsum(sorted_tgt)
    fps = jnp.cumsum((1.0 - sorted_tgt) * sorted_valid)

    n = sorted_key.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    boundary = sorted_key[1:] != sorted_key[:-1]
    is_run_last = jnp.concatenate([boundary, jnp.ones(1, bool)])
    is_run_first = jnp.concatenate([jnp.ones(1, bool), boundary])
    run_end = jax.lax.cummin(jnp.where(is_run_last, idx, n - 1)[::-1])[::-1]
    run_start = jax.lax.cummax(jnp.where(is_run_first, idx, 0))
    return sorted_key, sorted_valid, tps, fps, run_end, run_start


def binary_average_precision_fixed(preds: Array, target: Array, valid: Array) -> Array:
    """Exact binary average precision over the valid entries (jit-safe).

    Matches the reference AP (step-wise sum over deduped thresholds,
    functional/classification/average_precision.py): every positive
    contributes the precision at the END of its tie run. NaN when there are
    no positive targets (reference 0/0 semantics).
    """
    _, sorted_valid, tps, fps, run_end, _ = _masked_sorted_cumulants(preds, target, valid)
    total_pos = tps[-1]
    precision = tps / jnp.clip(tps + fps, 1.0, None)
    contributions = jnp.diff(tps, prepend=0.0) * precision[run_end] * sorted_valid
    return jnp.where(total_pos > 0, jnp.sum(contributions) / jnp.clip(total_pos, 1.0, None), jnp.nan)


def binary_auroc_fixed(preds: Array, target: Array, valid: Array) -> Array:
    """Exact binary AUROC over the valid entries (jit-safe, tie-exact).

    Trapezoidal area over run-end ROC points: positions inside a tie run
    carry identical (fpr, tpr), so their segments contribute zero width and
    the result equals the deduped-threshold integral. NaN when either class
    is absent.
    """
    _, _, tps, fps, run_end, _ = _masked_sorted_cumulants(preds, target, valid)
    total_pos, total_neg = tps[-1], fps[-1]
    tpr = tps[run_end] / jnp.clip(total_pos, 1.0, None)
    fpr = fps[run_end] / jnp.clip(total_neg, 1.0, None)
    first = 0.5 * tpr[0] * fpr[0]  # segment from the implicit (0, 0) point
    rest = jnp.sum(0.5 * (tpr[1:] + tpr[:-1]) * (fpr[1:] - fpr[:-1]))
    return jnp.where((total_pos > 0) & (total_neg > 0), first + rest, jnp.nan)


def binary_roc_fixed(
    preds: Array, target: Array, valid: Array
) -> Tuple[Array, Array, Array, Array]:
    """Static-shape ROC: ``(fpr, tpr, thresholds, point_mask)``, each
    ``[capacity + 1]``.

    Valid points (where ``point_mask``) reproduce the reference ROC exactly:
    the leading point is the prepended (0, 0) at ``thresholds[0] + 1``
    (reference functional/classification/roc.py), then one point per distinct
    threshold in descending-score order. Padded slots repeat the final point.
    """
    sorted_key, sorted_valid, tps, fps, run_end, _ = _masked_sorted_cumulants(preds, target, valid)
    total_pos, total_neg = tps[-1], fps[-1]
    idx = jnp.arange(sorted_key.shape[0])
    is_threshold = (run_end == idx) & sorted_valid

    tpr = jnp.concatenate([jnp.zeros(1), tps / jnp.clip(total_pos, 1.0, None)])
    fpr = jnp.concatenate([jnp.zeros(1), fps / jnp.clip(total_neg, 1.0, None)])
    thresholds = jnp.concatenate([sorted_key[:1] + 1.0, sorted_key])
    point_mask = jnp.concatenate([jnp.any(valid)[None], is_threshold])
    return fpr, tpr, thresholds, point_mask


def binary_precision_recall_curve_fixed(
    preds: Array, target: Array, valid: Array
) -> Tuple[Array, Array, Array, Array, Array]:
    """Static-shape PRC: ``(precision, recall, thresholds, point_mask,
    last_point)``, arrays ``[capacity]`` plus the appended reference endpoint.

    Valid points in descending-score order; the reference output
    (functional/classification/precision_recall_curve.py:150-176) is these
    points REVERSED with ``(precision=1, recall=0)`` appended — returned
    separately as ``last_point`` so the caller keeps static shapes.
    """
    sorted_key, sorted_valid, tps, fps, run_end, run_start = _masked_sorted_cumulants(preds, target, valid)
    total_pos = tps[-1]
    idx = jnp.arange(sorted_key.shape[0])
    is_threshold = (run_end == idx) & sorted_valid

    # reference/sklearn truncation: once a threshold point achieves full
    # recall, every LOWER threshold adds no recall and is dropped
    # (reference precision_recall_curve.py `last_ind = where(tps == tps[-1])[0]`).
    # A run is kept iff full recall was not yet reached strictly BEFORE it;
    # with zero positives the reference convention degenerates to keeping
    # only the first (highest) threshold, which the `run_start == 0` arm
    # reproduces (prev_end_tps < 0 is never true).
    prev_end_tps = jnp.where(run_start > 0, tps[jnp.maximum(run_start - 1, 0)], 0.0)
    is_threshold = is_threshold & ((prev_end_tps < total_pos) | (run_start == 0))

    precision = tps / jnp.clip(tps + fps, 1.0, None)
    recall = jnp.where(total_pos > 0, tps / jnp.clip(total_pos, 1.0, None), jnp.nan)
    last_point = jnp.asarray([1.0, 0.0])
    return precision, recall, sorted_key, is_threshold, last_point


# ---------------------------------------------------------------------------
# multiclass / multilabel wrappers: one-vs-rest over class columns
# ---------------------------------------------------------------------------


def _per_class_scores_targets(
    preds: Array, target: Array, num_classes: int, multilabel: bool
) -> Tuple[Array, Array]:
    """``([C, N] scores, [C, N] binary targets)`` for one-vs-rest kernels.

    ``preds`` is the ``[N, C]`` score buffer; ``target`` is ``[N]`` integer
    labels (multiclass) or ``[N, C]`` per-class indicators (multilabel).
    """
    scores = preds.astype(jnp.float32).T
    if multilabel:
        tgt = target.astype(jnp.int32).T
    else:
        tgt = (target[None, :] == jnp.arange(num_classes)[:, None]).astype(jnp.int32)
    return scores, tgt


def multiclass_roc_fixed(
    preds: Array, target: Array, valid: Array, num_classes: int, multilabel: bool = False
) -> Tuple[Array, Array, Array, Array]:
    """One-vs-rest :func:`binary_roc_fixed` per class column (vmapped).

    Returns ``(fpr, tpr, thresholds, point_mask)`` each ``[C, capacity + 1]``
    — row ``c`` is the exact binary ROC of class ``c`` vs rest, matching the
    reference's multiclass list-of-curves output
    (functional/classification/roc.py) with static shapes.
    """
    scores, tgt = _per_class_scores_targets(preds, target, num_classes, multilabel)
    return jax.vmap(binary_roc_fixed, in_axes=(0, 0, None))(scores, tgt, valid)


def multiclass_precision_recall_curve_fixed(
    preds: Array, target: Array, valid: Array, num_classes: int, multilabel: bool = False
) -> Tuple[Array, Array, Array, Array, Array]:
    """One-vs-rest :func:`binary_precision_recall_curve_fixed` per class
    column (vmapped); arrays ``[C, capacity]`` plus ``last_point [C, 2]``."""
    scores, tgt = _per_class_scores_targets(preds, target, num_classes, multilabel)
    return jax.vmap(binary_precision_recall_curve_fixed, in_axes=(0, 0, None))(scores, tgt, valid)


def multiclass_average_precision_fixed(
    preds: Array,
    target: Array,
    valid: Array,
    num_classes: int,
    average: str = "macro",
    multilabel: bool = False,
) -> Array:
    """Exact one-vs-rest average precision over a fixed-capacity buffer.

    ``average``: ``'macro'`` / ``'weighted'`` average over classes with at
    least one positive (undefined classes are EXCLUDED, the same convention
    as the capacity-mode multiclass AUROC — unbiased on sharded eval batches
    where tail classes may be absent); ``'micro'`` flattens scores against
    the one-vs-rest indicator matrix (reference micro semantics);
    ``'none'``/``None`` returns the per-class vector (NaN where undefined).
    """
    scores, tgt = _per_class_scores_targets(preds, target, num_classes, multilabel)
    if average == "micro":
        flat_valid = jnp.broadcast_to(valid[None, :], tgt.shape).reshape(-1)
        return binary_average_precision_fixed(scores.reshape(-1), tgt.reshape(-1), flat_valid)
    ap = jax.vmap(binary_average_precision_fixed, in_axes=(0, 0, None))(scores, tgt, valid)
    if average in (None, "none"):
        return ap
    n_pos = jnp.sum(tgt * valid[None, :], axis=1).astype(jnp.float32)
    defined = n_pos > 0
    # NaN (not 0) when NO class is defined — a blanked valid mask (overflow
    # poisoning, or a never-updated buffer) must never yield a plausible value
    any_defined = jnp.any(defined)
    if average == "macro":
        macro = jnp.sum(jnp.where(defined, ap, 0.0)) / jnp.maximum(jnp.sum(defined), 1)
        return jnp.where(any_defined, macro, jnp.nan)
    if average == "weighted":
        w = jnp.where(defined, n_pos, 0.0)
        weighted = jnp.sum(jnp.where(defined, ap, 0.0) * w) / jnp.maximum(jnp.sum(w), 1.0)
        return jnp.where(any_defined, weighted, jnp.nan)
    raise ValueError(
        f"Argument `average` expected to be one of ('micro', 'macro', 'weighted', 'none') but got {average}"
    )
