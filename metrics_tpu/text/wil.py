"""Modular WordInfoLost.

Behavior parity with /root/reference/torchmetrics/text/wil.py:23-98.
"""
from typing import Any, List, Union

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.wil import _wil_compute, _wil_update

Array = jax.Array


class WordInfoLost(Metric):
    """Word information lost of transcriptions vs references; 0 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = WordInfoLost()
        >>> metric(preds, target)
        Array(0.6527778, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=0.0, dist_reduce_fx="sum")
        self.add_state("target_total", default=0.0, dist_reduce_fx="sum")
        self.add_state("preds_total", default=0.0, dist_reduce_fx="sum")

    def _update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wil_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def _compute(self) -> Array:
        return _wil_compute(self.errors, self.target_total, self.preds_total)
