"""Modular CosineSimilarity (streaming sums for 'sum'/'mean' reductions).

Behavior parity with /root/reference/torchmetrics/regression/cosine_similarity.py:24-89.
The reference stores EVERY (pred, target) row and reduces at compute time;
but for ``reduction='sum'/'mean'`` the per-sample similarities are reduced
by a plain sum, so a running scalar sum + count is an EXACT fixed-shape
streaming state — O(1) memory, fusible/bucketable/sliceable with zero new
machinery. ``reduction='none'`` genuinely returns per-sample values, so it
keeps the cat-state path (as does ``exact=True``, which restores the
reference storage for the reduced modes too).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Computes cosine similarity between predictions and targets.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0., 1.], [1., 1.]])
        >>> preds = jnp.array([[0., 1.], [0., 1.]])
        >>> cosine_similarity = CosineSimilarity(reduction='mean')
        >>> cosine_similarity(preds, target)
        Array(0.8535534, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    __jit_unsafe__ = False  # streaming-sum default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"

    def __init__(self, reduction: Optional[str] = "sum", exact: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        # 'none' returns per-sample values: only the cat-state path can
        # represent that; the reduced modes stream exactly
        self._exact = bool(exact) or reduction in ("none", None)
        if self._exact:
            register_exact_list_states(self, ("preds", "target"))
            if exact:
                warn_exact_buffer("CosineSimilarity")
        else:
            self.add_state("sim_sum", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        if self._exact:
            self.preds.append(preds)
            self.target.append(target)
            return
        # the same per-sample similarity the compute kernel derives, reduced
        # incrementally — exact for 'sum'/'mean' (addition is associative up
        # to float rounding, within the documented batch-order tolerance)
        sim = _cosine_similarity_compute(preds, target, None)
        self.sim_sum = self.sim_sum + jnp.sum(sim)
        self.total = self.total + sim.reshape(-1).shape[0]

    def _compute(self) -> Array:
        if self._exact:
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
            return _cosine_similarity_compute(preds, target, self.reduction)
        if self.reduction == "mean":
            return self.sim_sum / jnp.clip(self.total.astype(jnp.float32), 1.0, None)
        return self.sim_sum