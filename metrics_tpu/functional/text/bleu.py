"""BLEU score (parity: /root/reference/torchmetrics/functional/text/bleu.py).

N-gram counting is host-side Counter math (inherently string-keyed); the
accumulated numerator/denominator/length states are device arrays so the
metric syncs over the mesh like any other (SURVEY §7.8).
"""
from collections import Counter
from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """Count all 1..n_gram tuples in a token list (bleu.py:26-44)."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: np.ndarray,
    denominator: np.ndarray,
    preds_len: float,
    target_len: float,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[float, float]:
    """Accumulate clipped n-gram hits into numerator/denominator (bleu.py:58-103).

    ``numerator``/``denominator`` are mutated in place (host numpy staging
    buffers); returns updated ``(preds_len, target_len)``.
    """
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]

    for pred, targets in zip(preds_tok, target_tok):
        preds_len += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)

        ngram_counter_clip = preds_counter & target_counter
        for counter_clip in ngram_counter_clip:
            numerator[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in preds_counter:
            denominator[len(counter) - 1] += preds_counter[counter]

    return preds_len, target_len


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int = 4,
    smooth: bool = False,
) -> Array:
    """Geometric mean of n-gram precisions with brevity penalty (bleu.py:106-141)."""
    numerator = jnp.asarray(numerator, jnp.float32)
    denominator = jnp.asarray(denominator, jnp.float32)
    preds_len = jnp.asarray(preds_len, jnp.float32)
    target_len = jnp.asarray(target_len, jnp.float32)

    if float(jnp.min(numerator)) == 0.0:
        return jnp.asarray(0.0, jnp.float32)

    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator[0])
    else:
        precision_scores = numerator / denominator

    log_precision_scores = (1.0 / n_gram) * jnp.log(precision_scores)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(
        preds_len > target_len, 1.0, jnp.exp(1 - target_len / preds_len)
    )
    return brevity_penalty * geometric_mean


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
) -> Array:
    """Calculate BLEU score of machine-translated text with one or more references.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu_score(preds, target)
        Array(0.75983566, dtype=float32)
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]

    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")

    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len, target_len = _bleu_score_update(
        preds_, target_, numerator, denominator, 0.0, 0.0, n_gram
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, smooth)
