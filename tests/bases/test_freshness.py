"""Freshness observatory tests (ISSUE 16 tentpole + satellites): the
FreshnessStamp monoid and payload round-trip, the typed ``read`` event
from every entry point (compute cache hit/miss, windowed folds, sliced
subset reads, retrieval table unpacks, fleet folds), the read/freshness
Prometheus families and the qsketch-backed window histograms under a
strict exposition parser, heterogeneous-fleet identity merges through
``merge_payloads`` AND ``render_prometheus``, the wire v2 span header
(v1 snapshots keep decoding), the collector clock-skew clamp, the
fleet-mode Perfetto export's publish->fold flow arrows, and the
``freshness_slo`` / ``read_latency`` alarm classes firing and clearing."""
import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import MeanMetric, SumMetric
from metrics_tpu.classification import Accuracy
from metrics_tpu.observability import (
    FleetCollector,
    FreshnessStamp,
    HealthMonitor,
    SnapshotSink,
    counter_payload,
    decode_snapshot,
    default_rules,
    encode_snapshot,
    export_perfetto,
    get_recorder,
    merge_payloads,
    merge_stamps,
    render_prometheus,
    snapshot_states,
    span,
)
from metrics_tpu.observability.freshness import IDENTITY
from metrics_tpu.observability.recorder import (
    SERIES_FRESHNESS_AGE_S,
    SERIES_READ_MS,
)
from metrics_tpu.observability.timeseries import TimeSeriesRegistry
from metrics_tpu.retrieval import RetrievalMAP
from metrics_tpu.sliced import SlicedMetric
from metrics_tpu.windowed import WindowedMetric

T0 = 1_000_000.0


@pytest.fixture
def recorder():
    """The default recorder, enabled for one test and ALWAYS disabled+reset
    after — the session-level conftest asserts nothing leaks."""
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


def read_events(rec, kind=None):
    evs = [e for e in rec.events() if e.get("type") == "read"]
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


# ----------------------------------------------------------------------
# the stamp itself: monoid laws, staleness semantics, payload round-trip
# ----------------------------------------------------------------------
class TestFreshnessStamp:
    def test_identity_and_commutativity(self):
        a = FreshnessStamp(min_event_t=10.0, max_event_t=20.0, async_age_s=1.0)
        b = FreshnessStamp(min_event_t=5.0, max_event_t=15.0, ring_span_s=3.0)
        assert a.merge(IDENTITY) == a and IDENTITY.merge(a) == a
        assert a.merge(b) == b.merge(a)
        m = a.merge(b)
        assert m.min_event_t == 5.0 and m.max_event_t == 20.0
        assert m.async_age_s == 1.0 and m.ring_span_s == 3.0

    def test_associativity(self):
        a = FreshnessStamp(min_event_t=10.0, max_event_t=20.0)
        b = FreshnessStamp(min_event_t=5.0, watermark_lag_s=2.0)
        c = FreshnessStamp(max_event_t=30.0, async_age_s=4.0)
        assert a.merge(b).merge(c) == a.merge(b.merge(c))
        assert merge_stamps([a, None, b, c]) == a.merge(b).merge(c)

    def test_staleness_components(self):
        s = FreshnessStamp(min_event_t=90.0, max_event_t=100.0, async_age_s=3.0,
                           watermark_lag_s=1.0)
        assert s.visible_age_s(now=107.0) == 7.0
        # visible age + max(async, watermark): components overlap, not add
        assert s.staleness_s(now=107.0) == 10.0
        assert IDENTITY.staleness_s(now=107.0) == 0.0 and IDENTITY.is_identity

    def test_payload_round_trip_and_missing_is_identity(self):
        s = FreshnessStamp(min_event_t=1.0, max_event_t=2.0, ring_span_s=0.5)
        assert FreshnessStamp.from_payload(s.to_payload()) == s
        assert FreshnessStamp.from_payload(None) == IDENTITY
        assert FreshnessStamp.from_payload({}) == IDENTITY


# ----------------------------------------------------------------------
# the typed read event, per entry point
# ----------------------------------------------------------------------
class TestReadEvents:
    def test_compute_cold_then_cache_hit(self, recorder):
        m = MeanMetric()
        m.update(jnp.ones((4,)))
        float(m.compute())              # cold fold
        float(m.compute())              # cached
        evs = read_events(recorder, "compute")
        assert [e["cache_hit"] for e in evs] == [False, True]
        assert all(e["metric"] == "MeanMetric" for e in evs)
        # ingested while enabled -> the stamp carries real event times
        assert evs[0].get("staleness_s") is not None
        totals = recorder.read_totals()
        assert totals["reads"] == 2 and totals["cache_hits"] == 1
        assert recorder.freshness_totals()["stamps"] == 2

    def test_disabled_read_path_records_nothing(self):
        rec = get_recorder()
        assert not rec.enabled
        m = MeanMetric()
        m.update(jnp.ones((4,)))
        float(m.compute())
        assert rec.events() == []
        assert rec.read_totals()["reads"] == 0

    def test_windowed_fold_counts_ring_buckets(self, recorder):
        wm = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=1)
        for err in (9.0, 9.0, 0.0, 0.0, 0.0):
            wm.update(jnp.array([err]), jnp.array([0.0]))
        wm.window_state(3)
        evs = read_events(recorder, "window")
        assert evs and evs[-1]["ring_buckets"] == 3
        assert evs[-1].get("ring_span_s", 0.0) >= 0.0
        # plain compute() goes through Metric.compute and picks the fold
        # size up via _read_extras — counted once, as a "compute" read
        float(wm.compute())
        cevs = read_events(recorder, "compute")
        assert cevs and cevs[-1]["ring_buckets"] == 3

    def test_sliced_subset_read(self, recorder):
        sm = SlicedMetric(MeanSquaredError(), num_slices=8)
        ids = jnp.asarray([0, 1, 2, 3])
        sm.update(ids, jnp.ones((4,)), jnp.zeros((4,)))
        sm.compute(slice_ids=jnp.asarray([1, 2]))
        evs = read_events(recorder, "sliced")
        assert len(evs) == 1
        # 2 selected slices x the wrapped metric's state leaves
        assert evs[0]["leaves"] == 2 * len(sm._template._defaults)
        assert evs[0].get("staleness_s") is not None

    def test_retrieval_table_rows(self, recorder):
        rm = RetrievalMAP()
        idx = jnp.asarray(np.repeat(np.arange(3), 5))
        preds = jnp.asarray(np.linspace(0.0, 1.0, 15, dtype=np.float32))
        target = jnp.asarray((np.arange(15) % 5 == 0).astype(np.int64))
        rm.update(preds, target, indexes=idx)
        float(rm.compute())
        evs = read_events(recorder, "compute")
        # the table packs one row per query group: 3 occupied rows unpacked
        assert evs and evs[-1]["table_rows"] == 3

    def test_fleet_fold_read(self, recorder, tmp_path):
        col = MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})
        col.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        sink.publish(states=snapshot_states(col), states_template=col, t=time.time())
        fleet = FleetCollector(
            str(tmp_path),
            template=MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()}),
            recorder=recorder,
        )
        fleet.poll()
        vals = fleet.fold_values()
        assert vals
        evs = read_events(recorder, "fleet")
        assert len(evs) == 1 and evs[0]["fanin"] == 1
        assert evs[0].get("watermark_lag_s", 0.0) >= 0.0
        assert recorder.read_totals()["max_fanin"] == 1


# ----------------------------------------------------------------------
# exposition: read/freshness families + strict-parser window histograms
# ----------------------------------------------------------------------
def parse_prometheus_strict(page):
    """A strict text-exposition parser: HELP/TYPE must precede their
    family's samples contiguously, histogram buckets must be cumulative
    with a terminal +Inf equal to _count. Returns {family: [(labels, v)]}."""
    families, types, current = {}, {}, None
    for line in page.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            current = line.split()[2]
            families.setdefault(current, [])
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[2] == current, f"TYPE {parts[2]} not under its HELP"
            types[parts[2]] = parts[3]
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        name_and_labels, value = line.rsplit(" ", 1)
        if "{" in name_and_labels:
            name, raw = name_and_labels.split("{", 1)
            labels = dict(
                kv.split("=", 1) for kv in raw.rstrip("}").split(",") if kv
            )
            labels = {k: v.strip('"') for k, v in labels.items()}
        else:
            name, labels = name_and_labels, {}
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
        assert base == current or name == current, (
            f"sample {name} interleaved outside its family block ({current})"
        )
        families.setdefault(base, []).append((name, labels, float(value)))
    return families, types


class TestExposition:
    def test_read_and_freshness_families(self, recorder):
        m = MeanMetric()
        m.update(jnp.ones((4,)))
        float(m.compute())
        float(m.compute())
        page = render_prometheus(recorder)
        assert 'metrics_tpu_read_total{' in page and 'cache="hit"' in page
        assert "metrics_tpu_read_seconds_total" in page
        assert 'metrics_tpu_read_folded_total{' in page
        assert "metrics_tpu_freshness_stamps_total" in page
        assert "metrics_tpu_freshness_staleness_seconds" in page
        parse_prometheus_strict(page)  # whole page must stay well-formed

    def test_window_histograms_strict(self, recorder):
        recorder.attach_timeseries(bucket_seconds=60.0, n_buckets=4, sketch_capacity=64)
        m = SumMetric()
        for _ in range(40):
            m.update(jnp.asarray(1.0))   # feeds the update_ms distribution
        page = render_prometheus(recorder)
        families, types = parse_prometheus_strict(page)
        assert types.get("metrics_tpu_window_hist") == "histogram"
        samples = families["metrics_tpu_window_hist"]
        buckets = [
            s for s in samples
            if s[0].endswith("_bucket") and s[1].get("series") == "update_ms"
        ]
        assert buckets, "update_ms histogram missing"
        les = [b[1]["le"] for b in buckets]
        assert les[-1] == "+Inf" and len(set(les)) == len(les)
        counts = [b[2] for b in buckets]
        assert counts == sorted(counts), "histogram buckets must be cumulative"
        count_rows = [
            s for s in samples
            if s[0].endswith("_count") and s[1].get("series") == "update_ms"
        ]
        assert count_rows and count_rows[0][2] == counts[-1] == 40.0

    def test_heterogeneous_fleet_merge(self, recorder):
        m = MeanMetric()
        m.update(jnp.ones((4,)))
        float(m.compute())
        new = counter_payload(recorder)
        assert new["read_totals"]["reads"] == 1 and new["freshness"]["stamps"] == 1
        old = {k: v for k, v in new.items() if k not in ("read_totals", "freshness")}
        old["process"] = 1
        merged = merge_payloads([new, old])
        # the v1 payload merges as identity: totals unchanged, not poisoned
        assert merged["read_totals"]["reads"] == 1
        fr = merged["freshness"]
        assert fr["stamps"] == 1
        assert fr["min_event_t"] == new["freshness"]["min_event_t"]
        assert fr["max_event_t"] == new["freshness"]["max_event_t"]
        # and the merged payload still renders a clean page (satellite 4
        # is pinned through BOTH merge_payloads and render_prometheus)
        page = render_prometheus(recorder, aggregate=merged)
        assert "metrics_tpu_read_total" in page
        parse_prometheus_strict(page)


# ----------------------------------------------------------------------
# wire v2 span header + collector clock-skew clamp + fleet perfetto
# ----------------------------------------------------------------------
def make_collection():
    return MetricCollection({"mse": MeanSquaredError()})


class TestWireAndCollector:
    def test_span_header_round_trip(self):
        ctx = {"span_id": 7, "parent_id": 3, "t": T0}
        blob = encode_snapshot(publisher="p0", seq=0, t=T0, span=ctx)
        snap = decode_snapshot(blob)
        assert snap.span == ctx

    def test_v1_snapshot_still_decodes(self):
        blob = encode_snapshot(publisher="p0", seq=0, t=T0, span={"span_id": 1, "t": T0})
        doc = json.loads(blob.decode("utf-8"))
        doc["schema"] = 1
        doc.pop("span")
        snap = decode_snapshot(json.dumps(doc).encode("utf-8"))
        assert snap.span is None and snap.publisher == "p0"

    def test_publish_captures_active_span(self, recorder, tmp_path):
        sink = SnapshotSink(str(tmp_path), publisher="p0")
        col = make_collection()
        col.update(jnp.ones((2,)), jnp.zeros((2,)))
        with span("publish_cycle"):
            sink.publish(states=snapshot_states(col), states_template=col)
        snap = decode_snapshot(open(sink.last_path, "rb").read())
        assert snap.span is not None and snap.span["span_id"] is not None

    def test_clock_skew_clamp(self, tmp_path):
        fleet = FleetCollector(
            str(tmp_path), template=make_collection(),
            clock=lambda: T0, max_skew_s=30.0, late_window_s=5.0,
        )
        sink = SnapshotSink(str(tmp_path), publisher="honest")
        rogue = SnapshotSink(str(tmp_path), publisher="rogue")
        col = make_collection()
        col.update(jnp.ones((2,)), jnp.zeros((2,)))
        # rogue clock runs 10 minutes ahead; unclamped it would place the
        # watermark at T0+600-late_window and late-drop the honest peer
        rogue.publish(states=snapshot_states(col), states_template=col, t=T0 + 600.0)
        sink.publish(states=snapshot_states(col), states_template=col, t=T0)
        fleet.poll(now=T0)
        totals = fleet.totals()
        assert totals["clock_skew_clamps"] == 1
        assert totals["absorbed"] == 2 and totals["late_dropped"] == 0
        assert fleet.watermark <= T0 + fleet.max_skew_s
        page = "\n".join(fleet.prometheus_lines(now=T0))
        assert "metrics_tpu_fleet_clock_skew_clamps_total 1" in page
        assert "metrics_tpu_fleet_clock_skew_seconds 600" in page

    def test_fleet_perfetto_flow_arrows(self, recorder, tmp_path):
        qdir = tmp_path / "q"
        qdir.mkdir()
        sink = SnapshotSink(str(qdir), publisher="p0")
        col = make_collection()
        col.update(jnp.ones((2,)), jnp.zeros((2,)))
        with span("publish_cycle"):
            sink.publish(states=snapshot_states(col), states_template=col)
        fleet = FleetCollector(str(qdir), template=make_collection(), recorder=recorder)
        fleet.poll()
        assert "p0" in fleet.publisher_spans()
        fleet.fold_values()  # emits the linked fleet_fold span
        out = tmp_path / "trace.json"
        assert export_perfetto(str(out), collector=fleet) == str(out)
        doc = json.loads(out.read_text())
        evs = doc["traceEvents"]
        procs = {e["args"]["name"] for e in evs if e.get("name") == "process_name"}
        assert "publisher p0" in procs
        starts = [e for e in evs if e.get("ph") == "s" and e.get("name") == "publish->fold"]
        ends = [e for e in evs if e.get("ph") == "f" and e.get("name") == "publish->fold"]
        assert len(starts) == 1 and len(ends) == 1
        assert starts[0]["id"] == ends[0]["id"]          # one paired flow
        assert starts[0]["pid"] != ends[0]["pid"]        # crosses processes


# ----------------------------------------------------------------------
# the alarm classes: freshness_slo + read_latency fire AND clear
# ----------------------------------------------------------------------
class TestFreshnessAlarms:
    def test_default_rules_cover_thirteen_classes(self):
        rules = default_rules()
        names = {r.name for r in rules}
        assert {"freshness_slo", "read_latency"} <= names
        assert len(rules) == 15  # 13 classes; queue + freshness have companions

    def test_fire_and_clear(self):
        registry = TimeSeriesRegistry(bucket_seconds=1.0, n_buckets=60)
        monitor = HealthMonitor(
            default_rules(freshness_bound_s=5.0, read_latency_limit_ms=100.0),
            registry=registry,
        )
        t0 = T0
        for i in range(6):
            registry.observe(SERIES_FRESHNESS_AGE_S, 30.0, t=t0 + i)   # stale reads
            registry.observe(SERIES_READ_MS, 500.0, t=t0 + i)          # slow reads
        snap = monitor.evaluate(now=t0 + 6)
        firing = {a.name for a in snap.firing}
        assert {"freshness_slo", "read_latency"} <= firing
        # recovery: fresh fast reads, old window rolls off
        for i in range(6):
            registry.observe(SERIES_FRESHNESS_AGE_S, 0.1, t=t0 + 62 + i)
            registry.observe(SERIES_READ_MS, 1.0, t=t0 + 62 + i)
        snap = monitor.evaluate(now=t0 + 68)
        assert snap.status == "ok"
        assert {"freshness_slo", "read_latency"} <= set(monitor.fired_and_cleared())
