"""Reference-parity sweep over the full classification input grid.

Mirrors the breadth of the reference's big per-metric files
(/root/reference/tests/classification/test_{f_beta,specificity,accuracy,
precision_recall}.py: every input case x average x mdmc_average), using the
reference implementation itself as the oracle (helpers/reference.py — the
strongest available ground truth for the canonicalization corners sklearn
wrappers can't express, e.g. samplewise mdmc, logits auto-sigmoid, top-k).
Each combo runs the full class lifecycle (per-batch forward value,
accumulated compute, virtual-rank merge, jit) plus the per-step
dist_sync_on_step semantics on a subset.
"""
from functools import partial

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.classification import Accuracy, FBetaScore, Precision, Recall, Specificity
from metrics_tpu.functional import fbeta_score as mt_fbeta
from metrics_tpu.functional import specificity as mt_specificity
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_binary_prob_plausible,
    _input_multiclass,
    _input_multiclass_logits,
    _input_multiclass_prob,
    _input_multiclass_with_missing_class,
    _input_multidim_multiclass,
    _input_multidim_multiclass_prob,
    _input_multilabel,
    _input_multilabel_logits,
    _input_multilabel_no_match,
    _input_multilabel_prob,
    _input_multilabel_prob_plausible,
)
from tests.helpers.reference import assert_accumulated_parity, ref_oracle as _ref_oracle
from tests.helpers.testers import NUM_CLASSES, MetricTester

torch = pytest.importorskip("torch")


# every input case in the reference grid, with the arguments its shape needs
# (the reference parametrization passes multiclass=False for the integer
# binary/multilabel fixtures so they are not re-deduced as multiclass).
# (name, fixture, needs_mdmc, extra_args)
INPUT_CASES = [
    ("binary_prob", _input_binary_prob, False, {}),
    ("binary", _input_binary, False, {"multiclass": False}),
    ("binary_logits", _input_binary_logits, False, {}),
    ("binary_prob_plausible", _input_binary_prob_plausible, False, {}),
    ("multilabel_prob", _input_multilabel_prob, False, {}),
    ("multilabel_logits", _input_multilabel_logits, False, {}),
    ("multilabel", _input_multilabel, False, {"multiclass": False}),
    ("multilabel_no_match", _input_multilabel_no_match, False, {"multiclass": False}),
    ("multilabel_prob_plausible", _input_multilabel_prob_plausible, False, {}),
    ("multiclass_prob", _input_multiclass_prob, False, {}),
    ("multiclass_logits", _input_multiclass_logits, False, {}),
    ("multiclass", _input_multiclass, False, {}),
    ("multiclass_missing_class", _input_multiclass_with_missing_class, False, {}),
    ("mdmc_prob", _input_multidim_multiclass_prob, True, {}),
    ("mdmc", _input_multidim_multiclass, True, {}),
]

AVERAGES = ["micro", "macro", "weighted", "none"]


def _case_args(case_name, average, mdmc_average, extra):
    """Constructor/functional args for a fixture, mirroring the reference
    test parametrization (num_classes where the case needs it)."""
    args = {"average": average, **extra}
    if case_name.startswith(("multiclass", "mdmc")):
        args["num_classes"] = NUM_CLASSES
    elif case_name.startswith("multilabel") and (average != "micro" or extra):
        args["num_classes"] = NUM_CLASSES
    elif case_name.startswith("binary") and (average != "micro" or extra):
        # binary is one class for the StatScores spine (reference grid passes
        # num_classes=1 for every binary fixture)
        args["num_classes"] = 1
    if mdmc_average is not None:
        args["mdmc_average"] = mdmc_average
    return args


def _iter_grid():
    for case_name, fixture, needs_mdmc, extra in INPUT_CASES:
        for average in AVERAGES:
            mdmcs = ["global", "samplewise"] if needs_mdmc else [None]
            for mdmc in mdmcs:
                yield case_name, fixture, average, mdmc, extra


GRID = list(_iter_grid())
GRID_IDS = [
    f"{case}-{avg}" + (f"-{mdmc}" if mdmc else "") for case, _, avg, mdmc, _e in GRID
]


@pytest.mark.parametrize("case_name, fixture, average, mdmc_average, extra", GRID, ids=GRID_IDS)
class TestFBeta2ReferenceGrid(MetricTester):
    atol = 1e-6

    def test_fbeta2(self, case_name, fixture, average, mdmc_average, extra):
        args = _case_args(case_name, average, mdmc_average, extra)
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=partial(FBetaScore, beta=2.0),
            sk_metric=_ref_oracle("fbeta_score", beta=2.0, **args),
            metric_args=args,
            # per-step cross-rank sync semantics on the plain-prob cases
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_fbeta2_functional(self, case_name, fixture, average, mdmc_average, extra):
        args = _case_args(case_name, average, mdmc_average, extra)
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_fbeta,
            sk_metric=_ref_oracle("fbeta_score", beta=2.0, **args),
            metric_args={"beta": 2.0, **args},
            atol=1e-6,
        )


@pytest.mark.parametrize("case_name, fixture, average, mdmc_average, extra", GRID, ids=GRID_IDS)
class TestSpecificityReferenceGrid(MetricTester):
    atol = 1e-6

    def test_specificity(self, case_name, fixture, average, mdmc_average, extra):
        args = _case_args(case_name, average, mdmc_average, extra)
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=Specificity,
            sk_metric=_ref_oracle("specificity", **args),
            metric_args=args,
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_specificity_functional(self, case_name, fixture, average, mdmc_average, extra):
        args = _case_args(case_name, average, mdmc_average, extra)
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_specificity,
            sk_metric=_ref_oracle("specificity", **args),
            metric_args=args,
            atol=1e-6,
        )


# ---------------------------------------------------------------------------
# Accuracy: the reference grid's extra axes (subset_accuracy, top_k,
# ignore_index) on top of the shared input cases
# ---------------------------------------------------------------------------

ACC_CASES = [
    ("binary_prob", _input_binary_prob),
    ("binary_logits", _input_binary_logits),
    ("multilabel_prob", _input_multilabel_prob),
    ("multilabel_no_match", _input_multilabel_no_match),
    ("multiclass_prob", _input_multiclass_prob),
    ("multiclass_logits", _input_multiclass_logits),
    ("mdmc_prob", _input_multidim_multiclass_prob),
    ("mdmc", _input_multidim_multiclass),
]


@pytest.mark.parametrize("case_name, fixture", ACC_CASES, ids=[c for c, _ in ACC_CASES])
@pytest.mark.parametrize("subset_accuracy", [False, True])
class TestAccuracyReferenceGrid(MetricTester):
    atol = 1e-6

    def test_accuracy(self, case_name, fixture, subset_accuracy):
        args = {"subset_accuracy": subset_accuracy}
        if case_name.startswith("mdmc"):
            args["mdmc_average"] = "global"
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=Accuracy,
            sk_metric=_ref_oracle("accuracy", **args),
            metric_args=args,
            dist_sync_on_step=case_name.endswith("_prob"),
        )


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("average", AVERAGES)
def test_accuracy_topk_reference_grid(top_k, average):
    args = {"top_k": top_k, "average": average, "num_classes": NUM_CLASSES}
    assert_accumulated_parity(Accuracy(**args), _input_multiclass_prob, _ref_oracle("accuracy", **args))


@pytest.mark.parametrize("metric_class, ref_name", [(Precision, "precision"), (Recall, "recall")])
@pytest.mark.parametrize("average", AVERAGES)
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
class TestPrecisionRecallMdmcReferenceGrid(MetricTester):
    """The mdmc x average corner the sklearn-oracle files could not cover."""

    atol = 1e-6

    def test_precision_recall_mdmc(self, metric_class, ref_name, average, mdmc_average):
        fixture = _input_multidim_multiclass_prob
        args = {"average": average, "mdmc_average": mdmc_average, "num_classes": NUM_CLASSES}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=metric_class,
            sk_metric=_ref_oracle(ref_name, **args),
            metric_args=args,
        )


# ---------------------------------------------------------------------------
# ignore_index sweep (reference test_{precision_recall,accuracy}.py
# parametrize ignore_index over [None, 0])
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("metric_class, ref_name", [
    (Precision, "precision"),
    (Recall, "recall"),
    (partial(FBetaScore, beta=0.5), "fbeta_score"),
    (Accuracy, "accuracy"),
])
@pytest.mark.parametrize("average", ["micro", "macro"])
def test_ignore_index_parity(metric_class, ref_name, average):
    fixture = _input_multiclass_prob
    args = {"average": average, "num_classes": NUM_CLASSES, "ignore_index": 0}
    ref_kwargs = dict(args)
    if ref_name == "fbeta_score":
        ref_kwargs["beta"] = 0.5
    assert_accumulated_parity(metric_class(**args), fixture, _ref_oracle(ref_name, **ref_kwargs))
