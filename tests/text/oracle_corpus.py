"""Corpus for the text stored-oracle fixtures — shared by the generator
(scripts/make_text_audio_oracle.py) and tests/text/test_stored_oracle.py.

Extends the MT fixture corpus (tests/text/inputs.py) with sentences that
make EVERY swept argument axis discriminative — the base corpus is
all-lowercase punctuation-free ASCII, on which tokenizer choice, lowercase,
no_punctuation, and normalize are all no-ops and would pin nothing:

- mixed case (lowercase axis),
- punctuation incl. attached/detached variants (13a/intl tokenizers, TER
  no_punctuation and normalize),
- non-ASCII accents and CJK (zh vs intl vs none tokenizers),
- numbers with separators (13a vs intl number handling).
"""
from tests.text.inputs import _inputs_multiple_references

_EXTRA = [
    (
        'The Quick-Witted Fox said: "Hello, World!" — twice.',
        [
            'the quick-witted fox said "hello, world" twice.',
            "The Quick-Witted Fox said: 'hello, world!' - twice.",
        ],
    ),
    (
        "Dr. Müller paid 1,234.56 € for the café's naïve décor on 2021-03-04.",
        [
            "Dr. Müller paid 1,234.56 euros for the cafe's naive decor on 2021-03-04.",
            "doctor müller paid €1234.56 for the café's naïve décor.",
        ],
    ),
    (
        "他说这个模型很快, and I Agree 100%!",
        [
            "他说这个模型非常快, and i agree 100%.",
            "He said this model is very fast, and I agree 100%!",
        ],
    ),
]


def flat_corpus():
    """(preds, targets): the flattened base MT corpus plus the
    axis-discriminative extension sentences."""
    preds = [p for batch in _inputs_multiple_references.preds for p in batch]
    targets = [t for batch in _inputs_multiple_references.targets for t in batch]
    for hyp, refs in _EXTRA:
        preds.append(hyp)
        targets.append(refs)
    return preds, targets


def engine_scores():
    """Our engines over the corpus — the ONE definition of the swept grid,
    shared by the fixture generator (scripts/make_text_audio_oracle.py) and
    the drift-pin test so the two cannot diverge."""
    from metrics_tpu.functional.text import (
        chrf_score,
        extended_edit_distance,
        sacre_bleu_score,
        translation_edit_rate,
    )

    preds, targets = flat_corpus()
    out = {}
    for tokenize in ("none", "13a", "zh", "intl", "char"):
        for lowercase in (False, True):
            out[f"sacrebleu_{tokenize}_lc{int(lowercase)}"] = float(
                sacre_bleu_score(preds, targets, tokenize=tokenize, lowercase=lowercase)
            )
    for normalize in (False, True):
        for no_punct in (False, True):
            for lowercase in (False, True):
                key = f"ter_norm{int(normalize)}_nopunct{int(no_punct)}_lc{int(lowercase)}"
                out[key] = float(
                    translation_edit_rate(
                        preds,
                        targets,
                        normalize=normalize,
                        no_punctuation=no_punct,
                        lowercase=lowercase,
                    )
                )
    out["chrf"] = float(chrf_score(preds, targets, n_word_order=0))
    out["chrfpp"] = float(chrf_score(preds, targets))
    out["chrf_lc"] = float(chrf_score(preds, targets, n_word_order=0, lowercase=True))
    out["eed"] = float(extended_edit_distance(preds, targets))
    return out
