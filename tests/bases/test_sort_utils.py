"""Unit contract for ``stable_sort_with_payloads`` (utils/data.py) — the
shared TPU sort-layout convention behind the AUROC rank kernel, the
retrieval row sort, and the exact-curve cumulants. Pinned here once so the
three call sites can rely on one tested definition."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.utils.data import stable_sort_with_payloads


def test_ascending_matches_stable_argsort():
    rng = np.random.default_rng(0)
    key = np.round(rng.random(64), 1).astype(np.float32)  # heavy ties
    payload = rng.random(64).astype(np.float32)
    sk, sp = stable_sort_with_payloads(jnp.asarray(key), jnp.asarray(payload))
    order = np.argsort(key, kind="stable")
    np.testing.assert_array_equal(np.asarray(sk), key[order])
    np.testing.assert_array_equal(np.asarray(sp), payload[order])


def test_descending_matches_stable_argsort_of_negated_key():
    rng = np.random.default_rng(1)
    key = np.round(rng.random(64), 1).astype(np.float32)
    payload = np.arange(64, dtype=np.int32)
    sk, sp = stable_sort_with_payloads(
        jnp.asarray(key), jnp.asarray(payload), descending=True
    )
    order = np.argsort(-key, kind="stable")
    np.testing.assert_array_equal(np.asarray(sk), key[order])
    # within ties the ORIGINAL order is preserved (stability), visible in
    # the index payload
    np.testing.assert_array_equal(np.asarray(sp), payload[order])


def test_bool_payloads_round_trip_and_minor_axis_batching():
    rng = np.random.default_rng(2)
    key = rng.random((5, 32)).astype(np.float32)
    flag = rng.random((5, 32)) < 0.5
    sk, sf = stable_sort_with_payloads(
        jnp.asarray(key), jnp.asarray(flag), descending=True
    )
    assert sf.dtype == jnp.bool_
    for r in range(5):
        order = np.argsort(-key[r], kind="stable")
        np.testing.assert_array_equal(np.asarray(sk)[r], key[r][order])
        np.testing.assert_array_equal(np.asarray(sf)[r], flag[r][order])


def test_multiple_payloads_and_inf_padding():
    key = jnp.asarray([0.5, -jnp.inf, 0.9, -jnp.inf, 0.1])
    a = jnp.asarray([0, 1, 2, 3, 4])
    b = jnp.asarray([True, False, True, False, True])
    sk, sa, sb = stable_sort_with_payloads(key, a, b, descending=True)
    np.testing.assert_array_equal(np.asarray(sa), [2, 0, 4, 1, 3])  # -inf last, stable
    np.testing.assert_array_equal(np.asarray(sb), [True, True, True, False, False])
    assert np.asarray(sk)[0] == pytest.approx(0.9)
    assert np.isneginf(np.asarray(sk)[-1])


def test_descending_rejects_unsigned_and_bool_keys():
    """Negation-based descending order is undefined for unsigned keys (wraps
    modulo 2**n); the dtype guard must reject them up front instead of
    silently mis-sorting (ADVICE round 5)."""
    payload = jnp.arange(4)
    for bad in (jnp.asarray([1, 2, 3, 0], jnp.uint32), jnp.asarray([True, False, True, False])):
        with pytest.raises(ValueError, match="signed-integer"):
            stable_sort_with_payloads(bad, payload, descending=True)
    # ascending keeps accepting any sortable dtype
    sk, _ = stable_sort_with_payloads(jnp.asarray([3, 1, 2], jnp.uint32), jnp.arange(3))
    np.testing.assert_array_equal(np.asarray(sk), [1, 2, 3])
    # signed ints (sans INT_MIN, per the documented contract) stay supported
    sk, sp = stable_sort_with_payloads(
        jnp.asarray([3, -5, 2], jnp.int32), jnp.arange(3), descending=True
    )
    np.testing.assert_array_equal(np.asarray(sk), [3, 2, -5])
    np.testing.assert_array_equal(np.asarray(sp), [0, 2, 1])
