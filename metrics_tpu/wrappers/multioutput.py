"""MultioutputWrapper — one metric clone per output dimension.

Behavior parity with /root/reference/torchmetrics/wrappers/multioutput.py:11-152.
"""
from copy import deepcopy
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric, _coerce_foreign
from metrics_tpu.utils.data import apply_to_collection

Array = jax.Array


def _get_nan_indices(*arrays: Array) -> Array:
    """Boolean mask of rows (dim 0) that contain NaNs in any input."""
    if len(arrays) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = arrays[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for a in arrays:
        flattened = a.reshape(len(a), -1).astype(jnp.float32)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(flattened), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """Evaluates one clone of ``base_metric`` per output along ``output_dim``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> target = jnp.array([[0.5, 1.0], [-1.0, 1.0], [7.0, -6.0]])
        >>> preds = jnp.array([[0.0, 2.0], [-1.0, 2.0], [8.0, -5.0]])
        >>> r2score = MultioutputWrapper(R2Score(), 2)
        >>> [round(float(v), 4) for v in r2score(preds, target)]
        [0.9654, 0.9082]
    """

    #: delegates to the child metric's full eager lifecycle (telemetry,
    #: coercion); the child registry already excludes it from fusion
    __jit_unsafe__ = True

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
    ) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple[list, dict]]:
        # this wrapper slices raw inputs BEFORE any child update runs, so the
        # torch-input coercion must happen here too (a direct .forward() call
        # bypasses __call__'s pass; coercion is a no-op on jax arrays)
        args = _coerce_foreign(args)
        kwargs = _coerce_foreign(kwargs)
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            def select(x, idx=i):
                return jnp.take(x, jnp.asarray([idx]), axis=self.output_dim)

            selected_args = list(apply_to_collection(args, jnp.ndarray, select))
            selected_kwargs = apply_to_collection(kwargs, jnp.ndarray, select)
            if self.remove_nans:
                args_kwargs = tuple(selected_args) + tuple(selected_kwargs.values())
                nan_idxs = np.asarray(_get_nan_indices(*args_kwargs))
                selected_args = [arg[~nan_idxs] for arg in selected_args]
                selected_kwargs = {k: v[~nan_idxs] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [jnp.squeeze(arg, axis=self.output_dim) for arg in selected_args]
                selected_kwargs = {k: jnp.squeeze(v, axis=self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def _update(self, *args: Any, **kwargs: Any) -> None:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def _compute(self) -> List[Array]:
        return [m.compute() for m in self.metrics]

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        results = []
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            results.append(metric(*selected_args, **selected_kwargs))
        if results[0] is None:
            return None
        return results

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()
