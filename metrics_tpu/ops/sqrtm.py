"""Device-side matrix square root: Newton–Schulz ``trace(sqrtm(Σ₁Σ₂))``.

FID's only non-streaming step is the Fréchet cross term
``tr((Σ₁Σ₂)^{1/2})``. The reference implementation hops to the host for
``scipy.linalg.sqrtm`` — a full Schur decomposition in float64 — which
serializes ``compute()`` behind a device→host→device round trip and a
LAPACK call. But the trace of the square root does not need a
decomposition: the coupled Newton–Schulz iteration

    ``Y₀ = A/‖A‖_F``, ``Z₀ = I``
    ``T  = (3I − Z Y)/2``;  ``Y ← Y T``;  ``Z ← T Z``

converges quadratically to ``Y → A^{1/2}/‖A‖_F^{1/2}`` whenever
``‖I − A/‖A‖_F‖ < 1`` — guaranteed here because ``A = Σ₁Σ₂`` is a
product of PSD matrices (real non-negative spectrum, similar to a PSD
matrix, and the normalization puts its spectrum in ``(0, 1]``). Each
step is two ``[d, d]`` matmuls: MXU-native, fusible into the same jit
program as the covariance identity, no host sync.

Registered as the jnp-only dispatch op ``trace_sqrtm`` so the routing
policy / kill switch / dispatch counters apply and a Pallas kernel can
be slotted in later without touching callers. Accuracy against the host
eigendecomposition is pinned by ``newton_schulz_abs_err`` in the
``bench.py image_detection`` gate and in ``tests/ops``; callers needing
certified float64 semantics use the metric-level ``exact=True`` hatch
(which routes to the host path), not this op.
"""
from functools import partial

import jax
import jax.numpy as jnp

from metrics_tpu.ops.dispatch import dispatch, register_kernel

Array = jax.Array

#: Newton–Schulz step count: quadratic convergence makes 20 steps ample
#: for float32 on Inception-scale (2048²) covariance products; the bench
#: gate pins the realized error against the host eigendecomposition.
NEWTON_SCHULZ_ITERS = 20


@partial(jax.jit, static_argnums=2)
def _trace_sqrtm_ns(sigma1: Array, sigma2: Array, iters: int = NEWTON_SCHULZ_ITERS) -> Array:
    """``tr((Σ₁Σ₂)^{1/2})`` by coupled Newton–Schulz; float32 in/out."""
    a = jnp.asarray(sigma1, jnp.float32) @ jnp.asarray(sigma2, jnp.float32)
    d = a.shape[0]
    norm = jnp.sqrt(jnp.sum(a * a))
    norm = jnp.maximum(norm, jnp.finfo(jnp.float32).tiny)
    eye = jnp.eye(d, dtype=jnp.float32)
    y, z = a / norm, eye

    def step(carry, _):
        y, z = carry
        t = 0.5 * (3.0 * eye - z @ y)
        return (y @ t, t @ z), None

    (y, _), _ = jax.lax.scan(step, (y, z), None, length=iters)
    return jnp.trace(y) * jnp.sqrt(norm)


register_kernel("trace_sqrtm", pallas_fn=None, jnp_fn=_trace_sqrtm_ns)


def trace_sqrtm_dispatch(sigma1: Array, sigma2: Array, iters: int = NEWTON_SCHULZ_ITERS) -> Array:
    """Dispatched ``tr((Σ₁Σ₂)^{1/2})`` for PSD ``Σ₁``, ``Σ₂`` (see module
    docstring; jnp-only today, counted under op ``trace_sqrtm``)."""
    return dispatch("trace_sqrtm", sigma1, sigma2, iters)
