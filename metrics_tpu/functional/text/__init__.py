from metrics_tpu.functional.text.bleu import bleu_score  # noqa: F401
from metrics_tpu.functional.text.cer import char_error_rate  # noqa: F401
from metrics_tpu.functional.text.mer import match_error_rate  # noqa: F401
from metrics_tpu.functional.text.rouge import rouge_score  # noqa: F401
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score  # noqa: F401
from metrics_tpu.functional.text.wer import word_error_rate  # noqa: F401
from metrics_tpu.functional.text.wil import word_information_lost  # noqa: F401
from metrics_tpu.functional.text.wip import word_information_preserved  # noqa: F401
