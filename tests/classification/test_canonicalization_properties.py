"""Property-based fuzzing of the input-canonicalization layer (hypothesis).

The deduction/canonicalization code (utils/checks.py) is the one component
every classification metric flows through; these properties must hold for
ANY valid input, not just the fixture grid:

- idempotence: re-formatting an already-canonical (N, C) int pair is stable;
- outputs are always binary int arrays of rank 2 or 3;
- the deduced case is stable under batch slicing;
- to_onehot/select_topk structural invariants.
"""
import numpy as np
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import select_topk, to_onehot

_settings = settings(max_examples=60, deadline=None)


@st.composite
def _multiclass_prob_inputs(draw):
    n = draw(st.integers(2, 12))
    c = draw(st.integers(2, 6))
    preds = draw(
        st.lists(st.lists(st.floats(0.01, 0.99), min_size=c, max_size=c), min_size=n, max_size=n)
    )
    target = draw(st.lists(st.integers(0, c - 1), min_size=n, max_size=n))
    return np.asarray(preds, np.float32), np.asarray(target, np.int32)


@given(_multiclass_prob_inputs())
@_settings
def test_canonical_outputs_are_binary_int(data):
    preds, target = data
    p, t, mode = _input_format_classification(jnp.asarray(preds), jnp.asarray(target))
    p, t = np.asarray(p), np.asarray(t)
    assert p.dtype == np.int32 and t.dtype == np.int32
    assert set(np.unique(p)) <= {0, 1} and set(np.unique(t)) <= {0, 1}
    assert p.shape == t.shape
    assert p.ndim in (2, 3)
    # exactly one predicted class per sample (top-1 on prob inputs)
    assert (p.sum(axis=1) == 1).all()
    assert (t.sum(axis=1) == 1).all()


@given(_multiclass_prob_inputs())
@_settings
def test_canonical_form_preserves_semantics(data):
    """The canonical one-hot form encodes exactly the top-1 prediction and
    the true label — no information is reshuffled. (True idempotence does
    NOT hold: the deduction table deliberately re-one-hots (N, 2) int inputs
    under multiclass=True, same as the reference.)"""
    preds, target = data
    p, t, _ = _input_format_classification(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_array_equal(np.argmax(np.asarray(p), axis=1), np.argmax(preds, axis=1))
    np.testing.assert_array_equal(np.argmax(np.asarray(t), axis=1), target)


@given(_multiclass_prob_inputs())
@_settings
def test_case_deduction_stable_under_slicing(data):
    preds, target = data
    if len(preds) < 4:
        return
    _, _, full_mode = _input_format_classification(jnp.asarray(preds), jnp.asarray(target))
    _, _, half_mode = _input_format_classification(
        jnp.asarray(preds[: len(preds) // 2]), jnp.asarray(target[: len(target) // 2])
    )
    assert full_mode == half_mode


@given(st.integers(2, 10), st.integers(1, 40))
@_settings
def test_to_onehot_roundtrip(num_classes, n):
    rng = np.random.default_rng(n * 100 + num_classes)
    labels = rng.integers(0, num_classes, n)
    onehot = np.asarray(to_onehot(jnp.asarray(labels), num_classes))
    assert onehot.shape == (n, num_classes)
    assert (onehot.sum(axis=1) == 1).all()
    np.testing.assert_array_equal(np.argmax(onehot, axis=1), labels)


@given(st.integers(2, 6), st.integers(2, 20), st.integers(1, 3))
@_settings
def test_select_topk_invariants(num_classes, n, k):
    if k > num_classes:
        return
    rng = np.random.default_rng(n * 7 + num_classes + k)
    probs = rng.random((n, num_classes)).astype(np.float32)
    mask = np.asarray(select_topk(jnp.asarray(probs), k))
    assert mask.shape == probs.shape
    assert (mask.sum(axis=1) == k).all()
    # selected entries dominate unselected ones row-wise
    for row_probs, row_mask in zip(probs, mask):
        if 0 < row_mask.sum() < num_classes:
            assert row_probs[row_mask == 1].min() >= row_probs[row_mask == 0].max()
