"""LPIPS parity: torch mirror of the `lpips` package (exact state_dict key
layout) vs the Flax net through ``convert_lpips_weights``.

The reference wraps the `lpips` torch package (whose pretrained weights need
a download this environment cannot perform), so conversion correctness is
proven on randomly initialized weights — same approach as the FID Inception
test — and the metric math (scaling layer, channel-normalized squared
diffs, 1x1 heads, spatial average, sum over stages) is checked end to end.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as F
from torch import nn as tnn

import jax.numpy as jnp

from metrics_tpu.image.lpip import LearnedPerceptualImagePatchSimilarity
from metrics_tpu.models.lpips import LPIPSNet, build_lpips, convert_lpips_weights

# torchvision-style feature stacks (indices match the lpips package slicing)
_ALEX_FEATURES = [
    tnn.Conv2d(3, 64, 11, stride=4, padding=2), tnn.ReLU(),          # 0, 1   | slice1: 0-1
    tnn.MaxPool2d(3, 2), tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),   # 2-4  | slice2: 2-4
    tnn.MaxPool2d(3, 2), tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),  # 5-7  | slice3: 5-7
    tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),                   # 8-9   | slice4: 8-9
    tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),                   # 10-11 | slice5: 10-11
]
_ALEX_SLICES = [(0, 2), (2, 5), (5, 8), (8, 10), (10, 12)]
_ALEX_CHANNELS = [64, 192, 384, 256, 256]

_VGG_FEATURES = [
    tnn.Conv2d(3, 64, 3, padding=1), tnn.ReLU(), tnn.Conv2d(64, 64, 3, padding=1), tnn.ReLU(),  # 0-3 | slice1
    tnn.MaxPool2d(2, 2), tnn.Conv2d(64, 128, 3, padding=1), tnn.ReLU(),
    tnn.Conv2d(128, 128, 3, padding=1), tnn.ReLU(),  # 4-8 | slice2
    tnn.MaxPool2d(2, 2), tnn.Conv2d(128, 256, 3, padding=1), tnn.ReLU(),
    tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(), tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),  # 9-15 | slice3
    tnn.MaxPool2d(2, 2), tnn.Conv2d(256, 512, 3, padding=1), tnn.ReLU(),
    tnn.Conv2d(512, 512, 3, padding=1), tnn.ReLU(), tnn.Conv2d(512, 512, 3, padding=1), tnn.ReLU(),  # 16-22 | slice4
    tnn.MaxPool2d(2, 2), tnn.Conv2d(512, 512, 3, padding=1), tnn.ReLU(),
    tnn.Conv2d(512, 512, 3, padding=1), tnn.ReLU(), tnn.Conv2d(512, 512, 3, padding=1), tnn.ReLU(),  # 23-29 | slice5
]
_VGG_SLICES = [(0, 4), (4, 9), (9, 16), (16, 23), (23, 30)]
_VGG_CHANNELS = [64, 128, 256, 512, 512]


class _NetLinLayer(tnn.Module):
    def __init__(self, channels):
        super().__init__()
        self.model = tnn.Sequential(tnn.Dropout(), tnn.Conv2d(channels, 1, 1, bias=False))


class _Slices(tnn.Module):
    """Holds slice1..slice5 with GLOBAL feature indices as submodule names
    (the lpips package's add_module(str(global_idx), ...) convention)."""

    def __init__(self, features, slices):
        super().__init__()
        for k, (lo, hi) in enumerate(slices):
            seq = tnn.Sequential()
            for idx in range(lo, hi):
                seq.add_module(str(idx), features[idx])
            setattr(self, f"slice{k + 1}", seq)


class TorchLPIPS(tnn.Module):
    def __init__(self, net_type):
        super().__init__()
        features = _ALEX_FEATURES if net_type == "alex" else _VGG_FEATURES
        slices = _ALEX_SLICES if net_type == "alex" else _VGG_SLICES
        channels = _ALEX_CHANNELS if net_type == "alex" else _VGG_CHANNELS
        self.net = _Slices(features, slices)
        for k, c in enumerate(channels):
            setattr(self, f"lin{k}", _NetLinLayer(c))
        self.register_buffer("shift", torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1))
        self.register_buffer("scale", torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1))
        self.num_slices = len(slices)

    @staticmethod
    def _normalize(feat):
        norm = torch.sqrt(torch.sum(feat**2, dim=1, keepdim=True))
        return feat / (norm + 1e-10)

    def forward(self, img1, img2):
        x1 = (img1 - self.shift) / self.scale
        x2 = (img2 - self.shift) / self.scale
        total = 0.0
        for k in range(self.num_slices):
            block = getattr(self.net, f"slice{k + 1}")
            x1, x2 = block(x1), block(x2)
            diff = (self._normalize(x1) - self._normalize(x2)) ** 2
            head = getattr(self, f"lin{k}").model(diff)
            total = total + head.mean(dim=(2, 3))
        return total[:, 0]


@pytest.fixture(scope="module", params=["alex", "vgg"])
def lpips_pair(request, tmp_path_factory):
    net_type = request.param
    torch.manual_seed(1)
    net = TorchLPIPS(net_type).eval()
    with torch.no_grad():  # random but reasonable head weights
        for k in range(5):
            getattr(net, f"lin{k}").model[1].weight.uniform_(0.0, 0.2)
    variables = convert_lpips_weights(net.state_dict(), net_type)
    path = tmp_path_factory.mktemp("lpips") / f"{net_type}.npz"
    np.savez(path, variables=np.asarray(variables, dtype=object))
    return net_type, net, str(path)


def test_lpips_conversion_parity(lpips_pair):
    net_type, torch_net, path = lpips_pair
    rng = np.random.RandomState(0)
    img1 = (rng.rand(2, 3, 64, 64) * 2 - 1).astype(np.float32)
    img2 = (rng.rand(2, 3, 64, 64) * 2 - 1).astype(np.float32)

    with torch.no_grad():
        want = torch_net(torch.from_numpy(img1), torch.from_numpy(img2)).numpy()
    scorer = build_lpips(net_type, path)
    got = np.asarray(scorer(jnp.asarray(img1), jnp.asarray(img2)))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_lpips_metric_accumulates(lpips_pair):
    net_type, torch_net, path = lpips_pair
    rng = np.random.RandomState(1)
    img1 = jnp.asarray((rng.rand(4, 3, 64, 64) * 2 - 1).astype(np.float32))
    img2 = jnp.asarray((rng.rand(4, 3, 64, 64) * 2 - 1).astype(np.float32))

    metric = LearnedPerceptualImagePatchSimilarity(net_type=net_type, net_weights_path=path)
    metric.update(img1[:2], img2[:2])
    metric.update(img1[2:], img2[2:])
    with torch.no_grad():
        want = torch_net(torch.from_numpy(np.asarray(img1)), torch.from_numpy(np.asarray(img2))).numpy()
    np.testing.assert_allclose(float(metric.compute()), want.mean(), rtol=1e-3, atol=1e-5)

    summed = LearnedPerceptualImagePatchSimilarity(net_type=net_type, net_weights_path=path, reduction="sum")
    summed.update(img1, img2)
    np.testing.assert_allclose(float(summed.compute()), want.sum(), rtol=1e-3, atol=1e-5)


def test_lpips_identical_images_zero(lpips_pair):
    net_type, _, path = lpips_pair
    rng = np.random.RandomState(2)
    img = jnp.asarray((rng.rand(2, 3, 64, 64) * 2 - 1).astype(np.float32))
    metric = LearnedPerceptualImagePatchSimilarity(net_type=net_type, net_weights_path=path)
    metric.update(img, img)
    assert abs(float(metric.compute())) < 1e-6


def test_lpips_validation_errors():
    metric = LearnedPerceptualImagePatchSimilarity(net=lambda a, b: jnp.zeros(a.shape[0]))
    with pytest.raises(ValueError, match="normalized"):
        metric.update(jnp.ones((2, 3, 8, 8)) * 2.0, jnp.ones((2, 3, 8, 8)))  # out of range
    with pytest.raises(ValueError, match="normalized"):
        metric.update(jnp.ones((2, 1, 8, 8)), jnp.ones((2, 1, 8, 8)))  # wrong channels
    with pytest.raises(ValueError, match="reduction"):
        LearnedPerceptualImagePatchSimilarity(net=lambda a, b: None, reduction="max")
    with pytest.raises(ValueError, match="net_type"):
        LearnedPerceptualImagePatchSimilarity(net_type="squeeze", net_weights_path="x.npz")
    with pytest.raises(ValueError, match="weights"):
        LearnedPerceptualImagePatchSimilarity(net_type="alex")
