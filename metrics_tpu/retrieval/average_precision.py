"""RetrievalMAP.

Behavior parity with /root/reference/torchmetrics/retrieval/average_precision.py:20-96.
"""
import jax

from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
from metrics_tpu.functional.retrieval.padded import average_precision_row
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> rmap(preds, target, indexes=indexes)
        Array(0.7916667, dtype=float32)
    """

    _padded_metric = staticmethod(average_precision_row)

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_average_precision(preds, target)
