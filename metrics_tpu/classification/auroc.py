"""Modular AUROC (sketch-backed streaming default; exact modes opt-in).

Behavior parity with /root/reference/torchmetrics/classification/auroc.py:27-181,
including mode locking. Three state modes:

* **default** — quantile-sketch streaming state (``metrics_tpu/sketches/``):
  O(``sketch_capacity``) memory, fixed-shape jit-safe update (fusible /
  bucketable / async-capable), ``"merge"``-reduced across ranks. Bit-equal
  to ``exact=True`` for every stream that fits the capacity (the lossless
  window); beyond it, weighted kernels under the sketch's rank-error bound.
* ``exact=True`` — the reference's unbounded cat-state path (and its
  memory-footprint warning, auroc.py:146-149), bit-for-bit.
* ``capacity=N`` — the static exact buffer mode (jit-safe exact curves,
  raises on overflow; see classification/_capacity.py).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.classification._sketch import DEFAULT_SKETCH_CAPACITY, SketchCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auroc import (
    _auroc_compute,
    _auroc_update,
    auroc_rank_multiclass_masked,
)
from metrics_tpu.functional.classification.exact_curve import binary_auroc_fixed
from metrics_tpu.functional.classification.sketch_curve import (
    average_class_scores,
    binary_auroc_max_fpr_weighted,
    binary_auroc_weighted,
    weighted_class_supports,
)
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import AverageMethod, DataType

Array = jax.Array


class AUROC(SketchCurveMixin, CapacityCurveMixin, Metric):
    """Computes the Area Under the Receiver Operating Characteristic Curve.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> auroc = AUROC(pos_label=1)
        >>> auroc(preds, target)
        Array(0.5, dtype=float32)
    """

    __jit_unsafe__ = False  # sketch default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"  # tracelint: classify the default mode
    __fused_mask_valid__ = True  # bucketed pads mask out via n_valid
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        capacity: Optional[int] = None,
        exact: bool = False,
        sketch_capacity: int = DEFAULT_SKETCH_CAPACITY,
        shape_stable_reads: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.MICRO, AverageMethod.NONE)
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )

        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")
        if exact and capacity is not None:
            raise ValueError("`exact=True` and `capacity` are mutually exclusive state modes")

        self.mode = None
        self._exact = bool(exact)
        if capacity is not None:
            # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
            # Binary (num_classes None/1) uses the curve-buffer triple;
            # multiclass (num_classes >= 2) keeps [capacity, C] score rows and
            # computes the exact one-vs-rest rank AUROC with a validity mask.
            if max_fpr is not None:
                raise ValueError("`capacity` mode does not support `max_fpr`")
            if num_classes is not None and num_classes >= 2:
                if average == AverageMethod.MICRO:
                    raise ValueError(
                        "`capacity` multiclass mode supports average in"
                        " ('macro', 'weighted', 'none'); 'micro' is not defined for the"
                        " one-vs-rest rank kernel"
                    )
                self._init_capacity(capacity, num_cols=num_classes)
                self._multiclass_capacity = True
            else:
                self._init_capacity(capacity)
                self._multiclass_capacity = False
        elif self._exact:
            register_exact_list_states(self, ("preds", "target"))
            warn_exact_buffer("AUROC")
        else:
            self._init_sketch_curve(
                sketch_capacity, num_classes, shape_stable_reads=shape_stable_reads
            )

    _multiclass_capacity: bool = False

    def _update(self, preds: Array, target: Array, n_valid: Optional[Array] = None) -> None:
        if self._capacity is not None:
            self._capacity_update(
                preds, target, pos_label=None if self._multiclass_capacity else self.pos_label
            )
            return
        preds, target, mode = _auroc_update(preds, target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        if self._exact:
            self.preds.append(preds)
            self.target.append(target)
        else:
            self._sketch_insert_canonical(
                preds, target, self.pos_label if mode == DataType.BINARY else 1, n_valid=n_valid
            )
        self.mode = mode

    def _compute(self) -> Array:
        if self._capacity is not None:
            if self._multiclass_capacity:
                preds, target, valid = self._capacity_buffers_2d()
                return auroc_rank_multiclass_masked(
                    preds, target, valid, self.num_classes, average=self.average
                )
            return binary_auroc_fixed(*self._capacity_buffers())
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        if self._exact:
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
            return _auroc_compute(
                preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
            )
        if self._sketch_reads_exact():
            preds, target, pos_label = self._sketch_exact_arrays()
            return _auroc_compute(
                preds, target, self.mode, self.num_classes, pos_label, self.average, self.max_fpr
            )
        return self._sketch_approx_compute()

    def _sketch_approx_compute(self) -> Array:
        """Weighted AUROC from the (bucket-padded) sketch rows: beyond the
        lossless window, or on every non-empty read under
        ``shape_stable_reads``; error bounded by the sketch's rank-error
        envelope.  The whole weighted pipeline runs as ONE pre-lowered
        executable per (mode, shape bucket) from the reader cache, so a
        dashboard polling a growing stream compiles O(log capacity) kernels
        total instead of re-tracing every eager curve op per fill count."""
        scores, y, w = self._sketch_weighted_arrays()
        if self.max_fpr is not None and self.mode != DataType.BINARY:
            # the exact/lossless paths raise this inside _auroc_compute; the
            # misconfiguration must stay loud past the window too
            raise ValueError(
                "Partial AUC computation not available in multilabel/multiclass setting,"
                f" 'max_fpr' must be set to `None`, received `{self.max_fpr}`."
            )
        mode, average, max_fpr = self.mode, self.average, self.max_fpr

        def build():
            def fn(scores, y, w):
                if mode == DataType.BINARY:
                    if max_fpr is not None and max_fpr < 1:
                        return binary_auroc_max_fpr_weighted(scores, y, w, max_fpr)
                    return binary_auroc_weighted(scores, y, w)
                if mode == DataType.MULTILABEL and average == AverageMethod.MICRO:
                    flat_w = jnp.broadcast_to(w[:, None], y.shape).reshape(-1)
                    return binary_auroc_weighted(scores.reshape(-1), y.reshape(-1), flat_w)
                per_class = jax.vmap(binary_auroc_weighted, in_axes=(1, 1, None))(scores, y, w)
                supports = weighted_class_supports(y, w)
                avg = None if average == AverageMethod.NONE else average
                return average_class_scores(per_class, supports, avg)

            return fn

        reader = self._readers.get(
            f"auroc_weighted:{mode}:{average}:{max_fpr}",
            build,
            scores,
            y,
            w,
            bucket=int(jnp.asarray(w).shape[0]),
        )
        return reader(scores, y, w)
