"""Aggregation metrics: Max/Min/Sum/Cat/Mean over a stream of values.

Behavior parity with /root/reference/torchmetrics/aggregation.py:24-408,
including the nan_strategy options (error/warn/ignore/float-impute,
aggregation.py:73-91). Deliberate fixes vs the reference snapshot: the
non-empty guard uses element count, not truthiness (the reference's
``any(value.flatten())`` skips all-zero updates); NaN handling under
tracing imputes via ``where`` with the aggregator's identity element
(0 for sum, -inf for max, +inf for min) so jit and eager agree; and
``MeanMetric`` filters value and weight jointly (the reference filters
them independently, which desyncs their shapes).
"""
from typing import Any, Callable, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.prints import rank_zero_warn as _rank_zero_warn
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class BaseAggregator(Metric):
    """Base class for aggregation metrics.

    ``nan_strategy``: 'error' | 'warn' (remove with warning) | 'ignore'
    (silent removal) | float (impute).
    """

    is_differentiable = None
    higher_is_better = None

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy}"
                f" but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state("value", default=default_value, dist_reduce_fx=fn)

    # identity element used to impute removed NaNs under tracing; None means
    # the aggregator has no neutral value (CatMetric) and passes NaNs through
    _nan_neutral = None

    def _cast_and_nan_check_input(self, x: Union[float, Array]) -> Array:
        x = jnp.asarray(x, dtype=jnp.float32) if not isinstance(x, jnp.ndarray) else x.astype(jnp.float32)

        if _is_concrete(x):
            nans = jnp.isnan(x)
            if bool(jnp.any(nans)):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encounted `nan` values in tensor")
                if self.nan_strategy == "warn":
                    _rank_zero_warn("Encounted `nan` values in tensor. Will be removed.", UserWarning)
                    x = x[~nans]
                elif self.nan_strategy == "ignore":
                    x = x[~nans]
                else:
                    x = jnp.where(nans, float(self.nan_strategy), x)
        elif isinstance(self.nan_strategy, float):
            x = jnp.where(jnp.isnan(x), float(self.nan_strategy), x)
        elif self._nan_neutral is not None:
            # traced array: removal is impossible, impute the aggregator's
            # identity so jit and eager agree for warn/ignore (and error,
            # which cannot raise on values under tracing)
            x = jnp.where(jnp.isnan(x), self._nan_neutral, x)
        return x

    def _update(self, value: Union[float, Array]) -> None:
        pass

    def _compute(self) -> Array:
        return self.value


class MaxMetric(BaseAggregator):
    """Running maximum of a stream of values.

    Example:
        >>> metric = MaxMetric()
        >>> metric.update(1.0)
        >>> metric.update(3.0)
        >>> metric.update(2.0)
        >>> metric.compute()
        Array(3., dtype=float32)
    """

    _nan_neutral = -jnp.inf

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", -jnp.asarray(jnp.inf), nan_strategy, **kwargs)

    def _update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size > 0:
            self.value = jnp.maximum(self.value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running minimum of a stream of values."""

    _nan_neutral = jnp.inf

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, **kwargs)

    def _update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size > 0:
            self.value = jnp.minimum(self.value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum of a stream of values."""

    _nan_neutral = 0.0

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)

    def _update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size > 0:
            self.value = self.value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate a stream of values."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def _update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        if value.size > 0:
            self.value.append(value)

    def _compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat([jnp.atleast_1d(v) for v in self.value])
        return jnp.asarray(self.value) if not isinstance(self.value, list) else jnp.zeros(0)


class MeanMetric(BaseAggregator):
    """Weighted running mean of a stream of values.

    Example:
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(2.0)
        >>> metric.compute()
        Array(1.5, dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        # broadcast first, then handle NaNs jointly so value and weight stay
        # aligned (independent filtering desyncs their shapes)
        value = jnp.asarray(value, dtype=jnp.float32)
        weight = jnp.broadcast_to(jnp.asarray(weight, dtype=jnp.float32), value.shape)
        if value.size == 0:
            return

        nans = jnp.isnan(value) | jnp.isnan(weight)
        if _is_concrete(value, weight):
            if bool(jnp.any(nans)):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encounted `nan` values in tensor")
                if self.nan_strategy == "warn":
                    _rank_zero_warn("Encounted `nan` values in tensor. Will be removed.", UserWarning)
                    value, weight = value[~nans], weight[~nans]
                elif self.nan_strategy == "ignore":
                    value, weight = value[~nans], weight[~nans]
                else:
                    value = jnp.where(jnp.isnan(value), float(self.nan_strategy), value)
                    weight = jnp.where(jnp.isnan(weight), float(self.nan_strategy), weight)
        elif isinstance(self.nan_strategy, float):
            value = jnp.where(jnp.isnan(value), float(self.nan_strategy), value)
            weight = jnp.where(jnp.isnan(weight), float(self.nan_strategy), weight)
        else:
            # traced removal is impossible: zero the weight at NaN positions so
            # those samples drop out of both sums (matches eager removal)
            value = jnp.where(nans, 0.0, value)
            weight = jnp.where(nans, 0.0, weight)

        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def _compute(self) -> Array:
        return self.value / self.weight
