"""Weighted reservoir sample with FIXED-shape state and jit-safe replacement.

A uniform (optionally weighted) sample of ``k`` payload rows from an
unbounded stream, as a packed single-leaf state

    ``[k, 1 + payload_cols]`` float32
    column 0: priority key (``-inf`` ⇒ empty slot)
    columns 1..: payload row (feature vector, (pred, target) pair, ...)

Replacement is the Gumbel-key (A-ExpJ) scheme: every inserted row draws a
deterministic counter-seeded Gumbel priority ``g + log(w)``; the reservoir
is always the top-``k`` rows by priority, which a single fixed-shape
``top_k``-style sort maintains under jit — no host RNG, no rejection
loops, and ``merge(a, b)`` is simply top-``k`` over the concatenated rows
(two independent reservoirs of the same stream prefix merge into exactly
the reservoir of the union).

**Lossless window.** While the total row count fits in ``k`` the packed
leaf holds every row in arrival order (stable pack, no replacement), so
consumers (KID subset selection) reproduce the cat-state path
bit-for-bit; only past ``k`` does uniform subsampling engage.

**Determinism & cross-rank merges.** Priorities come from
``fold_in(PRNGKey(seed), seen_counter)`` — reproducible across runs. Two
RANKS inserting with the same seed and counters would draw identical
priorities and bias the merge, so per-rank metrics fold
``jax.process_index()`` into their seed (see ``image/kid.py``).
"""
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_EMPTY = -jnp.inf


def reservoir_init(k: int, payload_cols: int) -> Array:
    """Fresh empty reservoir leaf ``[k, 1 + payload_cols]``."""
    if not (isinstance(k, int) and k > 0):
        raise ValueError(f"reservoir size `k` must be a positive int, got {k}")
    if not (isinstance(payload_cols, int) and payload_cols > 0):
        raise ValueError(f"`payload_cols` must be a positive int, got {payload_cols}")
    leaf = jnp.zeros((k, 1 + payload_cols), jnp.float32)
    return leaf.at[:, 0].set(_EMPTY)


@partial(jax.jit, static_argnums=1)
def _select(rows: Array, k: int) -> Array:
    """Top-``k`` rows by priority when over-occupied, else stable pack.
    Jitted (static ``k``) so eager metric updates pay one cached dispatch."""
    n = rows.shape[0]
    pri = rows[:, 0]
    occ = pri > _EMPTY
    n_occ = jnp.sum(occ)

    def pack(r):
        order = jnp.argsort(jnp.where(occ, 0, 1) * n + jnp.arange(n, dtype=jnp.int32))
        return r[order][:k]

    def topk(r):
        order = jnp.lexsort((jnp.arange(n, dtype=jnp.int32), -pri))
        return r[order][:k]

    return jax.lax.cond(n_occ > k, topk, pack, rows)


def reservoir_insert(
    reservoir: Array,
    payload: Array,
    seen: Array,
    seed: int = 0,
    weights: Optional[Array] = None,
    n_valid: Optional[Array] = None,
) -> Array:
    """Insert ``[B, payload_cols]`` rows; pure and jit-safe.

    ``seen`` is the caller-maintained count of rows inserted BEFORE this
    batch (a sum-reduced int state leaf) — it seeds the per-batch priority
    draw so replays are deterministic and successive batches never reuse
    priorities. ``weights`` bias inclusion probability (A-ExpJ:
    ``priority = gumbel + log(w)``); ``n_valid`` masks trailing pad rows
    out entirely (the fused pad-and-mask contract).
    """
    payload = jnp.asarray(payload, jnp.float32)
    payload = payload.reshape(payload.shape[0], -1)
    b = payload.shape[0]
    if payload.shape[1] != reservoir.shape[1] - 1:
        raise ValueError(
            f"payload has {payload.shape[1]} column(s) but the reservoir was initialized"
            f" with {reservoir.shape[1] - 1}"
        )
    if b == 0:
        return reservoir
    rng = jax.random.fold_in(jax.random.PRNGKey(seed), jnp.asarray(seen, jnp.int32))
    pri = jax.random.gumbel(rng, (b,), jnp.float32)
    if weights is not None:
        w = jnp.asarray(weights, jnp.float32).reshape(-1)
        pri = pri + jnp.where(w > 0, jnp.log(jnp.clip(w, 1e-30, None)), _EMPTY)
    if n_valid is not None:
        pri = jnp.where(jnp.arange(b) < n_valid, pri, _EMPTY)
    rows = jnp.concatenate([pri[:, None], payload], axis=1)
    k = reservoir.shape[0]
    out = reservoir
    for lo in range(0, b, k):
        chunk = rows[lo : lo + k]
        out = _select(jnp.concatenate([out, chunk], axis=0), k)
    return out


def reservoir_key(ids: Array) -> Array:
    """Deterministic hash priority in ``(0, 1]`` from integer ids.

    The same avalanche mix as the retrieval table's ``_qid_key``
    (retrieval/table.py): the priority is a PURE FUNCTION of the global
    id, so admission decisions are invariant to batch chunking, padding,
    and cross-rank merge order — the surviving id set under any
    partitioning of the stream is exactly the top-``k`` ids by hash.
    Compare :func:`reservoir_insert`'s counter-seeded Gumbel draw, whose
    priorities depend on how the stream was batched.
    """
    x = jnp.asarray(ids, jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    # top 24 bits -> (0, 1]: exactly representable in f32, never -inf/0
    return ((x >> 8).astype(jnp.float32) + 1.0) / float(1 << 24)


def reservoir_insert_keyed(
    reservoir: Array,
    payload: Array,
    keys: Array,
    n_valid: Optional[Array] = None,
) -> Array:
    """Insert ``[B, payload_cols]`` rows with CALLER-SUPPLIED priorities.

    The deterministic-key counterpart of :func:`reservoir_insert`: the
    caller derives each row's priority from a stable identity (e.g.
    :func:`reservoir_key` of a global arrival index), making the admitted
    set independent of batching. ``n_valid`` masks trailing pad rows to
    ``-inf`` priority (the fused pad-and-mask contract).
    """
    payload = jnp.asarray(payload, jnp.float32)
    payload = payload.reshape(payload.shape[0], -1)
    b = payload.shape[0]
    if payload.shape[1] != reservoir.shape[1] - 1:
        raise ValueError(
            f"payload has {payload.shape[1]} column(s) but the reservoir was initialized"
            f" with {reservoir.shape[1] - 1}"
        )
    if b == 0:
        return reservoir
    pri = jnp.asarray(keys, jnp.float32).reshape(-1)
    if pri.shape[0] != b:
        raise ValueError(f"got {pri.shape[0]} key(s) for {b} payload row(s)")
    if n_valid is not None:
        pri = jnp.where(jnp.arange(b) < n_valid, pri, _EMPTY)
    rows = jnp.concatenate([pri[:, None], payload], axis=1)
    k = reservoir.shape[0]
    out = reservoir
    for lo in range(0, b, k):
        out = _select(jnp.concatenate([out, rows[lo : lo + k]], axis=0), k)
    return out


def reservoir_merge(a: Array, b: Array) -> Array:
    """Merge two reservoirs (top-``k`` of the union by priority); the
    ``dist_reduce_fx`` operation. Exact (no row lost) while the combined
    occupancy fits in ``a``'s size."""
    if a.ndim != 2 or a.shape[1:] != b.shape[1:]:
        raise ValueError(f"cannot merge reservoirs with layouts {a.shape} and {b.shape}")
    k = a.shape[0]
    out = a
    for lo in range(0, b.shape[0], k):
        out = _select(jnp.concatenate([out, b[lo : lo + k]], axis=0), k)
    return out


class _ReservoirReduce:
    """``dist_reduce_fx`` folding :func:`reservoir_merge` over the stacked
    per-rank leaves ``[world, k, cols]`` — a picklable module-level class
    tagged like the quantile reducer so the merge plumbing treats both
    sketch kinds uniformly."""

    merge_like = True
    sketch_kind = "reservoir"
    __name__ = "reservoir_reduce"

    def __call__(self, stacked: Array) -> Array:
        stacked = jnp.asarray(stacked)
        if stacked.ndim == 2:
            return stacked
        out = stacked[0]
        for i in range(1, stacked.shape[0]):
            out = reservoir_merge(out, stacked[i])
        return out


_RESERVOIR_REDUCE = _ReservoirReduce()


def reservoir_merge_fx() -> _ReservoirReduce:
    """The shared reservoir ``dist_reduce_fx`` (see :class:`_ReservoirReduce`)."""
    return _RESERVOIR_REDUCE


def detection_table_init(max_images: int, row_cols: int) -> Array:
    """Detection matching table: a reservoir of PER-IMAGE packed rows.

    ``detection/mean_ap.py`` flattens each image's capped detection and
    ground-truth slots into one ``[row_cols]`` payload row and admits
    images through the standard reservoir contract: lossless (arrival
    order preserved) while ``images_seen <= max_images``, deterministic
    counter-seeded uniform subsampling past that. Same leaf layout as
    :func:`reservoir_init` — the alias exists so the state registration
    (and the interp ctor teaching) names the capacity model it implements.
    """
    return reservoir_init(max_images, row_cols)


def reservoir_fill(reservoir: Array) -> Array:
    """Number of occupied slots (int32 scalar)."""
    return jnp.sum(reservoir[:, 0] > _EMPTY).astype(jnp.int32)


def reservoir_rows(reservoir: Array) -> Array:
    """The payload rows ``[k, payload_cols]`` (occupied-first slot order;
    callers slice by :func:`reservoir_fill` on the host)."""
    return reservoir[:, 1:]
