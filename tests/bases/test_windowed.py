"""WindowedMetric: sliding-window (ring) and exponential-decay state for
any fusible metric (ISSUE 12 tentpole).

The acceptance pins: a ring-window ``compute()`` is BIT-identical to
recomputing the same window's batches from scratch on integer-exact data
(the sliding window IS the metric); decay mode matches its closed form;
``WindowedMetric(Accuracy())`` and ``WindowedMetric(SlicedMetric(MSE))``
run through ``compile_update_async`` with ONE compile across bucketed
ragged shapes; ring-of-sketches leaves (sketched AUROC) window exactly
inside the lossless window; and the windowed state pytree rides
``sync_pytree_in_mesh`` unchanged.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from metrics_tpu import (
    AUROC,
    Accuracy,
    MeanSquaredError,
    MetricCollection,
    WindowedMetric,
)
from metrics_tpu.observability import get_recorder
from metrics_tpu.parallel.distributed import sync_pytree_in_mesh
from metrics_tpu.sliced import SlicedMetric
from metrics_tpu.utils.compat import shard_map
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.windowed import DECAY_WEIGHT, RING_COUNT, RING_ROWS
from metrics_tpu.wrappers import MinMaxMetric


def _int_batches(rng, n_batches, n=64, hi=7):
    return [
        (
            jnp.asarray(rng.randint(0, hi, n).astype(np.float32)),
            jnp.asarray(rng.randint(0, hi, n).astype(np.float32)),
        )
        for _ in range(n_batches)
    ]


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

class TestRing:
    def test_ring_fold_bit_identical_to_fresh_recompute(self):
        """The acceptance pin: integer-exact data, ring compute == fresh
        metric over exactly the in-window batches, bit for bit."""
        rng = np.random.RandomState(0)
        batches = _int_batches(rng, 11)
        wm = WindowedMetric(MeanSquaredError(), window=4, updates_per_bucket=2)
        for b in batches:
            wm.update(*b)
        # 11 updates, 2/bucket -> current bucket 5; ring holds buckets
        # 2..5 = updates 4..10
        fresh = MeanSquaredError()
        for b in batches[4:]:
            fresh.update(*b)
        assert float(wm.compute()) == float(fresh.compute())

    def test_narrow_window_and_before(self):
        rng = np.random.RandomState(1)
        batches = _int_batches(rng, 12)
        wm = WindowedMetric(MeanSquaredError(), window=5, updates_per_bucket=2)
        for b in batches:
            wm.update(*b)
        # current bucket 5; window=2 -> buckets 4..5 = updates 8..11
        fresh = MeanSquaredError()
        for b in batches[8:]:
            fresh.update(*b)
        assert float(wm.compute(window=2)) == float(fresh.compute())
        # before=2 -> window of 2 ending at bucket 3 = updates 4..7
        ref = MeanSquaredError()
        for b in batches[4:8]:
            ref.update(*b)
        assert float(wm.compute(window=2, before=2)) == float(ref.compute())

    def test_bucket_self_eviction_on_wrap(self):
        """A wrapped slot is reset to defaults before accumulating — old
        buckets never leak into the new bucket's row."""
        wm = WindowedMetric(MeanSquaredError(), window=2, updates_per_bucket=1)
        wm.update(jnp.asarray([9.0]), jnp.asarray([0.0]))  # bucket 0
        wm.update(jnp.asarray([0.0]), jnp.asarray([0.0]))  # bucket 1
        wm.update(jnp.asarray([0.0]), jnp.asarray([0.0]))  # bucket 2 evicts 0
        assert float(wm.compute()) == 0.0

    def test_bucket_counts_and_clock(self):
        wm = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=2)
        for _ in range(5):
            wm.update(jnp.asarray([1.0]), jnp.asarray([0.0]))
        assert int(getattr(wm, RING_COUNT)) == 5
        counts = np.asarray(wm.bucket_counts)
        assert counts.tolist() == [2, 2, 1]

    def test_partial_ring_early_stream(self):
        """Fewer updates than buckets: compute covers what exists."""
        rng = np.random.RandomState(2)
        batches = _int_batches(rng, 2)
        wm = WindowedMetric(MeanSquaredError(), window=8, updates_per_bucket=1)
        fresh = MeanSquaredError()
        for b in batches:
            wm.update(*b)
            fresh.update(*b)
        assert float(wm.compute()) == float(fresh.compute())

    def test_window_past_ring_span_raises(self):
        wm = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=1)
        for _ in range(6):
            wm.update(jnp.asarray([1.0]), jnp.asarray([0.0]))
        with pytest.raises(MetricsUserError, match="evicted"):
            wm.compute(window=3, before=2)

    def test_reserved_constants_match_literals(self):
        """The registered literal state names are the exported constants
        (the literals exist so the manifest serializes the leaves)."""
        wm = WindowedMetric(MeanSquaredError(), window=3)
        assert RING_ROWS in wm._defaults and RING_COUNT in wm._defaults
        dm = WindowedMetric(MeanSquaredError(), mode="decay", decay=0.9)
        assert DECAY_WEIGHT in dm._defaults


# ---------------------------------------------------------------------------
# decay semantics
# ---------------------------------------------------------------------------

class TestDecay:
    def test_closed_form(self):
        """Constant per-update delta d: state_n = d * (1-a^n)/(1-a)."""
        a = 0.5
        dm = WindowedMetric(MeanSquaredError(), mode="decay", decay=a)
        for _ in range(5):
            dm.update(jnp.asarray([2.0]), jnp.asarray([0.0]))
        geo = (1 - a**5) / (1 - a)
        assert float(getattr(dm, "sum_squared_error")) == pytest.approx(4.0 * geo, rel=1e-6)
        assert float(dm.decay_weight) == pytest.approx(geo, rel=1e-6)
        # the RATIO metric is decay-invariant under a constant stream
        assert float(dm.compute()) == pytest.approx(4.0, rel=1e-6)

    def test_decay_forgets(self):
        dm = WindowedMetric(MeanSquaredError(), mode="decay", decay=0.2)
        dm.update(jnp.asarray([10.0]), jnp.asarray([0.0]))
        for _ in range(20):
            dm.update(jnp.asarray([0.0]), jnp.asarray([0.0]))
        assert float(dm.compute()) < 1e-6

    def test_integer_leaves_promoted(self):
        """Integer sum leaves would truncate alpha to 0 (a silent reset
        instead of a decay) — they promote to float32 at registration."""
        dm = WindowedMetric(Accuracy(), mode="decay", decay=0.5)
        for name in dm.wrapped._defaults:
            assert jnp.asarray(getattr(dm, name)).dtype == jnp.float32
        dm.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        dm.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        # tp decayed: 1*(1 + 0.5) = 1.5, not reset-and-recount
        assert float(getattr(dm, "tp")) == pytest.approx(1.5)

    def test_decay_rejects_window_queries(self):
        dm = WindowedMetric(MeanSquaredError(), mode="decay", decay=0.9)
        dm.update(jnp.asarray([1.0]), jnp.asarray([0.0]))
        with pytest.raises(MetricsUserError, match="ring-mode"):
            dm.compute(window=1)
        with pytest.raises(MetricsUserError, match="ring-mode"):
            dm.window_state()
        with pytest.raises(MetricsUserError, match="ring-mode"):
            _ = dm.bucket_counts


# ---------------------------------------------------------------------------
# construction validation
# ---------------------------------------------------------------------------

class TestValidation:
    def test_rejects_jit_unsafe_metric(self):
        with pytest.raises(MetricsUserError, match="jit_unsafe"):
            WindowedMetric(MinMaxMetric(MeanSquaredError()), window=4)

    def test_rejects_wrapper_metric(self):
        from metrics_tpu.core.metric import Metric

        class _Holder(Metric):
            def __init__(self):
                super().__init__()
                self.child = MeanSquaredError()  # registers in _children
                self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

            def _update(self, preds, target):
                self.total = self.total + jnp.sum(preds)

            def _compute(self):
                return self.total

        with pytest.raises(MetricsUserError, match="wrapper"):
            WindowedMetric(_Holder(), window=4)

    def test_rejects_nested_windowed(self):
        with pytest.raises(MetricsUserError, match="another WindowedMetric"):
            WindowedMetric(WindowedMetric(MeanSquaredError()), window=4)

    def test_rejects_mean_reduced_leaves(self):
        from metrics_tpu.core.metric import Metric

        class _MeanState(Metric):
            def __init__(self):
                super().__init__()
                self.add_state("avg", default=jnp.asarray(0.0), dist_reduce_fx="mean")

            def _update(self, preds):
                self.avg = jnp.mean(preds)

            def _compute(self):
                return self.avg

        with pytest.raises(MetricsUserError, match="sum-reduced numerator"):
            WindowedMetric(_MeanState(), window=4)

    def test_decay_rejects_extremum_leaves(self):
        from metrics_tpu.aggregation import MaxMetric

        with pytest.raises(MetricsUserError, match="mode='ring'"):
            WindowedMetric(MaxMetric(), mode="decay", decay=0.9)

    def test_decay_rejects_sketch_leaves(self):
        with pytest.raises(MetricsUserError, match="mode='ring'"):
            WindowedMetric(AUROC(pos_label=1), mode="decay", decay=0.9)

    def test_param_validation(self):
        with pytest.raises(MetricsUserError, match="window"):
            WindowedMetric(MeanSquaredError(), window=1)
        with pytest.raises(MetricsUserError, match="updates_per_bucket"):
            WindowedMetric(MeanSquaredError(), updates_per_bucket=0)
        with pytest.raises(MetricsUserError, match="decay"):
            WindowedMetric(MeanSquaredError(), mode="decay", decay=1.5)
        with pytest.raises(MetricsUserError, match="mode"):
            WindowedMetric(MeanSquaredError(), mode="sliding")
        with pytest.raises(MetricsUserError, match="only applies"):
            WindowedMetric(MeanSquaredError(), decay=0.9)
        with pytest.raises(MetricsUserError, match="only apply to mode='ring'"):
            WindowedMetric(MeanSquaredError(), mode="decay", decay=0.9, window=500)
        with pytest.raises(MetricsUserError, match="only apply to mode='ring'"):
            WindowedMetric(MeanSquaredError(), mode="decay", decay=0.9, updates_per_bucket=4)


# ---------------------------------------------------------------------------
# fused / async / sliced composition (the acceptance criteria)
# ---------------------------------------------------------------------------

def _ragged_int_batches(rng, shapes, hi=2):
    out = []
    for n in shapes:
        p = jnp.asarray(rng.randint(0, hi, n).astype(np.int32))
        t = jnp.asarray(rng.randint(0, hi, n).astype(np.int32))
        out.append((p, t))
    return out


class TestFusedAsync:
    def test_single_compile_across_ragged_shapes_and_bit_parity(self):
        rng = np.random.RandomState(3)
        batches = _ragged_int_batches(rng, (48, 64, 57, 64, 31, 60))

        def make():
            # num_classes makes Accuracy's canonicalizer jit-traceable, so
            # BOTH members genuinely ride the fused kernel (bare label
            # inputs would silently fall back to the eager path)
            return MetricCollection(
                {
                    "acc": WindowedMetric(Accuracy(num_classes=2), window=4, updates_per_bucket=2),
                    "mse": WindowedMetric(MeanSquaredError(), window=4, updates_per_bucket=2),
                }
            )

        fused_col = make()
        handle = fused_col.compile_update(buckets=(64,))
        eager_col = make()
        for b in batches:
            fused_col.update(*b)
            eager_col.update(*b)
        assert handle.n_compiles == 1
        fv, ev = fused_col.compute(), eager_col.compute()
        for k in fv:
            assert float(fv[k]) == float(ev[k]), k
        # state-level bit parity, leaf by leaf
        for name, m in fused_col.items():
            e = eager_col[name]
            for leaf in m._defaults:
                assert np.array_equal(np.asarray(getattr(m, leaf)), np.asarray(getattr(e, leaf))), (
                    name,
                    leaf,
                )

    def test_windowed_accuracy_through_async(self):
        """Acceptance: WindowedMetric(Accuracy()) through
        compile_update_async, 1 compile across bucketed ragged shapes."""
        rng = np.random.RandomState(4)
        batches = _ragged_int_batches(rng, (48, 64, 57, 60, 64, 33))
        col = MetricCollection(
            {"acc": WindowedMetric(Accuracy(num_classes=2), window=4, updates_per_bucket=2)}
        )
        handle = col.compile_update_async(buckets=(64,), queue_depth=4)
        ref = WindowedMetric(Accuracy(num_classes=2), window=4, updates_per_bucket=2)
        try:
            for b in batches:
                handle.update_async(*b)
                ref.update(*b)
            handle.flush()
            assert col.fused_update.n_compiles == 1
            assert float(col.compute()["acc"]) == float(ref.compute())
        finally:
            handle.close()

    def test_windowed_sliced_mse_through_async(self):
        """Acceptance: WindowedMetric(SlicedMetric(MSE)) through
        compile_update_async, 1 compile across bucketed ragged shapes,
        bit-identical to the eager windowed-sliced metric."""
        rng = np.random.RandomState(5)
        S = 8
        shapes = (48, 64, 57, 60, 64, 33)
        batches = []
        for n in shapes:
            ids = jnp.asarray(rng.randint(0, S, n).astype(np.int32))
            p = jnp.asarray(rng.randint(0, 5, n).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 5, n).astype(np.float32))
            batches.append((ids, p, t))

        def make():
            return WindowedMetric(
                SlicedMetric(MeanSquaredError(), num_slices=S), window=3, updates_per_bucket=2
            )

        col = MetricCollection({"wsliced": make()})
        handle = col.compile_update_async(buckets=(64,), queue_depth=4)
        ref = make()
        try:
            for b in batches:
                handle.update_async(*b)
                ref.update(*b)
            handle.flush()
            assert col.fused_update.n_compiles == 1
            fused_vals = np.asarray(col.compute()["wsliced"])
            ref_vals = np.asarray(ref.compute())
            assert np.array_equal(fused_vals, ref_vals)
        finally:
            handle.close()

    def test_windowed_sliced_parity_vs_per_window_fanout(self):
        """The composed semantics are right: per-slice windowed values
        equal fresh per-slice metrics over the in-window rows."""
        rng = np.random.RandomState(6)
        S = 4
        wm = WindowedMetric(SlicedMetric(MeanSquaredError(), num_slices=S), window=2, updates_per_bucket=1)
        batches = []
        for _ in range(4):
            ids = rng.randint(0, S, 32).astype(np.int32)
            p = rng.randint(0, 5, 32).astype(np.float32)
            t = rng.randint(0, 5, 32).astype(np.float32)
            batches.append((ids, p, t))
            wm.update(jnp.asarray(ids), jnp.asarray(p), jnp.asarray(t))
        # window of 2 = last two batches
        ref = SlicedMetric(MeanSquaredError(), num_slices=S)
        for ids, p, t in batches[2:]:
            ref.update(jnp.asarray(ids), jnp.asarray(p), jnp.asarray(t))
        assert np.array_equal(np.asarray(wm.compute()), np.asarray(ref.compute()))


# ---------------------------------------------------------------------------
# ring-of-sketches (merge leaves)
# ---------------------------------------------------------------------------

class TestRingSketches:
    def test_windowed_sketched_auroc_bit_identical_in_lossless_window(self):
        rng = np.random.RandomState(7)
        scores = rng.rand(5, 32).astype(np.float32)
        ys = (rng.rand(5, 32) < 0.4).astype(np.int32)
        wm = WindowedMetric(AUROC(pos_label=1, sketch_capacity=512), window=3, updates_per_bucket=1)
        for i in range(5):
            wm.update(jnp.asarray(scores[i]), jnp.asarray(ys[i]))
        ref = AUROC(pos_label=1, sketch_capacity=512)
        for i in range(2, 5):
            ref.update(jnp.asarray(scores[i]), jnp.asarray(ys[i]))
        assert float(wm.compute()) == float(ref.compute())

    def test_bucketed_windowed_auroc_corrects_sum_companions(self):
        """A masking template's merge leaves pad-mask themselves, but its
        SUM companions (n_seen) count the full padded batch — the wrapper's
        slot-aware correction must remove the pad rows from them too, so
        the bucketed fused path stays bit-identical to eager."""
        rng = np.random.RandomState(12)

        def make():
            return MetricCollection(
                {"auroc": WindowedMetric(AUROC(pos_label=1, sketch_capacity=512), window=3)}
            )

        fused_col = make()
        handle = fused_col.compile_update(buckets=(64,))
        eager = WindowedMetric(AUROC(pos_label=1, sketch_capacity=512), window=3)
        for n in (48, 64, 57):
            p = jnp.asarray(rng.rand(n).astype(np.float32))
            t = jnp.asarray((rng.rand(n) < 0.4).astype(np.int32))
            fused_col.update(p, t)
            eager.update(p, t)
        assert handle.n_compiles == 1
        fm = fused_col["auroc"]
        assert np.asarray(getattr(fm, "n_seen")).tolist() == np.asarray(
            getattr(eager, "n_seen")
        ).tolist()
        assert float(fused_col.compute()["auroc"]) == float(eager.compute())

    def test_sketch_fill_ratio_handles_ring_axis(self):
        wm = WindowedMetric(AUROC(pos_label=1, sketch_capacity=64), window=4, updates_per_bucket=1)
        rng = np.random.RandomState(8)
        wm.update(jnp.asarray(rng.rand(16).astype(np.float32)), jnp.asarray((rng.rand(16) < 0.5).astype(np.int32)))
        ratios = wm.sketch_fill_ratios()
        assert ratios and 0.0 < ratios["csketch"] <= 1.0
        # 16 rows in the live slot of capacity 64 — the WORST slot is the
        # fill signal (a ring average would hide an at-capacity live
        # bucket behind the empty slots for the whole first lap)
        assert ratios["csketch"] == pytest.approx(16 / 64)


# ---------------------------------------------------------------------------
# lifecycle: reset / state_dict / clone / merge_states
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_reset_restores_ring(self):
        wm = WindowedMetric(MeanSquaredError(), window=3)
        wm.update(jnp.asarray([2.0]), jnp.asarray([0.0]))
        wm.reset()
        assert int(getattr(wm, RING_COUNT)) == 0
        assert float(jnp.sum(jnp.asarray(getattr(wm, "sum_squared_error")))) == 0.0

    def test_state_dict_roundtrip(self):
        rng = np.random.RandomState(9)
        wm = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=2)
        for b in _int_batches(rng, 5):
            wm.update(*b)
        sd = wm.state_dict()
        other = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=2)
        other.load_state_dict(sd)
        assert float(other.compute()) == float(wm.compute())

    def test_clone_independent(self):
        wm = WindowedMetric(MeanSquaredError(), window=3)
        wm.update(jnp.asarray([2.0]), jnp.asarray([0.0]))
        c = wm.clone()
        c.update(jnp.asarray([4.0]), jnp.asarray([0.0]))
        assert float(wm.compute()) != float(c.compute())

    def test_merge_states_pairwise(self):
        """Two lock-stepped ranks' ring states merge: same-bucket rows add,
        and the merged compute equals the pooled stream's window."""
        rng = np.random.RandomState(10)
        a_batches = _int_batches(rng, 4, n=16)
        b_batches = _int_batches(rng, 4, n=16)
        wa = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=1)
        wb = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=1)
        for b in a_batches:
            wa.update(*b)
        for b in b_batches:
            wb.update(*b)
        merged = wa.merge_states(
            {k: getattr(wa, k) for k in wa._defaults},
            {k: getattr(wb, k) for k in wb._defaults},
        )
        # pooled in-window stream: last 3 batches of each rank
        fresh = MeanSquaredError()
        for b in a_batches[1:] + b_batches[1:]:
            fresh.update(*b)
        # fold the merged ring through the window machinery: bind and compute
        bound = wa._bind(merged)
        try:
            val = float(wa._compute())
        finally:
            for k, v in bound.items():
                object.__setattr__(wa, k, v)
        assert val == float(fresh.compute())

    def test_forward_returns_batch_value(self):
        wm = WindowedMetric(MeanSquaredError(), window=3)
        out = wm(jnp.asarray([3.0]), jnp.asarray([0.0]))
        assert float(out) == 9.0
        assert float(wm.compute()) == 9.0


# ---------------------------------------------------------------------------
# mesh sync
# ---------------------------------------------------------------------------

class TestMeshSync:
    def test_windowed_state_syncs_in_mesh(self):
        """Replicated windowed state over the 8-device mesh: sum-shaped
        ring leaves fold 8x elementwise per bucket, the clock rides max,
        and ring sketch leaves merge per slot (weight x8)."""
        wm = WindowedMetric(MeanSquaredError(), window=3)
        wm.update(jnp.asarray([1.0, 2.0]), jnp.asarray([0.0, 0.0]))
        state = {k: jnp.asarray(getattr(wm, k)) for k in wm._defaults}
        reds = wm.state_reductions()
        mesh = Mesh(np.asarray(jax.devices()[:8]), ("d",))
        from jax.sharding import PartitionSpec as P

        specs = jax.tree_util.tree_map(lambda x: P(), state)
        out = shard_map(
            lambda st: sync_pytree_in_mesh(st, reds, "d"),
            mesh=mesh,
            in_specs=(specs,),
            out_specs=specs,
        )(state)
        assert np.asarray(out["sum_squared_error"])[0] == pytest.approx(8 * 5.0)
        assert int(np.asarray(out[RING_COUNT])) == 1  # max, not 8

    def test_ring_sketch_merges_per_slot_in_mesh(self):
        wm = WindowedMetric(AUROC(pos_label=1, sketch_capacity=64), window=3)
        rng = np.random.RandomState(11)
        wm.update(
            jnp.asarray(rng.rand(16).astype(np.float32)),
            jnp.asarray((rng.rand(16) < 0.5).astype(np.int32)),
        )
        state = {k: jnp.asarray(getattr(wm, k)) for k in wm._defaults}
        reds = wm.state_reductions()
        mesh = Mesh(np.asarray(jax.devices()[:8]), ("d",))
        from jax.sharding import PartitionSpec as P

        specs = jax.tree_util.tree_map(lambda x: P(), state)
        out = shard_map(
            lambda st: sync_pytree_in_mesh(st, reds, "d"),
            mesh=mesh,
            in_specs=(specs,),
            out_specs=specs,
        )(state)
        sk_in, sk_out = np.asarray(state["csketch"]), np.asarray(out["csketch"])
        # total mass multiplies by world size; only the occupied slot moved
        assert sk_out[..., 0].sum() == pytest.approx(8 * sk_in[..., 0].sum())
        assert (sk_out[1, :, 0] > 0).sum() == 0  # untouched ring slots stay empty


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

class TestObservability:
    def test_footprint_prefixed_and_hwm_label_split(self):
        rec = get_recorder()
        rec.reset()
        rec.enable(footprint_warn_bytes=1 << 40)
        try:
            wm = WindowedMetric(MeanSquaredError(), window=4)
            wm.update(jnp.asarray([1.0]), jnp.asarray([0.0]))
            fp = wm.state_footprint()
            assert all(k.startswith("windowed/") for k in fp)
            hwm = rec.footprint_high_water_marks()
            assert "WindowedMetric[windowed]" in hwm
            assert "WindowedMetric" not in hwm  # no base-state mark: all windowed
        finally:
            rec.disable()
            rec.reset()

    def test_repr(self):
        assert "window=4" in repr(WindowedMetric(MeanSquaredError(), window=4))
        assert "decay=0.9" in repr(WindowedMetric(MeanSquaredError(), mode="decay", decay=0.9))
