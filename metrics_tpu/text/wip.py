"""Modular WordInfoPreserved.

Behavior parity with /root/reference/torchmetrics/text/wip.py:23-97.
"""
from typing import Any, List, Union

import jax

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.wip import _wip_compute, _wip_update

Array = jax.Array


class WordInfoPreserved(Metric):
    """Word information preserved of transcriptions vs references; 1 is perfect.

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = WordInfoPreserved()
        >>> metric(preds, target)
        Array(0.34722224, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=0.0, dist_reduce_fx="sum")
        self.add_state("target_total", default=0.0, dist_reduce_fx="sum")
        self.add_state("preds_total", default=0.0, dist_reduce_fx="sum")

    def _update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def _compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)
