"""Reference-parity sweep for the text domain.

Breadth parity with /root/reference/tests/text/ (per-metric files over a
shared tricky corpus, argument axes per metric): every text metric against
the reference implementation — which is pure Python over torch-CPU, so it
runs here even where the usual PyPI oracles (jiwer, bert_score) are absent
— on a corpus with casing, punctuation, unicode, numerals, repeated words,
multiple references, and empty hypotheses, sweeping each metric's own
argument axes (BLEU n-gram/smoothing, SacreBLEU tokenizers, ROUGE keys and
accumulation, TER flags, CHRF orders/whitespace, EED, WER family).
"""
import numpy as np
import pytest

from metrics_tpu.text import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from tests.helpers.reference import load_reference_module

torch = pytest.importorskip("torch")


# tricky shared corpus: casing, punctuation, unicode, numbers, repetition
PREDS = [
    "the cat sat on the mat",
    "A quick brown Fox jumps over the lazy dog!",
    "bonjour le monde, il fait 23.5 degres",
    "hello hello hello hello",
    "Transformer models are REALLY good at translation .",
    "an empty reference follows",
]
TARGETS = [
    "the cat sat on the mat",
    "a quick brown fox jumped over a lazy dog",
    "bonjour tout le monde, il fait 23,5 degres",
    "hello world",
    "transformer models are very good at machine translation.",
    "short",
]
# multi-reference layout for the BLEU/CHRF/TER families
MULTI_TARGETS = [[t, t.upper()] for t in TARGETS]


def _ref_cls(name, **kwargs):
    mod = load_reference_module("torchmetrics.text")
    return getattr(mod, name)(**kwargs)


def _assert_matches_reference(ours, ref, preds, targets, atol=1e-5):
    # two uneven batches, then accumulated compute on both sides
    ours.update(preds[:2], targets[:2])
    ours.update(preds[2:], targets[2:])
    ref.update(preds[:2], targets[:2])
    ref.update(preds[2:], targets[2:])
    got, want = ours.compute(), ref.compute()
    if isinstance(want, dict):
        assert set(map(str, got)) >= set(map(str, want))
        for k, v in want.items():
            np.testing.assert_allclose(
                float(got[k]), float(v), atol=atol, err_msg=f"key={k}"
            )
    else:
        np.testing.assert_allclose(float(got), float(want), atol=atol)


@pytest.mark.parametrize(
    "cls, name",
    [
        (WordErrorRate, "WordErrorRate"),
        (CharErrorRate, "CharErrorRate"),
        (MatchErrorRate, "MatchErrorRate"),
        (WordInfoLost, "WordInfoLost"),
        (WordInfoPreserved, "WordInfoPreserved"),
    ],
    ids=["wer", "cer", "mer", "wil", "wip"],
)
def test_edit_distance_family_reference_parity(cls, name):
    _assert_matches_reference(cls(), _ref_cls(name), PREDS, TARGETS)


@pytest.mark.parametrize("n_gram", [1, 2, 4])
@pytest.mark.parametrize("smooth", [False, True])
def test_bleu_reference_grid(n_gram, smooth):
    args = {"n_gram": n_gram, "smooth": smooth}
    _assert_matches_reference(BLEUScore(**args), _ref_cls("BLEUScore", **args), PREDS, MULTI_TARGETS)


@pytest.mark.parametrize("tokenize", ["13a", "char", "none", "intl"])
@pytest.mark.parametrize("lowercase", [False, True])
def test_sacre_bleu_reference_grid(tokenize, lowercase):
    args = {"tokenize": tokenize, "lowercase": lowercase}
    _assert_matches_reference(
        SacreBLEUScore(**args), _ref_cls("SacreBLEUScore", **args), PREDS, MULTI_TARGETS
    )


@pytest.mark.parametrize("normalize", [False, True])
@pytest.mark.parametrize("lowercase", [False, True])
@pytest.mark.parametrize("no_punctuation", [False, True])
def test_ter_reference_grid(normalize, lowercase, no_punctuation):
    args = {"normalize": normalize, "lowercase": lowercase, "no_punctuation": no_punctuation}
    _assert_matches_reference(
        TranslationEditRate(**args), _ref_cls("TranslationEditRate", **args), PREDS, MULTI_TARGETS
    )


@pytest.mark.parametrize("char_order, word_order", [(6, 2), (6, 0), (4, 2)])
@pytest.mark.parametrize("whitespace", [False, True])
def test_chrf_reference_grid(char_order, word_order, whitespace):
    args = {"n_char_order": char_order, "n_word_order": word_order, "whitespace": whitespace}
    _assert_matches_reference(CHRFScore(**args), _ref_cls("CHRFScore", **args), PREDS, MULTI_TARGETS)


def test_chrf_lowercase_and_return_sentence_scores():
    args = {"lowercase": True}
    _assert_matches_reference(CHRFScore(**args), _ref_cls("CHRFScore", **args), PREDS, MULTI_TARGETS)


@pytest.mark.parametrize("language", ["en", "ja"])
def test_eed_reference_grid(language):
    args = {"language": language}
    _assert_matches_reference(
        ExtendedEditDistance(**args), _ref_cls("ExtendedEditDistance", **args), PREDS, TARGETS
    )


# ROUGE is absent from this grid on purpose: the reference implementation
# sentence-splits through nltk's punkt data whenever nltk is importable (a
# download, unavailable offline), so it cannot run here at all. ROUGE parity
# is swept against the rouge_score package — the reference's own test oracle
# — in tests/text/test_rouge.py (keys x use_stemmer x accumulate).


def test_squad_reference_parity():
    preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"},
             {"prediction_text": "the Cat sat", "id": "id2"}]
    targets = [
        {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"},
        {"answers": {"answer_start": [0], "text": ["The cat sat on the mat."]}, "id": "id2"},
    ]
    ours, ref = SQuAD(), _ref_cls("SQuAD")
    ours.update(preds, targets)
    ref.update(preds, targets)
    got, want = ours.compute(), ref.compute()
    for k in ("exact_match", "f1"):
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-5, err_msg=k)


def test_empty_and_identical_edge_cases():
    """Identical pairs score perfectly; empty hypothesis degrades, never
    crashes — same on both implementations."""
    for cls, name in ((WordErrorRate, "WordErrorRate"), (CharErrorRate, "CharErrorRate")):
        ours, ref = cls(), _ref_cls(name)
        preds = ["", "same text"]
        targets = ["non empty reference", "same text"]
        ours.update(preds, targets)
        ref.update(preds, targets)
        np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-6)

    perfect = BLEUScore()
    perfect.update(["the cat"], [["the cat"]])
    assert 0.0 <= float(perfect.compute()) <= 1.0


# ---------------------------------------------------------------------------
# corpus-level parametrization (reference tests/text/inputs.py style): the
# same metric x argument grid over structurally different corpora
# ---------------------------------------------------------------------------

_CORPORA = {
    "short": (
        ["a", "b c", ""],
        [["a"], ["b d"], ["non empty"]],
    ),
    "long_multi_ref": (
        [
            "the quick brown fox jumps over the lazy dog " * 5,
            "pack my box with five dozen liquor jugs and then some more words",
        ],
        [
            ["the quick brown fox jumped over the lazy dog " * 5, "a fox jumps over a dog " * 4],
            ["pack my box with five dozen liquor jugs", "pack a box with liquor jugs quickly"],
        ],
    ),
    "unicode": (
        ["schrodinger's 猫 ist très muñeca", "ασπίδα και δόρυ"],
        [["schrodinger's 猫 ist tres muñeca"], ["ασπίδα και δόρατα"]],
    ),
}


@pytest.mark.parametrize("corpus", list(_CORPORA), ids=list(_CORPORA))
@pytest.mark.parametrize(
    "cls, name, args",
    [
        (BLEUScore, "BLEUScore", {"n_gram": 2}),
        (SacreBLEUScore, "SacreBLEUScore", {"tokenize": "13a"}),
        (SacreBLEUScore, "SacreBLEUScore", {"tokenize": "intl"}),
        (CHRFScore, "CHRFScore", {}),
        (TranslationEditRate, "TranslationEditRate", {}),
    ],
    ids=["bleu2", "sacre13a", "sacreintl", "chrf", "ter"],
)
def test_corpus_grid_multi_reference(cls, name, args, corpus):
    preds, targets = _CORPORA[corpus]
    ours, ref = cls(**args), _ref_cls(name, **args)
    # one-at-a-time updates exercise per-sentence accumulation
    for p, t in zip(preds, targets):
        ours.update([p], [t])
        ref.update([p], [t])
    np.testing.assert_allclose(
        float(ours.compute()), float(ref.compute()), atol=1e-5, err_msg=f"{name} {corpus}"
    )


@pytest.mark.parametrize("corpus", list(_CORPORA), ids=list(_CORPORA))
@pytest.mark.parametrize(
    "cls, name",
    [
        (WordErrorRate, "WordErrorRate"),
        (CharErrorRate, "CharErrorRate"),
        (MatchErrorRate, "MatchErrorRate"),
        (WordInfoLost, "WordInfoLost"),
        (WordInfoPreserved, "WordInfoPreserved"),
    ],
    ids=["wer", "cer", "mer", "wil", "wip"],
)
def test_corpus_grid_single_reference(cls, name, corpus):
    preds, targets = _CORPORA[corpus]
    flat_targets = [t[0] for t in targets]  # WER family takes single references
    ours, ref = cls(), _ref_cls(name)
    ours.update(preds, flat_targets)
    ref.update(preds, flat_targets)
    np.testing.assert_allclose(
        float(ours.compute()), float(ref.compute()), atol=1e-5, err_msg=f"{name} {corpus}"
    )
