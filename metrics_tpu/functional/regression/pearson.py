"""Pearson correlation coefficient — streaming moment accumulators.

Behavior parity with /root/reference/torchmetrics/functional/regression/
pearson.py:22-80. The streaming (mean, var, cov) update is the psum-merge
template for all moment metrics (SURVEY.md §7 stage 7).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_same_shape

Array = jax.Array


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Streaming update of the six moment accumulators."""
    _check_same_shape(preds, target)
    preds = jnp.squeeze(preds)
    target = jnp.squeeze(target)
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")

    n_obs = preds.size
    mx_new = (n_prior * mean_x + jnp.mean(preds) * n_obs) / (n_prior + n_obs)
    my_new = (n_prior * mean_y + jnp.mean(target) * n_obs) / (n_prior + n_obs)
    n_new = n_prior + n_obs
    var_x = var_x + jnp.sum((preds - mx_new) * (preds - mean_x))
    var_y = var_y + jnp.sum((target - my_new) * (target - mean_y))
    corr_xy = corr_xy + jnp.sum((preds - mx_new) * (target - mean_y))

    return mx_new, my_new, var_x, var_y, corr_xy, n_new


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = jnp.squeeze(corr_xy / jnp.sqrt(var_x * var_y))
    return jnp.clip(corrcoef, -1.0, 1.0)


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array]:
    """Merge per-device moment accumulators with the parallel (Chan et al.)
    variance/covariance formula.

    Role parity with reference pearson.py:23-53, but NOT formula parity: the
    reference snapshot's merge scales its variance and covariance terms
    inconsistently (vars as (n-1)-weighted averages of raw sums, cov as an
    n-weighted one), which biases the merged coefficient (fixed upstream in
    later torchmetrics releases). Here the states stay what `_update`
    accumulates — raw centered sums — and merge exactly:

        S = S1 + S2 + n1*n2/(n1+n2) * (m1 - m2)^2           (variance sums)
        C = C1 + C2 + n1*n2/(n1+n2) * (mx1-mx2)*(my1-my2)   (covariance sum)

    so the merged compute matches the single-pass result to rounding. The
    leading dim is the (static) device count; the fold is trace-friendly.
    """
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb
        w = (n1 * n2) / nb
        var_x = vx1 + vx2 + w * (mx1 - mx2) ** 2
        var_y = vy1 + vy2 + w * (my1 - my2) ** 2
        corr_xy = cxy1 + cxy2 + w * (mx1 - mx2) * (my1 - my2)
        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return vx1, vy1, cxy1, n1


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Computes the Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2., 7.])
        >>> preds = jnp.array([2.5, 0.0, 2., 8.])
        >>> pearson_corrcoef(preds, target)
        Array(0.98486954, dtype=float32)
    """
    zero = jnp.asarray(0.0, dtype=jnp.result_type(preds.dtype, jnp.float32))
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zero, zero, zero, zero, zero, jnp.asarray(0.0)
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
