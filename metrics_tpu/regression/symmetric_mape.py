"""Modular SymmetricMeanAbsolutePercentageError.

Behavior parity with /root/reference/torchmetrics/regression/symmetric_mape.py:25-91.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.symmetric_mape import (
    _symmetric_mean_absolute_percentage_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
)

Array = jax.Array


class SymmetricMeanAbsolutePercentageError(Metric):
    """Computes symmetric mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1., 10., 1e6])
        >>> preds = jnp.array([0.9, 15., 1.2e6])
        >>> smape = SymmetricMeanAbsolutePercentageError()
        >>> smape(preds, target)
        Array(0.22902714, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def _compute(self) -> Array:
        return _symmetric_mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)
