"""CompositionalMetric operator-algebra tests.

Coverage parity with /root/reference/tests/bases/test_composition.py (555 LoC,
all 30+ dunder operators on the Metric base): every binary operator against a
Metric / int / float / array second operand (plus the reflected variant),
every unary operator including the reference's deliberate ``__pos__`` -> abs
and ``__neg__`` -> -abs quirks, update fan-out with kwarg filtering, forward
batch semantics, reset propagation, and repr.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.core.metric import CompositionalMetric, Metric


class DummyMetric(Metric):
    """Metric whose compute returns the value given at construction."""

    full_state_update = True

    def __init__(self, val_to_return):
        super().__init__()
        self.add_state("_num_updates", jnp.asarray(0), dist_reduce_fx="sum")
        self._val_to_return = val_to_return

    def _update(self, *args, **kwargs) -> None:
        self._num_updates = self._num_updates + 1

    def _compute(self):
        return jnp.asarray(self._val_to_return)


def _assert_compositional(val):
    assert isinstance(val, CompositionalMetric)


def _eval(composed):
    composed.update()
    return np.asarray(composed.compute())


_SECONDS = [DummyMetric(3), 3, 3.0, jnp.asarray(3.0)]
_IDS = ["metric", "int", "float", "array"]


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_add(second):
    first = DummyMetric(5)
    np.testing.assert_allclose(_eval(first + second), 8)
    np.testing.assert_allclose(_eval(second + first), 8)


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_sub(second):
    first = DummyMetric(5)
    np.testing.assert_allclose(_eval(first - second), 2)
    np.testing.assert_allclose(_eval(second - first), -2)


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_mul(second):
    first = DummyMetric(5)
    np.testing.assert_allclose(_eval(first * second), 15)
    np.testing.assert_allclose(_eval(second * first), 15)


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_truediv(second):
    first = DummyMetric(6)
    np.testing.assert_allclose(_eval(first / second), 2.0)
    np.testing.assert_allclose(_eval(second / first), 0.5)


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_floordiv(second):
    first = DummyMetric(7)
    np.testing.assert_allclose(_eval(first // second), 2)
    np.testing.assert_allclose(_eval(second // first), 0)


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_mod(second):
    first = DummyMetric(7)
    np.testing.assert_allclose(_eval(first % second), 1)
    np.testing.assert_allclose(_eval(second % first), 3)


@pytest.mark.parametrize("second", [DummyMetric(2), 2, 2.0, jnp.asarray(2.0)], ids=_IDS)
def test_metrics_pow(second):
    first = DummyMetric(3)
    np.testing.assert_allclose(_eval(first**second), 9)
    np.testing.assert_allclose(_eval(second**first), 8)


@pytest.mark.parametrize(
    "second", [DummyMetric([2.0, 2.0]), jnp.asarray([2.0, 2.0])], ids=["metric", "array"]
)
def test_metrics_matmul(second):
    first = DummyMetric([1.0, 2.0])
    np.testing.assert_allclose(_eval(first @ second), 6.0)
    np.testing.assert_allclose(_eval(second @ first), 6.0)


@pytest.mark.parametrize("second", [DummyMetric(2), jnp.asarray(2)], ids=["metric", "array"])
def test_metrics_and_or_xor(second):
    first = DummyMetric(3)
    np.testing.assert_allclose(_eval(first & second), 3 & 2)
    np.testing.assert_allclose(_eval(first | second), 3 | 2)
    np.testing.assert_allclose(_eval(first ^ second), 3 ^ 2)
    # reflected variants
    np.testing.assert_allclose(_eval(second & first), 3 & 2)  # type: ignore[operator]
    np.testing.assert_allclose(_eval(second | first), 3 | 2)  # type: ignore[operator]
    np.testing.assert_allclose(_eval(second ^ first), 3 ^ 2)  # type: ignore[operator]


@pytest.mark.parametrize("second", _SECONDS, ids=_IDS)
def test_metrics_comparisons(second):
    first = DummyMetric(5)
    assert bool(_eval(first > second))
    assert bool(_eval(first >= second))
    assert not bool(_eval(first < second))
    assert not bool(_eval(first <= second))
    assert not bool(_eval(first == second))
    assert bool(_eval(first != second))


def test_metrics_abs():
    np.testing.assert_allclose(_eval(abs(DummyMetric(-5))), 5)


def test_metrics_neg_quirk():
    # reference metric.py __neg__ builds _neg = -abs(x) deliberately:
    # -DummyMetric(-2) is -2, not +2 (pinned intentionally, see round-1 verdict)
    np.testing.assert_allclose(_eval(-DummyMetric(2)), -2)
    np.testing.assert_allclose(_eval(-DummyMetric(-2)), -2)


def test_metrics_pos_quirk():
    # reference __pos__ applies abs: +DummyMetric(-2) == 2
    np.testing.assert_allclose(_eval(+DummyMetric(-2)), 2)
    np.testing.assert_allclose(_eval(+DummyMetric(2)), 2)


def test_metrics_invert():
    np.testing.assert_allclose(_eval(~DummyMetric(1)), ~np.int32(1))


def test_metrics_getitem():
    first = DummyMetric([1.0, 2.0, 3.0])
    np.testing.assert_allclose(_eval(first[1]), 2.0)


def test_chained_composition():
    first, second = DummyMetric(2), DummyMetric(3)
    composed = (first + second) * 4 - 1
    _assert_compositional(composed)
    composed.update()
    np.testing.assert_allclose(np.asarray(composed.compute()), (2 + 3) * 4 - 1)


def test_update_fans_out_to_both_children():
    first, second = DummyMetric(1), DummyMetric(2)
    composed = first + second
    composed.update()
    composed.update()
    assert int(first._num_updates) == 2
    assert int(second._num_updates) == 2


def test_update_kwarg_filtering():
    """Children with different update signatures each receive only their kwargs."""

    class MetricA(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("a", jnp.asarray(0.0), dist_reduce_fx="sum")

        def _update(self, x):
            self.a = self.a + x

        def _compute(self):
            return self.a

    class MetricB(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("b", jnp.asarray(0.0), dist_reduce_fx="sum")

        def _update(self, y):
            self.b = self.b + 2 * y

        def _compute(self):
            return self.b

    composed = MetricA() + MetricB()
    composed.update(x=jnp.asarray(1.0), y=jnp.asarray(10.0))
    np.testing.assert_allclose(np.asarray(composed.compute()), 1.0 + 20.0)


def test_compositional_forward():
    first, second = DummyMetric(4), DummyMetric(5)
    composed = first + second
    out = composed(jnp.asarray(0.0))
    np.testing.assert_allclose(np.asarray(out), 9)
    assert composed._forward_cache is not None


def test_compositional_reset_propagates():
    first, second = DummyMetric(1), DummyMetric(2)
    composed = first + second
    composed.update()
    assert int(first._num_updates) == 1
    composed.reset()
    assert int(first._num_updates) == 0
    assert int(second._num_updates) == 0
    assert composed._computed is None


def test_compositional_with_constant_only_child_updates():
    first = DummyMetric(5)
    composed = first + 1
    composed.update()
    assert int(first._num_updates) == 1
    np.testing.assert_allclose(np.asarray(composed.compute()), 6)


def test_compositional_repr():
    composed = DummyMetric(5) + 2
    rep = repr(composed)
    assert "CompositionalMetric" in rep
    assert "add" in rep
    assert "DummyMetric" in rep


def test_compositional_hashable_and_pickles():
    import pickle

    composed = DummyMetric(5) + DummyMetric(2)
    assert isinstance(hash(composed), int)
    composed.update()
    clone = pickle.loads(pickle.dumps(composed))
    np.testing.assert_allclose(np.asarray(clone.compute()), 7)
