"""Fused single-dispatch MetricCollection updates.

A collection of N metrics updated eagerly pays N separate XLA dispatches per
batch (plus one more per metric for the mean-merge counter bump), with host
round-trips between each. This module stitches every member metric's pure
``update_state`` transform into ONE jitted ``(states, batch) -> states``
function, so the whole collection's update is a single device dispatch:

* **Donated state buffers** — the states pytree is passed with
  ``donate_argnums=0`` (on backends that honor donation), so accumulator
  updates are in-place on device instead of allocate-and-copy. Callers must
  not hold outside references to state arrays across a fused update.
* **Signature-keyed compile cache** — entries are keyed on the batch's
  array (shape, dtype) signature, the non-array (static) arguments, the
  fused metric set, and the states' own signature, following the bucketing
  precedent in ``functional/detection/mean_ap.py`` / ``functional/audio/
  stoi.py``. Each entry is AOT-compiled once (``jit -> lower -> compile``)
  and billed to telemetry as its own ``compile`` event.
* **Pad-and-mask shape bucketing** — with ``buckets=(...)``, shape-varying
  batches are edge-padded along the leading axis to the nearest bucket and
  the pad rows' contribution is subtracted inside the kernel (one extra
  single-row update per metric), so ≥3 ragged batch shapes share ONE
  compilation — the exact recompile failure mode the telemetry recorder
  warns about. Exact for sum-reduced states (the pad rows replicate the
  last real row, so their contribution is ``k * delta(last_row)``) and a
  no-op for max/min-reduced states (a replicated row cannot move an
  extremum); metrics with mean/custom/None-reduced array states decline
  bucketing, as does any metric flagging ``__fused_bucket_unsafe__``.
* **Compute-group dedup** — once groups are known, only group leaders are
  updated inside the fused kernel (one update per group, not per metric),
  the same 2-3x sharing the eager path provides.
* **Transparent fallback** — metrics flagged ``__jit_unsafe__``, wrapper
  metrics (child registries), list ("cat") states, and metrics whose update
  fails a one-time trace probe run through the ordinary eager per-metric
  path in the same call, so the fused path composes with any collection.
* **Manifest-seeded fusibility** — the tracelint abstract interpreter
  (``metrics_tpu/analysis/interp.py``) proves fusibility at review time and
  ``scripts/fusibility_manifest.json`` carries the verdicts; a metric whose
  class is verdicted ``fusible`` skips the per-(metric, signature)
  ``jax.eval_shape`` probe entirely, cutting first-batch setup cost. The
  probe remains the authority for ``unknown``/absent classes, and
  ``METRICS_TPU_VERIFY_MANIFEST=1`` runs it anyway as a cross-check
  (warning on disagreement, trusting the probe). A manifest-seeded fused
  build that still fails re-probes the seeded members and retries once, so
  a stale manifest degrades to the eager path instead of crashing.

The auto-registered ``_n_updates`` mean-merge counter is bumped INSIDE the
kernel (once per batch, sentinel-preserving), eliminating the per-metric
``jnp.where`` dispatch of the eager path.

Sliced metrics (``metrics_tpu/sliced/``) ride this path unchanged: a
``SlicedMetric``'s update is a pure segment-scatter over fixed-shape
``[S]``-leading states, so it fuses, donates, and AOT-caches like any other
member — one dispatch ingests a batch spanning thousands of slices. The
pad-and-mask bucket correction stays exact for it too: an edge-padded row
replicates the last real row *including its slice id*, so the
``k * delta(last_row)`` subtraction lands in exactly the slice the pad rows
polluted (and a replicated row cannot move a per-slice extremum).

Windowed metrics (``metrics_tpu/windowed/``) fuse the same way: the ring
rotation is a fixed-shape ``.at[slot].set`` driven by a state-carried
clock. Their sum-shaped leaves carry TAGGED reducers (``windowed_kind``)
rather than ``dim_zero_sum`` on purpose — the generic pad correction
below probes the delta from the DEFAULT state, whose ring slot differs
from the live one, so the wrapper performs its own slot-aware correction
via the ``n_valid`` mask contract and the bucket-eligibility check
accepts the tagged leaves on that basis.
"""
from __future__ import annotations

import os
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# stdlib-only import: the analysis package never pulls jax, so consulting
# the static manifest costs one cached JSON read, not an import cascade
from metrics_tpu.analysis.manifest import (
    ENV_VERIFY_MANIFEST,
    manifest_verdict as _manifest_verdict,
)
from metrics_tpu.analysis.interp import VERDICT_FUSIBLE as _FUSIBLE
from metrics_tpu.core.metric import _AUTO_COUNT, Metric, _coerce_foreign
from metrics_tpu.observability.memory import executable_nbytes, register_cache_plane
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.utils.data import dim_zero_max, dim_zero_min, dim_zero_sum
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

#: telemetry entry point for fused-update signature tracking (the recompile
#: detector) and per-cache-entry compile billing
FUSED_ENTRY = "MetricCollection.fused_update"

#: one-time warning threshold for compile-cache growth — an un-bucketed
#: ragged pipeline (or a per-batch static scalar) compiles per batch, and
#: that must be loud even with telemetry off
_CACHE_WARN_ENTRIES = 16


def _env_flag(name: str) -> bool:
    """Boolean env switch: '0'/'false'/'no'/'off'/'' all read as DISABLED,
    so exporting METRICS_TPU_VERIFY_MANIFEST=0 opts out instead of silently
    enabling verify mode (which would defeat the probe-skip fast path)."""
    return os.environ.get(name, "").strip().lower() not in ("", "0", "false", "no", "off")


def _supports_donation() -> bool:
    """Buffer donation is honored on TPU/GPU; XLA:CPU ignores it (with a
    per-dispatch warning), so donation defaults off there."""
    return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")


def _pure_update(metric: Metric, state: Dict[str, Any], args: Tuple, kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """``(state, batch) -> state`` through the metric's ``_update``, WITHOUT
    the auto-count bump or telemetry — the fused kernel owns both."""
    old = metric._bind(state)
    try:
        metric._update(*args, **kwargs)
        return {k: getattr(metric, k) for k in metric._defaults}
    finally:
        for k, v in old.items():
            object.__setattr__(metric, k, v)


def _state_pytree(metric: Metric) -> Dict[str, Array]:
    """The metric's current array-state pytree (host ints — the eager
    counter fast path — re-materialize as int32 scalars)."""
    out = {}
    for name in metric._defaults:
        val = getattr(metric, name)
        out[name] = jnp.asarray(val, jnp.int32) if isinstance(val, int) else jnp.asarray(val)
    return out


def _default_pytree(metric: Metric) -> Dict[str, Array]:
    return {k: jnp.asarray(v) for k, v in metric._defaults.items()}


class _CacheEntry:
    __slots__ = ("fn", "aot", "index", "calls", "nbytes")

    def __init__(self, fn: Any, aot: bool, index: int, nbytes: int = 0) -> None:
        self.fn = fn
        self.aot = aot
        self.index = index
        self.calls = 0
        #: device bytes the compiled executable holds (compiler-reported
        #: code + temp buffers; 0 for the non-AOT fallback and on backends
        #: that report nothing) — the ``fused_compile`` plane sums these
        self.nbytes = nbytes


#: every live FusedUpdate handle (weak — handles die with their collection);
#: the ``fused_compile`` memory plane fans out over this set
_LIVE_FUSED: "weakref.WeakSet[FusedUpdate]" = weakref.WeakSet()


def _fused_plane_nbytes() -> int:
    return sum(
        e.nbytes for h in list(_LIVE_FUSED) for e in list(h._cache.values())
    )


class FusedUpdate:
    """Handle returned by :meth:`MetricCollection.compile_update`.

    Calling the handle (or ``collection.update(...)`` once compiled) runs
    the fused single-dispatch update. ``buckets`` enables pad-and-mask
    shape bucketing along ``axis 0``; ``donate`` overrides the
    backend-derived buffer-donation default.
    """

    def __init__(
        self,
        collection: Any,
        buckets: Optional[Sequence[int]] = None,
        donate: Optional[bool] = None,
        use_manifest: Optional[bool] = None,
    ) -> None:
        self._collection = collection
        self._buckets: Tuple[int, ...] = tuple(sorted(int(b) for b in buckets)) if buckets else ()
        if any(b <= 0 for b in self._buckets):
            raise ValueError(f"bucket sizes must be positive, got {self._buckets}")
        self._donate = _supports_donation() if donate is None else bool(donate)
        # static-manifest consultation default-on; METRICS_TPU_NO_MANIFEST
        # (handled inside manifest.py) or use_manifest=False turn it off
        self._use_manifest = True if use_manifest is None else bool(use_manifest)
        #: the config as REQUESTED — `_use_manifest` can be demoted to False
        #: at runtime by the stale-manifest safety net, and warm reuse must
        #: keep matching the original request or an epoch loop rebuilds a
        #: fresh manifest-trusting handle that re-hits the same stale
        #: manifest (and re-warns, and re-probes) every epoch
        self._requested_manifest = self._use_manifest
        self._cache: Dict[Tuple, _CacheEntry] = {}
        self._fusible: Dict[Tuple, bool] = {}
        #: (name, sig) keys whose fusibility came from the static manifest
        #: WITHOUT a runtime probe — the retry safety net re-probes exactly
        #: these if a fused build ever fails
        self._manifest_seeded: set = set()
        self.manifest_probe_skips = 0
        self._bucket_ok: Dict[Tuple[str, ...], bool] = {}
        self._bucket_warned = False
        self.n_compiles = 0
        #: members the runtime probe (or a manifest demotion) routed to the
        #: eager fallback for at least one signature — their buffers stay
        #: alive through an eager update, so donated_state_bytes() must not
        #: count them as dispatch-owned. Grows monotonically; its size is
        #: part of the donated-bytes cache key.
        self._eager_names: set = set()
        self._donated_bytes_cache: Optional[Tuple[Tuple[bool, int], int]] = None
        _LIVE_FUSED.add(self)

    # compiled executables (and the collection back-reference) must not be
    # deep-copied: MetricCollection.clone() drops the handle and the clone
    # re-compiles on its own first fused call
    def __deepcopy__(self, memo: Dict) -> None:
        return None

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    @property
    def donating(self) -> bool:
        """Whether dispatches donate the state buffers (in-place accumulator
        updates). While a donating dispatch is in flight the PREVIOUS state
        arrays are dead — the async pipeline (core/pipeline.py) keys its
        in-flight buffer-ownership accounting on this flag."""
        return self._donate

    def config_matches(
        self,
        buckets: Optional[Sequence[int]] = None,
        donate: Optional[bool] = None,
        use_manifest: Optional[bool] = None,
    ) -> bool:
        """True when a ``compile_update(...)`` request resolves to this
        handle's exact config — the warm-reuse test that lets an epoch
        loop's ``reset(); compile_update_async()`` keep the compile cache
        instead of paying a fresh XLA build."""
        want_buckets = tuple(sorted(int(b) for b in buckets)) if buckets else ()
        want_donate = _supports_donation() if donate is None else bool(donate)
        want_manifest = True if use_manifest is None else bool(use_manifest)
        return (
            self._buckets == want_buckets
            and self._donate == want_donate
            # compare the REQUEST, not the live flag: a runtime stale-
            # manifest demotion must survive warm reuse, not be rebuilt away
            and self._requested_manifest == want_manifest
        )

    def donated_state_bytes(self) -> int:
        """Unique state bytes a donating dispatch takes ownership of:
        compute-group leaders only (members borrow the leader's arrays, so
        counting them would double-book the same buffers), and only members
        that can reach the fused kernel — eager fallbacks (jit-unsafe,
        wrapper, list-state, and members the runtime probe or a manifest
        demotion rejected) update in the calling thread and keep their
        buffers alive throughout. The async worker calls this per batch, so
        the O(n_metrics) state walk is cached — fused state shapes are fixed
        by contract, and the only structural shifts while a handle is open
        are group discovery flipping ``_groups_checked`` and probe demotions
        growing ``_eager_names``, both part of the cache key (membership
        changes go through add_metrics/reset, which drop the handle)."""
        col = self._collection
        key = (col._groups_checked, len(self._eager_names))
        cached = self._donated_bytes_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        if col._groups_checked:
            names = [cg[0] for cg in col._groups.values()]
        else:
            names = list(col._metrics)
        total = 0
        for name in names:
            if self._never_fused(name):
                continue
            total += col._metrics[name].total_state_bytes()
        self._donated_bytes_cache = (key, total)
        return total

    @staticmethod
    def _static_unfusible(m: Any) -> bool:
        """The structural exclusions shared by the fusibility check and
        donated-byte accounting — ``__jit_unsafe__``, wrapper children,
        list-valued state (declared default or current value). One
        predicate so the two call sites cannot drift."""
        if getattr(m, "__jit_unsafe__", False) or m._children:
            return True
        return any(isinstance(v, list) for v in m._defaults.values()) or any(
            isinstance(getattr(m, k), list) for k in m._defaults
        )

    def _never_fused(self, name: str) -> bool:
        """Static exclusions plus learned ones: members the runtime probe
        (or a manifest demotion) already routed to the eager fallback for
        some signature. A member excluded here updates eagerly, keeps its
        buffers alive, and must never be booked as dispatch-owned.
        (Per-name and conservative on purpose — ``_is_fusible`` stays
        per-signature, so a mixed-signature member may still fuse for
        other signatures while its bytes are left uncounted.)"""
        return (
            self._static_unfusible(self._collection._metrics[name])
            or name in self._eager_names
        )

    # ------------------------------------------------------------------
    # fusibility / bucket eligibility
    # ------------------------------------------------------------------
    def _is_fusible(self, name: str, args: Tuple, kwargs: Dict[str, Any], sig: Tuple) -> bool:
        m = self._collection._metrics[name]
        if self._static_unfusible(m):
            return False
        key = (name, sig)
        cached = self._fusible.get(key)
        if cached is not None:
            return cached
        verify = _env_flag(ENV_VERIFY_MANIFEST)
        if self._use_manifest and not verify:
            # manifest-seeded fast path: a class the abstract interpreter
            # proved fusible skips the eval_shape probe for every signature
            if _manifest_verdict(type(m)) == _FUSIBLE:
                self._fusible[key] = True
                self._manifest_seeded.add(key)
                self.manifest_probe_skips += 1
                return True
        # one-time trace probe: host-dependent updates (concrete value
        # checks, data-dependent shapes) surface here instead of crashing
        # the fused kernel build
        try:
            fkw = m._filter_kwargs(**kwargs)
            jax.eval_shape(lambda s, a, kw: _pure_update(m, s, a, kw), _state_pytree(m), args, fkw)
            ok = True
        except Exception:
            ok = False
        if verify and self._use_manifest:
            static = _manifest_verdict(type(m))
            if static == _FUSIBLE and not ok:
                rank_zero_warn(
                    f"fusibility manifest says `{type(m).__name__}` is fusible but the"
                    " eval_shape probe disagrees for this signature; trusting the probe."
                    " The committed manifest is stale — regenerate with"
                    " `python scripts/tracelint.py --manifest`.",
                    UserWarning,
                )
        self._fusible[key] = ok
        if not ok:
            self._eager_names.add(name)
        return ok

    def _bucket_eligible(self, names: List[str]) -> bool:
        key = tuple(names)
        cached = self._bucket_ok.get(key)
        if cached is None:
            cached = self._bucket_ok[key] = self._bucket_eligible_uncached(names)
        return cached

    def _bucket_eligible_uncached(self, names: List[str]) -> bool:
        for name in names:
            m = self._collection._metrics[name]
            if getattr(m, "__fused_bucket_unsafe__", False):
                return False
            mask_valid = bool(getattr(m, "__fused_mask_valid__", False))
            for sname, red in m._reductions.items():
                if sname == _AUTO_COUNT:
                    continue  # bumped once per batch; padding cannot skew it
                if getattr(red, "merge_like", False) and mask_valid:
                    # sketch leaves on a metric that accepts the n_valid
                    # pad-mask kwarg: edge-pad rows insert with weight 0
                    # instead of needing an (impossible) subtraction — see
                    # _one_metric, which threads n_valid into the update
                    continue
                if getattr(red, "windowed_kind", None) is not None and mask_valid:
                    # windowed ring/decay leaves (metrics_tpu/windowed/): the
                    # wrapper receives n_valid and performs its own slot-aware
                    # k * delta pad correction — the generic dim_zero_sum
                    # correction below would probe from the DEFAULT state's
                    # ring slot and double-correct, which is exactly why
                    # these leaves carry a tagged reducer instead of sum
                    continue
                if red not in (dim_zero_sum, dim_zero_max, dim_zero_min):
                    return False
                default = m._defaults[sname]
                if red is dim_zero_sum and getattr(default, "dtype", None) == jnp.bool_:
                    return False
        return True

    # ------------------------------------------------------------------
    # call path
    # ------------------------------------------------------------------
    def __call__(self, *args: Any, **kwargs: Any) -> None:
        self.dispatch(args, kwargs)

    def dispatch(self, args: Tuple, kwargs: Dict[str, Any]) -> None:
        """Non-blocking fused dispatch on a pre-packed ``(args, kwargs)``
        batch — the entry point the async pipeline's worker calls. Returns
        as soon as XLA has enqueued the kernel (JAX's async dispatch): no
        ``block_until_ready``, no scalar readback, so the caller (a worker
        thread overlapping ingest with device compute) never stalls on
        device completion. The only host-synchronizing work on this path is
        one-time: first-call compute-group discovery and eager fallbacks
        for jit-unsafe members, both of which run in the calling thread."""
        col = self._collection
        rec = _TELEMETRY if _TELEMETRY.enabled else None
        t0 = time.perf_counter() if rec is not None else 0.0
        args = _coerce_foreign(args)
        kwargs = _coerce_foreign(kwargs)

        if col._groups_checked:
            leaders = [cg[0] for cg in col._groups.values()]
        else:
            leaders = list(col._metrics)

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        # floats trace as 0-d arrays: a per-batch Python scalar (a weight, a
        # threshold) must not key the compile cache by VALUE, or every batch
        # recompiles. Ints/bools/strings stay static — they are commonly
        # structural (top_k, flags); a metric that needs a float concrete
        # fails the fusibility probe and falls back to the eager path.
        dyn_idx = {
            i
            for i, leaf in enumerate(leaves)
            if isinstance(leaf, (jnp.ndarray, np.ndarray, float))
        }
        dyn = [jnp.asarray(leaves[i]) for i in sorted(dyn_idx)]
        static = tuple((i, leaves[i]) for i in range(len(leaves)) if i not in dyn_idx)
        sig = tuple((tuple(x.shape), str(x.dtype)) for x in dyn)

        fused_set = {n for n in leaders if self._is_fusible(n, args, kwargs, sig)}
        fused_names = [n for n in leaders if n in fused_set]
        fallback_names = [n for n in leaders if n not in fused_set]

        # eager fallback keeps the ordinary per-metric lifecycle (telemetry,
        # coercion already done) — with group attribution intact
        member_of = {cg[0]: cg for cg in col._groups.values()} if col._groups_checked else {}
        for name in fallback_names:
            m = col._metrics[name]
            group = member_of.get(name, [name])
            if rec is not None and len(group) > 1:
                with rec.group_attribution(group):
                    m.update(*args, **m._filter_kwargs(**kwargs))
            else:
                m.update(*args, **m._filter_kwargs(**kwargs))

        bucket = cache_hit = None
        if fused_names:
            try:
                bucket, cache_hit = self._run_fused(fused_names, treedef, dyn, static, sig)
            except Exception:
                if not any((n, sig) in self._manifest_seeded for n in fused_names):
                    raise  # no static seed involved: a genuine bug, not a stale manifest
                # stale-manifest safety net: the build trusted a static
                # `fusible` verdict that the tracer just refuted. Stop
                # trusting the manifest for this handle, re-probe every
                # previously-seeded member, run the refuted ones eagerly,
                # and retry the (now probe-verified) fused set once.
                rank_zero_warn(
                    "fused update build failed for a manifest-seeded metric set; "
                    "the committed fusibility manifest is stale. Falling back to "
                    "eval_shape probes for this collection — regenerate with "
                    "`python scripts/tracelint.py --manifest`.",
                    UserWarning,
                )
                self._use_manifest = False
                for key in list(self._manifest_seeded):
                    self._fusible.pop(key, None)
                self._manifest_seeded.clear()
                retry_set = {n for n in fused_names if self._is_fusible(n, args, kwargs, sig)}
                demoted = [n for n in fused_names if n not in retry_set]
                # demoted members take the ordinary eager fallback path,
                # including group attribution, and are counted as fallbacks
                for name in demoted:
                    m = col._metrics[name]
                    group = member_of.get(name, [name])
                    if rec is not None and len(group) > 1:
                        with rec.group_attribution(group):
                            m.update(*args, **m._filter_kwargs(**kwargs))
                    else:
                        m.update(*args, **m._filter_kwargs(**kwargs))
                fallback_names = fallback_names + demoted
                fused_names = [n for n in fused_names if n in retry_set]
                if fused_names:
                    bucket, cache_hit = self._run_fused(fused_names, treedef, dyn, static, sig)

        if not col._groups_checked and col._enable_compute_groups:
            # first-call group discovery on the concrete post-update states
            # (the eager path's semantics); the NEXT call fuses leaders only
            col._merge_compute_groups()
            col._groups_checked = True

        if rec is not None:
            rec.record_fused_update(
                n_metrics=len(col._metrics),
                n_fused=len(fused_names),
                n_fallback=len(fallback_names),
                duration_s=time.perf_counter() - t0,
                # leading-axis row count of the batch (host shape read): the
                # windowed ingest_rows series turns it into a rolling
                # rows/sec rate for the serving observatory
                batch_rows=next(
                    (int(x.shape[0]) for x in dyn if getattr(x, "ndim", 0) >= 1), None
                ),
                n_groups=len(col._groups) if col._groups_checked else None,
                bucket=bucket,
                cache_entries=len(self._cache),
                cache_hit=cache_hit,
                # sliced members served by this dispatch (duck-typed on the
                # slice-count attribute to keep the hot path import-free):
                # one fused kernel ingesting a batch that fans out across
                # num_slices segments per such member
                n_sliced=sum(
                    1
                    for n in fused_names
                    if getattr(col._metrics[n], "num_slices", None) is not None
                ),
            )

    def _run_fused(
        self,
        names: List[str],
        treedef: Any,
        dyn: List[Array],
        static: Tuple,
        sig: Tuple,
    ) -> Tuple[Optional[int], bool]:
        col = self._collection
        bucket = self._pick_bucket(dyn, names)
        n_valid = None
        if bucket is not None:
            n = next(int(x.shape[0]) for x in dyn if x.ndim >= 1)
            n_valid = jnp.asarray(n, jnp.int32)
            if bucket != n:
                dyn = [
                    jnp.pad(x, [(0, bucket - n)] + [(0, 0)] * (x.ndim - 1), mode="edge")
                    if x.ndim >= 1
                    else x
                    for x in dyn
                ]
            sig = tuple((tuple(x.shape), str(x.dtype)) for x in dyn)

        states = {name: _state_pytree(col._metrics[name]) for name in names}
        state_sig = tuple(
            (name, k, tuple(v.shape), str(v.dtype)) for name in names for k, v in states[name].items()
        )
        static_sig = tuple((i, repr(v)) for i, v in static)
        # the ops-dispatch routing state (backend, METRICS_TPU_NO_PALLAS,
        # forced interpret/jnp test mode) is resolved at TRACE time by the
        # kernels this update traces through (_bincount, the sliced scatter,
        # sketch compaction); folding it into the cache key keeps the
        # documented runtime kill switch honest — a flipped env var must
        # recompile, not keep executing the suspect kernel from a stale trace
        from metrics_tpu.ops.dispatch import dispatch_mode

        key = (tuple(names), treedef, sig, static_sig, state_sig, bucket, dispatch_mode())

        entry = self._cache.get(key)
        cache_hit = entry is not None
        if entry is None:
            entry = self._compile(key, names, treedef, static, bucket, states, dyn, n_valid)
            if len(self._cache) == _CACHE_WARN_ENTRIES:
                if _TELEMETRY.enabled:
                    _TELEMETRY.record_cache_plane(
                        "fused_compile",
                        entries=len(self._cache),
                        nbytes=sum(e.nbytes for e in self._cache.values()),
                        reason="growth_warning",
                    )
                rank_zero_warn(
                    f"compile_update: the fused compile cache now holds"
                    f" {_CACHE_WARN_ENTRIES} entries — shape-varying batches (or a"
                    " per-batch static argument such as a Python int) are"
                    " recompiling the fused kernel repeatedly. Pass"
                    " `compile_update(buckets=...)` to collapse ragged batch"
                    " sizes, and pass per-batch scalars as floats or 0-d arrays"
                    " so they trace instead of keying the cache.",
                    UserWarning,
                )
        if _TELEMETRY.enabled:
            # feed the recompile detector: bucketed shapes collapse to one
            # signature here, un-bucketed ragged batches accumulate and trip
            # the standard recompile warning
            _TELEMETRY.track_signature(FUSED_ENTRY, signature=(sig, static_sig, bucket))

        entry.calls += 1
        if bucket is not None:
            new_states = entry.fn(states, dyn, n_valid)
        else:
            new_states = entry.fn(states, dyn)

        member_of = {cg[0]: cg for cg in col._groups.values()} if col._groups_checked else {}
        for name in names:
            for mname in member_of.get(name, [name]):
                # group members get the leader's NEW arrays too: after a
                # donating update the previous arrays are dead buffers, and
                # compute() installed exactly those into the members — they
                # must never be left pointing at donated memory
                m = col._metrics[mname]
                for k, v in new_states[name].items():
                    object.__setattr__(m, k, v)
                m._mark_fused_written()
        return bucket, cache_hit

    def _pick_bucket(self, dyn: List[Array], names: List[str]) -> Optional[int]:
        if not self._buckets or not dyn:
            return None
        # scalar leaves (traced Python floats, 0-d arrays) ride along
        # unpadded; bucketing keys on the batched (ndim >= 1) leaves
        batched = [x for x in dyn if x.ndim >= 1]
        if not batched:
            return None
        n = int(batched[0].shape[0])
        if n == 0:  # an empty batch has no last row to edge-pad from
            return None
        if any(int(x.shape[0]) != n for x in batched):
            return None
        if not self._bucket_eligible(names):
            if not self._bucket_warned:
                self._bucket_warned = True
                rank_zero_warn(
                    "compile_update: shape bucketing is disabled for this collection —"
                    " a fused metric carries a mean/custom/None-reduced (or"
                    " `__fused_bucket_unsafe__`) state with no exact pad correction."
                    " Batches compile per exact shape instead.",
                    UserWarning,
                )
            return None
        for b in self._buckets:
            if b >= n:
                return b
        return None  # larger than every bucket: exact-shape entry

    # ------------------------------------------------------------------
    # kernel build + AOT compile
    # ------------------------------------------------------------------
    def _compile(
        self,
        key: Tuple,
        names: List[str],
        treedef: Any,
        static: Tuple,
        bucket: Optional[int],
        states: Dict[str, Dict[str, Array]],
        dyn: List[Array],
        n_valid: Optional[Array],
    ) -> _CacheEntry:
        col_metrics = self._collection._metrics
        static_map = dict(static)
        n_leaves = len(static) + len(dyn)
        dyn_pos = [i for i in range(n_leaves) if i not in static_map]

        def rebuild(dyn_leaves: List[Array]) -> Tuple[Tuple, Dict[str, Any]]:
            leaves: List[Any] = [None] * n_leaves
            for i, v in static_map.items():
                leaves[i] = v
            for pos, v in zip(dyn_pos, dyn_leaves):
                leaves[pos] = v
            return jax.tree_util.tree_unflatten(treedef, leaves)

        def _one_metric(name: str, state: Dict[str, Array], dyn_leaves: List[Array], k_pad: Optional[Array]) -> Dict[str, Array]:
            m = col_metrics[name]
            args, kwargs = rebuild(dyn_leaves)
            fkw = m._filter_kwargs(**kwargs)
            if k_pad is not None and getattr(m, "__fused_mask_valid__", False):
                # pad-and-mask for merge-leaf (sketch) states: the metric's
                # update masks rows past n_valid to weight 0, so pad rows
                # never enter the sketch; its sum-reduced leaves still take
                # the ordinary k * delta correction below
                fkw = dict(fkw)
                fkw["n_valid"] = jnp.asarray(bucket, jnp.int32) - k_pad
            new = _pure_update(m, state, args, fkw)
            if k_pad is not None:
                # pad rows replicate the last real row: their contribution to
                # a sum-reduced state is k * delta(last_row); max/min states
                # cannot be moved by a replicated row and need no correction
                pad_args, pad_kwargs = rebuild([x[-1:] if x.ndim >= 1 else x for x in dyn_leaves])
                pad_fkw = m._filter_kwargs(**pad_kwargs)
                init = _default_pytree(m)
                d = _pure_update(m, init, pad_args, pad_fkw)
                for s, v in new.items():
                    if s != _AUTO_COUNT and m._reductions[s] is dim_zero_sum:
                        delta = d[s] - init[s]
                        new[s] = v - delta * k_pad.astype(jnp.result_type(delta))
            if _AUTO_COUNT in new:
                c = new[_AUTO_COUNT]
                new[_AUTO_COUNT] = jnp.where(c < 0, c, c + 1)
            return new

        if bucket is not None:
            def raw(states_in, dyn_leaves, n_ok):
                k_pad = jnp.asarray(bucket, jnp.int32) - n_ok
                return {n: _one_metric(n, states_in[n], dyn_leaves, k_pad) for n in names}
            example = (states, dyn, n_valid)
        else:
            def raw(states_in, dyn_leaves):
                return {n: _one_metric(n, states_in[n], dyn_leaves, None) for n in names}
            example = (states, dyn)

        index = self.n_compiles
        label = f"{FUSED_ENTRY}[{index}]"
        jitted = jax.jit(raw, donate_argnums=(0,) if self._donate else ())
        t0 = time.perf_counter()
        try:
            lowered = jitted.lower(*example)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            entry = _CacheEntry(
                compiled, aot=True, index=index, nbytes=executable_nbytes(compiled)
            )
        except Exception:
            # AOT pipeline unavailable: fall back to the jitted callable
            # (jax's own cache compiles on first call instead)
            t1 = t2 = time.perf_counter()
            compiled = None
            entry = _CacheEntry(jitted, aot=False, index=index)

        self.n_compiles += 1
        self._cache[key] = entry
        if _TELEMETRY.enabled:
            cost: Dict[str, float] = {}
            memory: Dict[str, int] = {}
            if compiled is not None:
                from metrics_tpu.observability.profiling import _normalize_cost, _normalize_memory, _try

                cost = _normalize_cost(_try(compiled.cost_analysis))
                memory = _normalize_memory(_try(compiled.memory_analysis))
            # per-cache-entry compile billing: each entry is its own labelled
            # compile event, so the recompile count is priced entry by entry
            _TELEMETRY.record_compile(
                label,
                trace_s=t1 - t0,
                lower_s=0.0,
                compile_s=t2 - t1,
                cost=cost or None,
                memory=memory or None,
                n_fused_metrics=len(names),
                bucket=bucket,
                donated=self._donate and entry.aot,
            )
        return entry


# one plane per cache KIND (see observability/memory.py): the fused compile
# cache's device bytes, summed over every live handle's entries
register_cache_plane("fused_compile", _fused_plane_nbytes)
