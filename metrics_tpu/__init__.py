"""metrics_tpu — a TPU-native (JAX/XLA) machine-learning metrics framework.

Capability parity target: TorchMetrics v0.8.0dev (/root/reference). Exports
grow as domains land; see SURVEY.md §2.8 for the full target inventory.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.classification import (  # noqa: E402
    Accuracy,
    F1Score,
    FBetaScore,
    HammingDistance,
    Precision,
    Recall,
    Specificity,
    StatScores,
)

__all__ = [
    "Accuracy",
    "CompositionalMetric",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "Metric",
    "Precision",
    "Recall",
    "Specificity",
    "StatScores",
]
