"""Test session configuration: force CPU with 8 virtual devices so mesh /
collective tests run without TPU hardware (SURVEY.md §4 implication).
Setup logic is shared with the repo-root conftest via
tests/helpers/force_cpu.py."""
import os

from tests.helpers.force_cpu import setup_forced_cpu

setup_forced_cpu()

import jax  # noqa: E402

if not os.environ.get("METRICS_TPU_TEST_ON_TPU"):
    assert jax.device_count() >= 8, f"expected >=8 virtual devices, got {jax.device_count()}"
