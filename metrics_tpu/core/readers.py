"""AOT-compiled reader executables for the incremental read plane.

Read-side kernels (subset gathers, top-k selection, partial window folds)
historically re-traced per call-site shape: ``compute(slice_ids=ids)``
compiled once per distinct subset length, ``compute(top_k=k)`` once per
distinct ``k``, and the sketch/window folds once per fill count. Each
retrace is tens of milliseconds of host work on a path whose budget is a
serving-loop probe tick.

This module fixes the class of problem once:

* **Shape buckets** (:func:`round_up_bucket`) collapse the family of read
  shapes to a small fixed set — callers pad their index vector up to the
  bucket (:func:`pad_ids`, repeating the last id: re-reading a slice is
  idempotent, so the padding rows are exact no-ops on the result prefix).
* **A reader cache** (:class:`ReaderCache`) holds pre-lowered
  ``jax.jit(fn).lower(...).compile()`` executables keyed on
  ``(kind, shape-bucket, input signature, dispatch_mode())``. The ops
  dispatch mode is part of the key for the same reason it keys the fused
  update cache (core/fused.py): a flipped ``METRICS_TPU_NO_PALLAS`` /
  forced-backend test mode must recompile the reader, not keep serving a
  stale trace of the disabled kernel.

Readers are pure jnp programs, so AOT compilation changes WHEN the compile
happens, never WHAT is computed — the bit-parity discipline of the
incremental read plane (docs/incremental_reads.md) is untouched.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from metrics_tpu.observability.memory import executable_nbytes, register_cache_plane

#: every live ReaderCache instance (weak — caches die with their owning
#: metric); the ``reader_cache`` memory plane below fans out over this set
_LIVE_READER_CACHES: "weakref.WeakSet[ReaderCache]" = weakref.WeakSet()


def _reader_plane_nbytes() -> int:
    return sum(c.nbytes() for c in list(_LIVE_READER_CACHES))

#: the small bucket family read shapes round up into; reads larger than the
#: last entry double from there (and every bucket is capped at the caller's
#: axis size, so a full read never pads)
DEFAULT_ID_BUCKETS: Tuple[int, ...] = (8, 64, 512, 4096)

#: reader-cache entries per instance before the leak warning fires — the
#: key space is (kinds x buckets x dispatch modes), all small and bounded,
#: so unbounded growth means a caller is keying on something per-call
READER_CACHE_WARN_ENTRIES = 64


def round_up_bucket(
    n: int, cap: Optional[int] = None, buckets: Tuple[int, ...] = DEFAULT_ID_BUCKETS
) -> int:
    """Smallest bucket ``>= n`` from the family (doubling past the last
    entry), capped at ``cap`` (the axis size — a full-axis read is its own
    exact bucket)."""
    n = max(int(n), 1)
    if cap is not None and n >= cap:
        return cap
    for b in buckets:
        if b >= n:
            return min(b, cap) if cap is not None else b
    b = buckets[-1]
    while b < n:
        b *= 2
    return min(b, cap) if cap is not None else b


def pad_ids(ids: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a 1-D host id vector up to ``bucket`` rows by repeating the last
    id (int32). Re-reading an id is idempotent, so padded rows change
    nothing; callers slice the result back to the real prefix."""
    ids = np.asarray(ids, dtype=np.int32).reshape(-1)
    if ids.size == 0:
        raise ValueError("pad_ids: cannot pad an empty id vector")
    if ids.size >= bucket:
        return ids[:bucket]
    return np.concatenate([ids, np.full(bucket - ids.size, ids[-1], np.int32)])


def _leaf_sig(leaf: Any) -> Tuple[Tuple[int, ...], str]:
    """Shape/dtype signature WITHOUT materializing the leaf — `np.asarray`
    on a device array would drag the whole state to host per cache probe."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        arr = np.asarray(leaf)
        shape, dtype = arr.shape, arr.dtype
    return (tuple(shape), str(dtype))


class ReaderCache:
    """Per-owner cache of pre-lowered read executables.

    ``get(kind, build, *args, bucket=...)`` returns a compiled executable
    for ``build()`` (a zero-arg factory returning the pure reader function)
    specialized to the argument shapes/dtypes — compiling it on first use
    and replaying the XLA executable afterwards. One instance lives on each
    metric that serves incremental reads, so the closure identity problem
    (readers close over the wrapped template) never reaches the key.
    """

    def __init__(self) -> None:
        self._cache: Dict[Tuple, Any] = {}
        self._fast: Dict[Tuple, Any] = {}
        self._nbytes: Dict[Tuple, int] = {}
        self._warned = False
        _LIVE_READER_CACHES.add(self)

    def __len__(self) -> int:
        return len(self._cache)

    def nbytes(self) -> int:
        """Device bytes the cached executables hold (code + temp buffers,
        per the compiler's own ``memory_analysis``; 0 where the backend
        reports none, e.g. CPU) — this cache's contribution to the
        ``reader_cache`` memory plane."""
        return sum(self._nbytes.values())

    # compiled XLA executables are neither copyable nor picklable; a
    # cloned/restored metric starts with a cold reader cache and re-lowers
    # on first read — behavior, not results, so parity is unaffected
    def __deepcopy__(self, memo: Dict) -> "ReaderCache":
        return ReaderCache()

    def __getstate__(self) -> Dict:
        return {}

    def __setstate__(self, state: Dict) -> None:
        self.__init__()

    def clear(self) -> None:
        self._cache.clear()
        self._fast.clear()
        self._nbytes.clear()

    def fast(self, kind: str, bucket: Optional[int]) -> Optional[Callable]:
        """Signature-free probe: the executable the last :meth:`get` for
        ``(kind, bucket)`` under the current dispatch mode resolved to.

        Hashing the full leaf signature costs tens of microseconds per
        probe — real money on a sub-millisecond incremental read. An owner
        whose state shapes/dtypes are fixed for its lifetime (and who calls
        :meth:`clear` on the mutations that do change them, e.g.
        ``set_dtype``) can probe this first and fall back to :meth:`get`
        on a miss."""
        from metrics_tpu.ops.dispatch import dispatch_mode

        return self._fast.get((kind, bucket, dispatch_mode()))

    def get(
        self,
        kind: str,
        build: Callable[[], Callable],
        *example_args: Any,
        bucket: Optional[int] = None,
    ) -> Callable:
        from metrics_tpu.ops.dispatch import dispatch_mode

        mode = dispatch_mode()
        sig = tuple(_leaf_sig(leaf) for leaf in jax.tree_util.tree_leaves(example_args))
        key = (kind, bucket, sig, mode)
        entry = self._cache.get(key)
        if entry is None:
            entry = jax.jit(build()).lower(*example_args).compile()
            self._cache[key] = entry
            self._nbytes[key] = executable_nbytes(entry)
            if len(self._cache) == READER_CACHE_WARN_ENTRIES and not self._warned:
                self._warned = True
                from metrics_tpu.observability.recorder import _DEFAULT_RECORDER
                from metrics_tpu.utils.prints import rank_zero_warn

                if _DEFAULT_RECORDER.enabled:
                    # typed event carrying entries + bytes: the fleet alarms
                    # on reader-cache bloat instead of losing it to stderr
                    _DEFAULT_RECORDER.record_cache_plane(
                        "reader_cache",
                        entries=len(self._cache),
                        nbytes=self.nbytes(),
                        reason="growth_warning",
                    )
                rank_zero_warn(
                    f"ReaderCache: {READER_CACHE_WARN_ENTRIES} reader executables"
                    " cached on one metric — a read path is keying on a per-call"
                    " quantity instead of a shape bucket (see"
                    " metrics_tpu/core/readers.py).",
                    UserWarning,
                )
        self._fast[(kind, bucket, mode)] = entry
        return entry


# one plane per cache KIND: the callback fans out over live instances, so
# per-metric caches come and go without registry churn (idempotent —
# re-import under a reloaded module simply replaces the callback)
register_cache_plane("reader_cache", _reader_plane_nbytes)
