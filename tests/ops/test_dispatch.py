"""The shared ops dispatch layer: registry, routing, escape hatches,
observability. Runs entirely on CPU — TPU routing is proven with a faked
``jax.default_backend`` exactly like the box-IoU f64 routing test (a wrong
route would attempt a real ``pallas_call`` on CPU and crash)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import ops
from metrics_tpu.ops.dispatch import choose_backend
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER


@pytest.fixture(autouse=True)
def _clean_recorder():
    yield
    _DEFAULT_RECORDER.disable()
    _DEFAULT_RECORDER.reset()


def test_registry_holds_the_suite():
    names = ops.kernel_names()
    for expected in ("bincount", "box_iou", "qsketch_compact", "segment_max", "segment_min", "segment_sum"):
        assert expected in names


def test_get_kernel_unknown_name_raises():
    with pytest.raises(KeyError, match="no kernel 'nope'"):
        ops.get_kernel("nope")


def test_register_requires_callable_fallback():
    with pytest.raises(TypeError, match="jnp_fn must be callable"):
        ops.register_kernel("bad", pallas_fn=None, jnp_fn=None)


def test_jnp_only_op_never_routes_pallas(monkeypatch):
    # segment_max/min grew real kernels (PR 15), so the jnp-only contract
    # is pinned on a synthetic slot the way future reservations register
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    spec = ops.register_kernel("_test_jnp_only", pallas_fn=None, jnp_fn=lambda x: x)
    try:
        assert choose_backend(spec, jnp.ones((512,))) == "jnp"
    finally:
        import sys

        _d = sys.modules["metrics_tpu.ops.dispatch"]  # package attr is the function
        with _d._REGISTRY_LOCK:
            _d._REGISTRY.pop("_test_jnp_only", None)


def test_route_respected_on_fake_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    spec = ops.get_kernel("segment_sum")
    big = (jnp.ones((2048, 4)), jnp.zeros(2048, jnp.int32), 256)
    small = (jnp.ones((8, 4)), jnp.zeros(8, jnp.int32), 4)
    assert choose_backend(spec, *big) == "pallas"
    assert choose_backend(spec, *small) == "jnp"  # below the density floor
    ints = (jnp.ones((2048, 4), jnp.int32), jnp.zeros(2048, jnp.int32), 256)
    assert choose_backend(spec, *ints) == "jnp"  # int partials: exact fallback
    bf16 = (jnp.ones((2048, 4), jnp.bfloat16), jnp.zeros(2048, jnp.int32), 256)
    assert choose_backend(spec, *bf16) == "jnp"  # jnp accumulates bf16 IN bf16
    wide = (jnp.ones((2048, 4096), jnp.float32), jnp.zeros(2048, jnp.int32), 256)
    assert choose_backend(spec, *wide) == "jnp"  # untiled feature dim: VMEM bound
    jax.config.update("jax_enable_x64", True)
    try:
        f64 = (jnp.ones((2048, 4), jnp.float64), jnp.zeros(2048, jnp.int32), 256)
        assert choose_backend(spec, *f64) == "jnp"  # dtype guard
    finally:
        jax.config.update("jax_enable_x64", False)


def test_no_pallas_env_is_absolute(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv(ops.NO_PALLAS_ENV, "1")
    spec = ops.get_kernel("segment_sum")
    args = (jnp.ones((2048, 4)), jnp.zeros(2048, jnp.int32), 256)
    assert ops.pallas_disabled()
    assert choose_backend(spec, *args) == "jnp"
    # the kill switch beats even a forced interpret parity mode
    with ops.forced_backend("interpret"):
        assert choose_backend(spec, *args) == "jnp"


def test_no_pallas_env_dispatch_still_correct(monkeypatch):
    """With the hatch set on a (fake) TPU backend, the dispatched value is
    the jnp fallback's — on CPU an attempted real pallas_call would crash,
    so agreement proves the routing."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv(ops.NO_PALLAS_ENV, "1")
    vals = jnp.asarray(np.random.RandomState(0).randint(0, 5, (1024, 2)).astype(np.float32))
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 100, 1024), jnp.int32)
    got = ops.segment_sum_dispatch(vals, ids, 100)
    want = jax.ops.segment_sum(vals, ids, num_segments=100)
    assert jnp.array_equal(got, want)


def test_forced_backend_validates_and_restores():
    with pytest.raises(ValueError, match="forced_backend mode"):
        with ops.forced_backend("tpu"):
            pass
    spec = ops.get_kernel("segment_sum")
    args = (jnp.ones((512,)), jnp.zeros(512, jnp.int32), 128)
    assert choose_backend(spec, *args) == "jnp"  # CPU default
    with ops.forced_backend("interpret"):
        assert choose_backend(spec, *args) == "interpret"
        with ops.forced_backend("jnp"):
            assert choose_backend(spec, *args) == "jnp"
        assert choose_backend(spec, *args) == "interpret"
    assert choose_backend(spec, *args) == "jnp"


def test_dispatch_mode_tracks_routing_state(monkeypatch):
    base = ops.dispatch_mode()
    with ops.forced_backend("interpret"):
        assert ops.dispatch_mode() != base
    monkeypatch.setenv(ops.NO_PALLAS_ENV, "1")
    assert ops.dispatch_mode() != base
    monkeypatch.delenv(ops.NO_PALLAS_ENV)
    assert ops.dispatch_mode() == base


def test_dispatch_counters_by_op_and_backend():
    _DEFAULT_RECORDER.reset()
    _DEFAULT_RECORDER.enable()
    x = jnp.asarray([0, 1, 1, 2], jnp.int32)
    ops.bincount_dispatch(x, 4)
    with ops.forced_backend("interpret"):
        ops.bincount_dispatch(x, 4)
    ops.segment_max_dispatch(jnp.ones(4), x, 4)
    totals = _DEFAULT_RECORDER.ops_dispatch_totals()
    assert totals["bincount|jnp"] == 1
    assert totals["bincount|interpret"] == 1
    assert totals["segment_max|jnp"] == 1


def test_dispatch_counters_off_when_disabled():
    _DEFAULT_RECORDER.reset()
    assert not _DEFAULT_RECORDER.enabled
    ops.bincount_dispatch(jnp.asarray([0, 1], jnp.int32), 2)
    assert _DEFAULT_RECORDER.ops_dispatch_totals() == {}


def test_counters_ride_aggregate_and_prometheus():
    from metrics_tpu.observability import aggregate_across_hosts
    from metrics_tpu.observability.exporters import render_prometheus

    _DEFAULT_RECORDER.reset()
    _DEFAULT_RECORDER.enable()
    ops.bincount_dispatch(jnp.asarray([0, 1, 1], jnp.int32), 3)
    agg = aggregate_across_hosts(_DEFAULT_RECORDER)
    assert agg["ops_dispatch_totals"]["bincount|jnp"] == 1
    page = render_prometheus(recorder=_DEFAULT_RECORDER, aggregate=agg)
    assert 'metrics_tpu_ops_dispatch_total{op="bincount",backend="jnp"' in page


def test_fused_compile_cache_keyed_on_dispatch_mode():
    """The fused AOT cache must fold in the ops routing state: a flipped
    kill switch or a forced parity mode has to RECOMPILE, not keep
    executing a stale trace with the old backend baked in."""
    from metrics_tpu import MetricCollection
    from metrics_tpu.classification import ConfusionMatrix

    col = MetricCollection({"cm": ConfusionMatrix(num_classes=3)})
    handle = col.compile_update()
    labels = jnp.asarray([0, 1, 2, 2], jnp.int32)
    col.update(labels, labels)
    n0 = handle.n_compiles
    with ops.forced_backend("interpret"):
        col.update(labels, labels)
        assert handle.n_compiles == n0 + 1  # new routing state -> new trace
    col.update(labels, labels)
    assert handle.n_compiles == n0 + 1  # original trace reused
    assert int(jnp.asarray(col["cm"].confmat).trace()) == 12


def test_aggregate_merge_sums_and_tolerates_old_payloads():
    from metrics_tpu.observability.aggregate import merge_payloads

    new = {"process": 0, "ops_dispatch_totals": {"bincount|pallas": 3, "segment_sum|jnp": 1}}
    newer = {"process": 1, "ops_dispatch_totals": {"bincount|pallas": 2}}
    old = {"process": 2}  # pre-suite build: family absent, merges as identity
    merged = merge_payloads([new, newer, old])
    assert merged["ops_dispatch_totals"] == {"bincount|pallas": 5, "segment_sum|jnp": 1}
