"""Modular AveragePrecision (sketch-backed streaming default).

Behavior parity with /root/reference/torchmetrics/classification/avg_precision.py:28-143.
State modes as in auroc.py: streaming quantile sketch by default (bit-equal
to ``exact=True`` inside the lossless window, weighted step-sum beyond),
``exact=True`` for the unbounded cat-state path, ``capacity=N`` for the
static exact buffers.
"""
from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.classification._sketch import DEFAULT_SKETCH_CAPACITY, SketchCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.exact_curve import (
    binary_average_precision_fixed,
    multiclass_average_precision_fixed,
)
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.functional.classification.sketch_curve import (
    average_class_scores,
    binary_average_precision_weighted,
    weighted_class_supports,
)
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class AveragePrecision(SketchCurveMixin, CapacityCurveMixin, Metric):
    """Computes the average precision score.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> average_precision(pred, target)
        Array(1., dtype=float32)
    """

    __jit_unsafe__ = False  # sketch default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"
    __fused_mask_valid__ = True
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        capacity: Optional[int] = None,
        multilabel: bool = False,
        exact: bool = False,
        sketch_capacity: int = DEFAULT_SKETCH_CAPACITY,
        shape_stable_reads: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self._exact = bool(exact)
        if exact and capacity is not None:
            raise ValueError("`exact=True` and `capacity` are mutually exclusive state modes")
        # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
        # Binary keeps the flat triple; num_classes >= 2 keeps [capacity, C]
        # score rows (one-vs-rest AP per class); `multilabel=True`
        # additionally stores [capacity, C] indicator targets.
        if (
            capacity is not None
            and num_classes is not None
            and num_classes >= 2
            and not multilabel
            and average == "micro"
        ):
            # parity with the unbounded path and capacity-mode AUROC
            # (reference avg_precision.py raises for micro + multi-class input)
            raise ValueError("Cannot use `micro` average with multi-class input")
        self._init_capacity_case(capacity, num_classes, multilabel)
        if capacity is None:
            if self._exact:
                register_exact_list_states(self, ("preds", "target"))
                warn_exact_buffer("AveragePrecision")
            else:
                self._init_sketch_curve(
                    sketch_capacity, num_classes, shape_stable_reads=shape_stable_reads
                )

    def _update(self, preds: Array, target: Array, n_valid: Optional[Array] = None) -> None:
        if self._capacity is not None:
            self._capacity_update(preds, target, pos_label=self.pos_label)
            return
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        if self._exact:
            self.preds.append(preds)
            self.target.append(target)
        else:
            self._sketch_insert_canonical(
                preds, target, pos_label if preds.ndim == 1 else 1, n_valid=n_valid
            )
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _compute(self) -> Union[Array, List[Array]]:
        if self._capacity is not None:
            if self._capacity_cols is not None:
                return multiclass_average_precision_fixed(
                    *self._capacity_buffers_2d(),
                    self.num_classes,
                    average="none" if self.average is None else self.average,
                    multilabel=self._capacity_multilabel,
                )
            return binary_average_precision_fixed(*self._capacity_buffers())
        if self._exact:
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
            return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)
        if self._sketch_reads_exact():
            preds, target, pos_label = self._sketch_exact_arrays()
            return _average_precision_compute(preds, target, self.num_classes, pos_label, self.average)
        return self._sketch_approx_compute()

    def _sketch_approx_compute(self):
        """Weighted average precision from the compacted sketch rows."""
        scores, y, w = self._sketch_weighted_arrays()
        if self._sketch_cols is None:
            return binary_average_precision_weighted(scores, y, w)
        if self.average == "micro":
            flat_w = jnp.broadcast_to(w[:, None], y.shape).reshape(-1)
            return binary_average_precision_weighted(scores.reshape(-1), y.reshape(-1), flat_w)
        per_class = jax.vmap(binary_average_precision_weighted, in_axes=(1, 1, None))(scores, y, w)
        supports = weighted_class_supports(y, w)
        return average_class_scores(per_class, supports, self.average)
