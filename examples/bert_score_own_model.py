"""BERTScore with your own encoder + tokenizer (analog of the reference's
tm_examples/bert_score-own_model.py): any callable that maps
(input_ids, attention_mask) -> [batch, seq, dim] works as the model — here a
trivial hash-embedding encoder, so the example runs with no downloads."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

import numpy as np

import jax.numpy as jnp

from metrics_tpu.text import BERTScore

_VOCAB = {w: i + 4 for i, w in enumerate("hello there general kenobi master the cat sat on a mat".split())}


def tokenizer(texts, max_length):
    """User-tokenizer protocol: (texts, max_length) -> input_ids + mask.
    Must prepend a [CLS]-like (2) and append a [SEP]-like (3) token."""
    rows = [[2] + [_VOCAB.get(w, 1) for w in t.split()][: max_length - 2] + [3] for t in texts]
    width = max(len(r) for r in rows)
    ids = np.zeros((len(rows), width), np.int32)
    mask = np.zeros((len(rows), width), np.int32)
    for i, r in enumerate(rows):
        ids[i, : len(r)] = r
        mask[i, : len(r)] = 1
    return {"input_ids": ids, "attention_mask": mask}


def model(input_ids, attention_mask):
    """Deterministic toy encoder: fixed random embedding per token id."""
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.standard_normal((64, 16)).astype(np.float32))
    return table[input_ids]


def main() -> None:
    metric = BERTScore(model=model, user_tokenizer=tokenizer)
    metric.update(["hello there", "master kenobi"], ["hello there", "general kenobi"])
    for key, values in metric.compute().items():
        print(f"{key}: {[round(v, 3) for v in values]}")


if __name__ == "__main__":
    main()
