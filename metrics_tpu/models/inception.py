"""Flax InceptionV3 feature extractor for FID/KID/IS.

TPU-native replacement for the reference's torch-fidelity
``FeatureExtractorInceptionV3`` (/root/reference/torchmetrics/image/fid.py:
26-57): the same TF-slim "inception-v3-compat" topology expressed in Flax
linen, exposing the four FID feature depths (64, 192, 768, 2048) and the
1008-way logits.

Weights are NOT bundled (this environment has no network access): pass an
``.npz`` checkpoint produced by ``convert_torch_fidelity_weights`` (host-side
helper that maps a locally-downloaded torch-fidelity state_dict onto this
module's parameter tree). Constructing an extractor without weights raises.
"""
from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn

    _FLAX_AVAILABLE = True
except ImportError:  # pragma: no cover
    _FLAX_AVAILABLE = False

Array = jax.Array

FID_FEATURE_DEPTHS = (64, 192, 768, 2048)


if _FLAX_AVAILABLE:

    class BasicConv2d(nn.Module):
        """Conv + BN(eps=1e-3, no scale-γ=False) + ReLU, matching TF-slim inception."""

        out_channels: int
        kernel_size: Sequence[int]
        strides: Sequence[int] = (1, 1)
        padding: Union[str, Sequence] = "VALID"

        @nn.compact
        def __call__(self, x: Array) -> Array:
            x = nn.Conv(
                self.out_channels, self.kernel_size, strides=self.strides, padding=self.padding, use_bias=False
            )(x)
            x = nn.BatchNorm(use_running_average=True, epsilon=1e-3)(x)
            return nn.relu(x)

    def _max_pool(x: Array, window: int = 3, stride: int = 2) -> Array:
        return nn.max_pool(x, (window, window), strides=(stride, stride))

    def _avg_pool3(x: Array) -> Array:
        return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME", count_include_pad=False)

    class InceptionA(nn.Module):
        pool_features: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(64, (1, 1))(x)
            b2 = BasicConv2d(48, (1, 1))(x)
            b2 = BasicConv2d(64, (5, 5), padding="SAME")(b2)
            b3 = BasicConv2d(64, (1, 1))(x)
            b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
            b3 = BasicConv2d(96, (3, 3), padding="SAME")(b3)
            b4 = _avg_pool3(x)
            b4 = BasicConv2d(self.pool_features, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionB(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(384, (3, 3), strides=(2, 2))(x)
            b2 = BasicConv2d(64, (1, 1))(x)
            b2 = BasicConv2d(96, (3, 3), padding="SAME")(b2)
            b2 = BasicConv2d(96, (3, 3), strides=(2, 2))(b2)
            b3 = _max_pool(x)
            return jnp.concatenate([b1, b2, b3], axis=-1)

    class InceptionC(nn.Module):
        channels_7x7: int

        @nn.compact
        def __call__(self, x: Array) -> Array:
            c7 = self.channels_7x7
            b1 = BasicConv2d(192, (1, 1))(x)
            b2 = BasicConv2d(c7, (1, 1))(x)
            b2 = BasicConv2d(c7, (1, 7), padding="SAME")(b2)
            b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
            b3 = BasicConv2d(c7, (1, 1))(x)
            b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
            b3 = BasicConv2d(c7, (1, 7), padding="SAME")(b3)
            b3 = BasicConv2d(c7, (7, 1), padding="SAME")(b3)
            b3 = BasicConv2d(192, (1, 7), padding="SAME")(b3)
            b4 = _avg_pool3(x)
            b4 = BasicConv2d(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionD(nn.Module):
        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(192, (1, 1))(x)
            b1 = BasicConv2d(320, (3, 3), strides=(2, 2))(b1)
            b2 = BasicConv2d(192, (1, 1))(x)
            b2 = BasicConv2d(192, (1, 7), padding="SAME")(b2)
            b2 = BasicConv2d(192, (7, 1), padding="SAME")(b2)
            b2 = BasicConv2d(192, (3, 3), strides=(2, 2))(b2)
            b3 = _max_pool(x)
            return jnp.concatenate([b1, b2, b3], axis=-1)

    class InceptionE(nn.Module):
        """Final inception blocks; ``pool`` selects avg (E1) or max (E2, the
        FID-compat quirk in the last block)."""

        pool: str = "avg"

        @nn.compact
        def __call__(self, x: Array) -> Array:
            b1 = BasicConv2d(320, (1, 1))(x)
            b2 = BasicConv2d(384, (1, 1))(x)
            b2 = jnp.concatenate(
                [BasicConv2d(384, (1, 3), padding="SAME")(b2), BasicConv2d(384, (3, 1), padding="SAME")(b2)],
                axis=-1,
            )
            b3 = BasicConv2d(448, (1, 1))(x)
            b3 = BasicConv2d(384, (3, 3), padding="SAME")(b3)
            b3 = jnp.concatenate(
                [BasicConv2d(384, (1, 3), padding="SAME")(b3), BasicConv2d(384, (3, 1), padding="SAME")(b3)],
                axis=-1,
            )
            if self.pool == "avg":
                b4 = _avg_pool3(x)
            else:
                b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            b4 = BasicConv2d(192, (1, 1))(b4)
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)

    class InceptionV3FID(nn.Module):
        """FID-compat InceptionV3 returning the requested feature depth.

        Input: uint8/float images ``[N, 3, H, W]`` (NCHW like the reference);
        internally resized to 299x299 and normalized to [-1, 1].
        """

        num_classes: int = 1008

        @nn.compact
        def __call__(self, x: Array, feature: Union[int, str] = 2048) -> Array:
            # NCHW -> NHWC, resize, scale to [-1, 1]. The value-range decision
            # is made from the *dtype* (static at trace time, jit-safe):
            # integer inputs are [0, 255], floats are [0, 1] — same contract as
            # the reference (uint8 by default, float via normalize=True).
            is_int = jnp.issubdtype(x.dtype, jnp.integer)
            x = jnp.transpose(x.astype(jnp.float32), (0, 2, 3, 1))
            x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear")
            x = x / 127.5 - 1.0 if is_int else x * 2.0 - 1.0

            x = BasicConv2d(32, (3, 3), strides=(2, 2))(x)
            x = BasicConv2d(32, (3, 3))(x)
            x = BasicConv2d(64, (3, 3), padding="SAME")(x)
            x = _max_pool(x)
            if feature == 64:
                return jnp.mean(x, axis=(1, 2))

            x = BasicConv2d(80, (1, 1))(x)
            x = BasicConv2d(192, (3, 3))(x)
            x = _max_pool(x)
            if feature == 192:
                return jnp.mean(x, axis=(1, 2))

            x = InceptionA(pool_features=32)(x)
            x = InceptionA(pool_features=64)(x)
            x = InceptionA(pool_features=64)(x)
            x = InceptionB()(x)
            x = InceptionC(channels_7x7=128)(x)
            x = InceptionC(channels_7x7=160)(x)
            x = InceptionC(channels_7x7=160)(x)
            x = InceptionC(channels_7x7=192)(x)
            if feature == 768:
                return jnp.mean(x, axis=(1, 2))

            x = InceptionD()(x)
            x = InceptionE(pool="avg")(x)
            x = InceptionE(pool="max")(x)
            x = jnp.mean(x, axis=(1, 2))  # [N, 2048]
            if feature == 2048:
                return x

            logits = nn.Dense(self.num_classes)(x)
            if feature == "logits_unbiased":
                # torch-fidelity's unbiased logits drop the bias term
                kernel = self.variables["params"]["Dense_0"]["kernel"]
                return x @ kernel
            return logits


# torch-fidelity / pytorch-fid module names for each Flax submodule, in the
# order the Flax `@nn.compact` bodies create them (creation order defines the
# auto-generated ``BasicConv2d_<i>`` names).
_STEM_CONVS = ("Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3", "Conv2d_3b_1x1", "Conv2d_4a_3x3")
_A_BRANCHES = ("branch1x1", "branch5x5_1", "branch5x5_2",
               "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool")
_B_BRANCHES = ("branch3x3", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3")
_C_BRANCHES = ("branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3",
               "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3",
               "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool")
_D_BRANCHES = ("branch3x3_1", "branch3x3_2", "branch7x7x3_1",
               "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4")
_E_BRANCHES = ("branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b",
               "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a",
               "branch3x3dbl_3b", "branch_pool")
_BLOCK_LAYOUT = (
    # (flax submodule name, torch module name, torch branch-conv order)
    ("InceptionA_0", "Mixed_5b", _A_BRANCHES),
    ("InceptionA_1", "Mixed_5c", _A_BRANCHES),
    ("InceptionA_2", "Mixed_5d", _A_BRANCHES),
    ("InceptionB_0", "Mixed_6a", _B_BRANCHES),
    ("InceptionC_0", "Mixed_6b", _C_BRANCHES),
    ("InceptionC_1", "Mixed_6c", _C_BRANCHES),
    ("InceptionC_2", "Mixed_6d", _C_BRANCHES),
    ("InceptionC_3", "Mixed_6e", _C_BRANCHES),
    ("InceptionD_0", "Mixed_7a", _D_BRANCHES),
    ("InceptionE_0", "Mixed_7b", _E_BRANCHES),
    ("InceptionE_1", "Mixed_7c", _E_BRANCHES),
)


def convert_torch_fidelity_weights(state_dict: Any) -> dict:
    """Map a torch-fidelity ``FeatureExtractorInceptionV3`` state_dict (or any
    torchvision-style inception with ``Mixed_*``/``Conv2d_*`` module names,
    e.g. pytorch-fid's underlying ``fid_inception_v3()`` — NOT its
    ``blocks.N.M``-indexed wrapper) onto this module's Flax variable tree.

    Host-side helper: accepts torch tensors or numpy arrays keyed by the
    standard inception module names (``Mixed_5b.branch1x1.conv.weight`` ...).
    Returns ``{"params": ..., "batch_stats": ...}``. Persist with
    ``np.savez(path, variables=variables)`` and pass ``path`` as
    ``feature_extractor_weights_path``. Replaces the torch-side loading at
    reference image/fid.py:26-57 (torch-fidelity download + torch state_dict).
    """
    import numpy as np

    from metrics_tpu.utils.data import torch_to_numpy

    def _np(t: Any) -> np.ndarray:
        return np.asarray(torch_to_numpy(t), dtype=np.float32)

    sd = dict(state_dict)
    # tolerate a uniform key prefix (e.g. "model." or "inception.")
    probe = f"{_STEM_CONVS[0]}.conv.weight"
    if probe not in sd:
        prefixes = {k[: -len(probe)] for k in sd if k.endswith(probe)}
        if len(prefixes) != 1:
            raise KeyError(f"Cannot locate '{probe}' (or a unique prefixed variant) in state_dict")
        prefix = prefixes.pop()
        sd = {k[len(prefix):]: v for k, v in sd.items() if k.startswith(prefix)}

    def _basic_conv(torch_name: str):
        kernel = _np(sd[f"{torch_name}.conv.weight"]).transpose(2, 3, 1, 0)  # OIHW -> HWIO
        params = {
            "Conv_0": {"kernel": kernel},
            "BatchNorm_0": {"scale": _np(sd[f"{torch_name}.bn.weight"]), "bias": _np(sd[f"{torch_name}.bn.bias"])},
        }
        stats = {
            "BatchNorm_0": {
                "mean": _np(sd[f"{torch_name}.bn.running_mean"]),
                "var": _np(sd[f"{torch_name}.bn.running_var"]),
            }
        }
        return params, stats

    params: dict = {}
    batch_stats: dict = {}
    for i, torch_name in enumerate(_STEM_CONVS):
        params[f"BasicConv2d_{i}"], batch_stats[f"BasicConv2d_{i}"] = _basic_conv(torch_name)
    for flax_name, torch_name, branch_order in _BLOCK_LAYOUT:
        block_params: dict = {}
        block_stats: dict = {}
        for j, branch in enumerate(branch_order):
            block_params[f"BasicConv2d_{j}"], block_stats[f"BasicConv2d_{j}"] = _basic_conv(
                f"{torch_name}.{branch}"
            )
        params[flax_name] = block_params
        batch_stats[flax_name] = block_stats
    if "fc.weight" in sd:
        params["Dense_0"] = {"kernel": _np(sd["fc.weight"]).T, "bias": _np(sd["fc.bias"])}
    return {"params": params, "batch_stats": batch_stats}


def build_fid_inception(
    feature: Union[int, str] = 2048, weights_path: Optional[str] = None
) -> Callable[[Array], Array]:
    """Build an ``imgs -> [N, d]`` extractor from the bundled InceptionV3.

    Raises a clear error when no weights are provided — FID/KID/IS values
    from a randomly-initialized network are meaningless. Pass a callable
    ``feature`` to the metrics to use your own extractor instead.
    """
    if not _FLAX_AVAILABLE:
        raise ModuleNotFoundError("The bundled InceptionV3 requires `flax` to be installed.")
    if weights_path is None:
        raise ValueError(
            "The bundled InceptionV3 needs pretrained weights for meaningful FID/KID/IS values"
            " and none are bundled (no network access). Provide"
            " `feature_extractor_weights_path` (an .npz produced by"
            " `metrics_tpu.models.inception.convert_torch_fidelity_weights`),"
            " or pass a callable `feature` extractor."
        )
    import numpy as np

    model = InceptionV3FID()
    loaded = dict(np.load(weights_path, allow_pickle=True))
    variables = jax.tree_util.tree_map(jnp.asarray, loaded["variables"].item())

    jitted = jax.jit(lambda imgs: model.apply(variables, imgs, feature=feature))
    pending_max = None  # async max of the previous device batch, checked next call

    def _validate_max(mx: float) -> None:
        if mx > 1.5:
            raise ValueError(
                "Float images must be in [0, 1] (got max value"
                f" {mx:.3g}). Pass uint8 images for the [0, 255] range."
            )

    def extract(imgs: Array) -> Array:
        # Guard against mis-ranged float inputs: a float image holding
        # [0, 255] values (e.g. uint8 cast to float32) would be silently
        # mis-scaled by the dtype-keyed normalization inside the jitted
        # forward. Host numpy inputs are checked synchronously (free); device
        # arrays are checked with a one-batch delay — the max is enqueued
        # async and read back on the NEXT call, by which point it has long
        # finished, so dispatch stays pipelined (no per-call device sync).
        # The final batch of a stream is therefore only validated if another
        # call follows; the synchronous numpy path has no such gap.
        nonlocal pending_max
        if jnp.issubdtype(imgs.dtype, jnp.floating):
            if isinstance(imgs, np.ndarray):
                _validate_max(float(imgs.max()))
            else:
                if pending_max is not None:
                    _validate_max(float(pending_max))
                pending_max = jnp.max(imgs)
        return jitted(imgs)

    def finalize() -> None:
        """Flush the pending async range check (covers the LAST device batch
        of a stream, which the one-batch-delayed check would otherwise skip).
        FID/KID/IS call this at compute time."""
        nonlocal pending_max
        if pending_max is not None:
            mx = float(pending_max)
            pending_max = None
            _validate_max(mx)

    extract.finalize = finalize
    return extract
