"""BootStrapper — confidence intervals by resampling updates.

Behavior parity with /root/reference/torchmetrics/wrappers/bootstrapping.py:25-174.
Sampling indices are drawn host-side with numpy (seedable) — the resample is
data-layout work, not device math.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import apply_to_collection

Array = jax.Array


def _bootstrap_sampler(
    size: int,
    sampling_strategy: str = "poisson",
    rng: Optional[np.random.RandomState] = None,
) -> Array:
    """Indices resampling [0, size) with replacement."""
    rng = rng or np.random
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    if sampling_strategy == "multinomial":
        return jnp.asarray(rng.randint(0, size, size))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Computes bootstrapped mean/std/quantile/raw of a base metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> base_metric = Accuracy()
        >>> bootstrap = BootStrapper(base_metric, num_bootstraps=20, seed=123)
        >>> bootstrap.update(jnp.arange(20) % 5, (jnp.arange(20) * 3) % 5)
        >>> output = bootstrap.compute()
        >>> sorted(output.keys())
        ['mean', 'std']
    """

    #: delegates to the child metric's full eager lifecycle (telemetry,
    #: coercion); the child registry already excludes it from fusion
    __jit_unsafe__ = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )

        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        self._rng = np.random.RandomState(seed)

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but recieved {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy

    def _update(self, *args: Any, **kwargs: Any) -> None:
        """Update all bootstrap copies, each on a fresh resample of the batch."""
        args_sizes = apply_to_collection(args, jnp.ndarray, len)
        kwargs_sizes = list(apply_to_collection(kwargs, jnp.ndarray, len).values())
        if len(args_sizes) > 0:
            size = args_sizes[0]
        elif len(kwargs_sizes) > 0:
            size = kwargs_sizes[0]
        else:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            new_args = apply_to_collection(args, jnp.ndarray, lambda x: jnp.take(x, sample_idx, axis=0))
            new_kwargs = apply_to_collection(kwargs, jnp.ndarray, lambda x: jnp.take(x, sample_idx, axis=0))
            self.metrics[idx].update(*new_args, **new_kwargs)

    def _compute(self) -> Dict[str, Array]:
        computed_vals = jnp.stack([m.compute() for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = jnp.mean(computed_vals, axis=0)
        if self.std:
            output_dict["std"] = jnp.std(computed_vals, axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()
