"""RetrievalNormalizedDCG.

Behavior parity with /root/reference/torchmetrics/retrieval/ndcg.py:22-112
(graded targets allowed).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg
from metrics_tpu.functional.retrieval.padded import ndcg_row
from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k

Array = jax.Array


class RetrievalNormalizedDCG(RetrievalMetric):
    """Mean nDCG@k over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(ndcg_row)

    @property
    def _padded_k(self):
        return self.k

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        _check_retrieval_k(k)
        self.k = k
        self.allow_non_binary_target = True

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_normalized_dcg(preds, target, k=self.k)
