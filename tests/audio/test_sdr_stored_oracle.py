"""Stored-oracle fixture for the SDR solver
(scripts/make_text_audio_oracle.py — the PESQ/FID stored-corpus pattern).

Unconditional engine drift pin over the seeded two-channel corpus: dense
Toeplitz solve, CG solve, zero-mean variant, and SI-SDR. When a networked
environment has stored ``sdr_official_scores.csv`` (fast_bss_eval over the
same corpus), |ours − official| is bounded from storage here with no
fast_bss_eval import needed.
"""
import csv
import os

import pytest

from tests.audio.sdr_corpus import engine_scores

_FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _read(name):
    path = os.path.join(_FIXDIR, name)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return {row["case"]: float(row["score"]) for row in csv.DictReader(fh)}


def test_sdr_engine_drift_pin():
    pinned = _read("sdr_engine_scores.csv")
    assert pinned is not None, "run scripts/make_text_audio_oracle.py"
    got = engine_scores()  # the generator's own scoring definition
    assert set(got) == set(pinned)
    for key, val in got.items():
        # the dense f64-path scores are stable to ~1e-4 dB across backends
        assert val == pytest.approx(pinned[key], abs=1e-3), key


def test_sdr_official_scores_from_storage():
    ours = _read("sdr_engine_scores.csv")
    assert ours is not None, "run scripts/make_text_audio_oracle.py"
    official = _read("sdr_official_scores.csv")
    if official is None:
        pytest.skip(
            "official fixture not generated (run scripts/make_text_audio_oracle.py"
            " in an environment with fast_bss_eval)"
        )
    for key, off in official.items():
        assert abs(ours[key] - off) <= 0.1, (key, ours[key], off)  # dB
