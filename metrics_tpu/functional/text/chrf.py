"""chrF / chrF++ score (character + word n-gram F-beta).

Behavior parity with /root/reference/torchmetrics/functional/text/chrf.py
(703 LoC; itself following m-popovic/chrF and sacrebleu): character n-grams
up to ``n_char_order`` (whitespace stripped unless ``whitespace=True``) and
word n-grams up to ``n_word_order`` with leading/trailing punctuation split
off; per sentence the BEST-scoring reference contributes its statistics to
the corpus totals; F-beta averaged uniformly over all n-gram orders with the
1e-16 denominator smoothing.

Re-designed around plain Counters and float totals (the reference threads
six dict-of-tensor states through every helper); device scalars only at the
boundary. Host-side string processing feeding scalar device states
(SURVEY §2.7).
"""
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _validate_inputs

Array = jax.Array

_EPS_SMOOTHING = 1e-16
# fixed by the sacrebleu chrF spec
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")

# per-order totals for (pred_char, pred_word, target_char, target_word,
# matching_char, matching_word) — the six corpus accumulators
_Totals = Tuple[Dict[int, float], Dict[int, float], Dict[int, float], Dict[int, float], Dict[int, float], Dict[int, float]]


def _zero_totals(n_char_order: int, n_word_order: int) -> _Totals:
    char_orders = {n: 0.0 for n in range(1, n_char_order + 1)}
    word_orders = {n: 0.0 for n in range(1, n_word_order + 1)}
    return (
        dict(char_orders), dict(word_orders),
        dict(char_orders), dict(word_orders),
        dict(char_orders), dict(word_orders),
    )


def _split_word_punctuation(word: str) -> List[str]:
    """chrF++ word tokenization: peel ONE leading or trailing punctuation."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _sentence_units(sentence: str, lowercase: bool, whitespace: bool) -> Tuple[List[str], List[str]]:
    """(character list, word list) after chrF preprocessing."""
    if lowercase:
        sentence = sentence.lower()
    chars = list(sentence) if whitespace else list(sentence.strip().replace(" ", ""))
    words = [piece for word in sentence.strip().split() for piece in _split_word_punctuation(word)]
    return chars, words


def _ngram_counters(units: Sequence[str], max_order: int) -> Dict[int, Counter]:
    return {
        n: Counter(tuple(units[i : i + n]) for i in range(len(units) - n + 1))
        for n in range(1, max_order + 1)
    }


def _matches(pred_counts: Dict[int, Counter], target_counts: Dict[int, Counter]) -> Dict[int, float]:
    return {
        n: float(sum((pred_counts[n] & target_counts[n]).values())) for n in pred_counts
    }


def _totals_of(counts: Dict[int, Counter]) -> Dict[int, float]:
    return {n: float(sum(c.values())) for n, c in counts.items()}


def _fscore(
    matching_char: Dict[int, float],
    matching_word: Dict[int, float],
    pred_char: Dict[int, float],
    pred_word: Dict[int, float],
    target_char: Dict[int, float],
    target_word: Dict[int, float],
    n_order: float,
    beta: float,
) -> float:
    """Uniform average of per-order F-beta over char + word orders."""

    def _per_order(matching: Dict[int, float], target: Dict[int, float], pred: Dict[int, float]) -> float:
        total = 0.0
        for n in matching:
            precision = matching[n] / pred[n] if pred[n] > 0 else 0.0
            recall = matching[n] / target[n] if target[n] > 0 else 0.0
            denominator = max(beta**2 * precision + recall, _EPS_SMOOTHING)
            total += (1 + beta**2) * precision * recall / denominator
        return total

    return (
        _per_order(matching_char, target_char, pred_char)
        + _per_order(matching_word, target_word, pred_word)
    ) / n_order


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    totals: _Totals,
    n_char_order: int,
    n_word_order: int,
    n_order: float,
    beta: float,
    lowercase: bool,
    whitespace: bool,
) -> Tuple[_Totals, List[float]]:
    """Accumulate best-reference statistics per sentence into ``totals``."""
    target_corpus, preds = _validate_inputs(target, preds)
    (t_pred_char, t_pred_word, t_tgt_char, t_tgt_word, t_match_char, t_match_word) = totals

    sentence_scores: List[float] = []
    for pred, targets in zip(preds, target_corpus):
        chars, words = _sentence_units(pred, lowercase, whitespace)
        pred_char_counts = _ngram_counters(chars, n_char_order)
        pred_word_counts = _ngram_counters(words, n_word_order)
        pred_char = _totals_of(pred_char_counts)
        pred_word = _totals_of(pred_word_counts)
        for n in pred_char:
            t_pred_char[n] += pred_char[n]
        for n in pred_word:
            t_pred_word[n] += pred_word[n]

        best = 0.0
        best_stats = (
            {n: 0.0 for n in pred_char}, {n: 0.0 for n in pred_word},
            {n: 0.0 for n in pred_char}, {n: 0.0 for n in pred_word},
        )
        for tgt in targets:
            tgt_chars, tgt_words = _sentence_units(tgt, lowercase, whitespace)
            tgt_char_counts = _ngram_counters(tgt_chars, n_char_order)
            tgt_word_counts = _ngram_counters(tgt_words, n_word_order)
            tgt_char = _totals_of(tgt_char_counts)
            tgt_word = _totals_of(tgt_word_counts)
            match_char = _matches(pred_char_counts, tgt_char_counts)
            match_word = _matches(pred_word_counts, tgt_word_counts)
            score = _fscore(
                match_char, match_word, pred_char, pred_word, tgt_char, tgt_word, n_order, beta
            )
            if score > best:
                best = score
                best_stats = (match_char, match_word, tgt_char, tgt_word)

        sentence_scores.append(best)
        match_char, match_word, tgt_char, tgt_word = best_stats
        for n in tgt_char:
            t_tgt_char[n] += tgt_char[n]
            t_match_char[n] += match_char[n]
        for n in tgt_word:
            t_tgt_word[n] += tgt_word[n]
            t_match_word[n] += match_word[n]

    return (t_pred_char, t_pred_word, t_tgt_char, t_tgt_word, t_match_char, t_match_word), sentence_scores


def _chrf_score_compute(totals: _Totals, n_order: float, beta: float) -> Array:
    (t_pred_char, t_pred_word, t_tgt_char, t_tgt_word, t_match_char, t_match_word) = totals
    score = _fscore(t_match_char, t_match_word, t_pred_char, t_pred_word, t_tgt_char, t_tgt_word, n_order, beta)
    return jnp.asarray(score, jnp.float32)


def _validate_chrf_args(n_char_order: int, n_word_order: int, beta: float) -> None:
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Corpus chrF (``n_word_order=0``) / chrF++ (default) score.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(chrf_score(preds, target))  # doctest: +ELLIPSIS
        0.8640...
    """
    _validate_chrf_args(n_char_order, n_word_order, beta)
    n_order = float(n_char_order + n_word_order)
    totals, sentence_scores = _chrf_score_update(
        preds, target, _zero_totals(n_char_order, n_word_order),
        n_char_order, n_word_order, n_order, beta, lowercase, whitespace,
    )
    score = _chrf_score_compute(totals, n_order, beta)
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, jnp.float32)
    return score
