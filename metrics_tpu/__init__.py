"""metrics_tpu — a TPU-native (JAX/XLA) machine-learning metrics framework.

Capability parity target: TorchMetrics v0.8.0dev (/root/reference). Exports
grow as domains land; see SURVEY.md §2.8 for the full target inventory.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    ROC,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.aggregation import (  # noqa: E402
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)
from metrics_tpu.image import (  # noqa: E402
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.regression import (  # noqa: E402
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from metrics_tpu.text import (  # noqa: E402
    BLEUScore,
    CharErrorRate,
    MatchErrorRate,
    ROUGEScore,
    SacreBLEUScore,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "AUC",
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BLEUScore",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "BootStrapper",
    "CalibrationError",
    "CatMetric",
    "CharErrorRate",
    "ClasswiseWrapper",
    "CohenKappa",
    "CompositionalMetric",
    "ConfusionMatrix",
    "CosineSimilarity",
    "ExplainedVariance",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KLDivergence",
    "MatchErrorRate",
    "MatthewsCorrCoef",
    "MaxMetric",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanMetric",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "Metric",
    "MetricCollection",
    "MetricTracker",
    "MinMaxMetric",
    "MinMetric",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "MultioutputWrapper",
    "PeakSignalNoiseRatio",
    "PearsonCorrCoef",
    "Precision",
    "PrecisionRecallCurve",
    "R2Score",
    "ROC",
    "ROUGEScore",
    "Recall",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "SacreBLEUScore",
    "SpearmanCorrCoef",
    "Specificity",
    "StatScores",
    "StructuralSimilarityIndexMeasure",
    "SumMetric",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "UniversalImageQualityIndex",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
