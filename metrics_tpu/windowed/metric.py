"""``WindowedMetric`` — sliding-window / exponential-decay state for any
fusible metric.

All-of-time metric values answer "how good has this model been since
reset"; a live serving job needs "how good is it NOW" — AUROC over the
last five minutes, MSE over the last N thousand requests, a per-tenant
error surface that forgets last week's traffic. This wrapper gives any
fusible metric that time axis while staying inside the single fused
dispatch, with two state layouts:

* **Ring mode** (default) — every wrapped state leaf is broadcast to a
  leading ``[R]`` ring axis (the same structural trick as
  ``SlicedMetric``'s ``[S]`` slice axis), one row per *bucket* of
  ``updates_per_bucket`` consecutive updates. Each update rotates into its
  slot with one ``.at[slot].set`` (slot = bucket index mod ``R``, derived
  from the ``_ring_count`` state — jit-clean, no host clock), resetting
  the slot to defaults on the first update of a fresh bucket so expired
  buckets self-evict. ``compute()`` folds the in-window rows oldest-first
  through the wrapped metric's OWN reducers (``merge_states``: sum leaves
  add, max/min fold, sketch leaves ``qsketch_merge`` in arrival order —
  bit-identical to recomputing the window's batches inside each sketch's
  lossless window), then runs the wrapped compute. ``compute(window=w)``
  narrows to the last ``w`` buckets.
* **Decay mode** (``mode="decay"``) — every (necessarily sum-reduced)
  leaf becomes the exponentially-decayed sum ``alpha * state + delta``:
  O(1) extra memory, an infinite soft window with half-life
  ``ln(2)/ln(1/alpha)`` updates. Max/min and sketch leaves have no decay
  (an extremum cannot forget; scaling sketch weights skews compaction) —
  such metrics use ring mode, which is exactly why both live here.

Both layouts are pure fixed-shape ``(state, batch) -> state`` transforms,
so a ``WindowedMetric`` fuses, buckets, ingests asynchronously, and
mesh-syncs unchanged: ``compile_update``/``compile_update_async`` compile
it once across bucketed ragged shapes (the wrapper declares
``__fused_mask_valid__`` and performs its own slot-aware ``k * delta``
pad correction — the generic ``dim_zero_sum`` correction would probe the
DEFAULT state's slot, see :mod:`.reducers`), and cross-rank sync folds
ring rows bucket-by-bucket. Per-tenant windowed metrics are
``WindowedMetric(SlicedMetric(...))`` by construction: the leaves become
``[R, S, ...]`` and every mechanism above composes. See
docs/windowed_metrics.md.
"""
from __future__ import annotations

import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import _AUTO_COUNT, Metric
from metrics_tpu.core.readers import ReaderCache
from metrics_tpu.observability.freshness import FreshnessStamp
from metrics_tpu.observability.memory import register_cache_plane
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.observability.recorder import WINDOWED_FOOTPRINT_PREFIX
from metrics_tpu.utils.data import _squeeze_if_scalar, dim_zero_max, dim_zero_min, dim_zero_sum
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.windowed.reducers import decay_sum_fx, ring_merge_fx, ring_sum_fx

Array = jax.Array

#: per-bucket update counter, ``[R]`` int32 — which ring rows are live and
#: how much traffic each bucket absorbed ("ring"-reduced: same-bucket
#: counts add across ranks)
RING_ROWS = "_ring_rows"

#: total updates since reset, int32 scalar — the jit-clean clock the ring
#: slot derives from ("max"-reduced: the furthest clock wins a sync)
RING_COUNT = "_ring_count"

#: decayed effective sample weight ``sum_i alpha^i``, float32 scalar —
#: what a decayed sum is "out of" (decay-reduced like the leaves it scales)
DECAY_WEIGHT = "_decay_weight"

_RESERVED = (RING_ROWS, RING_COUNT, DECAY_WEIGHT)

_MODES = ("ring", "decay")

#: LRU bound on the per-instance fold memos — one entry per distinct
#: (window, before) read pattern; serving loops use one or two
_FOLD_MEMO_MAX = 8

#: every live WindowedMetric (weak); the ``windowed_fold_memo`` memory
#: plane sums both per-instance fold memos (prefix folds + merged window
#: states — device arrays the state footprint does not cover) over this set
_LIVE_WINDOWED: "weakref.WeakSet" = weakref.WeakSet()


def _fold_memo_nbytes() -> int:
    total = 0
    for m in list(_LIVE_WINDOWED):
        for memo in (getattr(m, "_fold_memo", None), getattr(m, "_wstate_memo", None)):
            if not memo:
                continue
            for entry in list(memo.values()):
                total += int(
                    sum(
                        getattr(leaf, "nbytes", 0) or 0
                        for leaf in jax.tree_util.tree_leaves(entry)
                    )
                )
    return total


register_cache_plane("windowed_fold_memo", _fold_memo_nbytes)


def _reducer_name(red: Any) -> str:
    names = {dim_zero_sum: "sum", dim_zero_max: "max", dim_zero_min: "min"}
    if red is None:
        return "None"
    return names.get(red) or getattr(red, "__name__", repr(red))


class WindowedMetric(Metric):
    """Track ``metric`` over a sliding window (ring) or with exponential
    decay.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> from metrics_tpu.windowed import WindowedMetric
        >>> recent = WindowedMetric(MeanSquaredError(), window=3, updates_per_bucket=1)
        >>> for err in (9.0, 9.0, 0.0, 0.0, 0.0):  # old errors age out
        ...     recent.update(jnp.array([err]), jnp.array([0.0]))
        >>> float(recent.compute())  # only the last 3 buckets remain
        0.0

    Ring mode: ``window`` buckets of ``updates_per_bucket`` updates each;
    ``compute()`` covers the whole ring, ``compute(window=w)`` the last
    ``w`` buckets. Decay mode: ``WindowedMetric(m, mode="decay",
    decay=0.99)`` keeps one exponentially-decayed copy of each sum leaf.
    Reset / state_dict / merge_states / sync ride the stock
    :class:`Metric` machinery — the states are ordinary array leaves.
    """

    higher_is_better = None
    is_differentiable = False

    def __init__(
        self,
        metric: Metric,
        *,
        window: Optional[int] = None,
        updates_per_bucket: Optional[int] = None,
        mode: str = "ring",
        decay: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise MetricsUserError(
                f"WindowedMetric wraps a Metric instance, got {type(metric).__name__}"
            )
        if isinstance(metric, WindowedMetric):
            raise MetricsUserError("WindowedMetric cannot wrap another WindowedMetric")
        if mode not in _MODES:
            raise MetricsUserError(f"`mode` must be one of {_MODES}, got {mode!r}")
        if mode == "ring":
            window = 8 if window is None else window
            updates_per_bucket = 1 if updates_per_bucket is None else updates_per_bucket
            if not isinstance(window, int) or window < 2:
                raise MetricsUserError(f"`window` must be an int >= 2, got {window!r}")
            if not isinstance(updates_per_bucket, int) or updates_per_bucket < 1:
                raise MetricsUserError(
                    f"`updates_per_bucket` must be a positive int, got {updates_per_bucket!r}"
                )
            if decay is not None:
                raise MetricsUserError("`decay` only applies to mode='decay'")
        else:
            if window is not None or updates_per_bucket is not None:
                # a silently-ignored ring knob would answer a different
                # question than the caller configured (mirrors ring mode
                # rejecting `decay`)
                raise MetricsUserError(
                    "`window`/`updates_per_bucket` only apply to mode='ring'"
                )
            window, updates_per_bucket = 0, 0  # unused in decay paths
            if decay is None:
                decay = 0.99
            if not isinstance(decay, (int, float)) or not (0.0 < float(decay) < 1.0):
                raise MetricsUserError(f"`decay` must be a float in (0, 1), got {decay!r}")
        self.mode = mode
        self.window = int(window)
        self.updates_per_bucket = int(updates_per_bucket)
        self._alpha = float(decay) if decay is not None else None
        self._validate_windowable(metric, mode)
        # template metric, stored via object.__setattr__ so it does NOT
        # register as a child (a child registry would mark this class a
        # wrapper and statically exclude it from the fused path) — the
        # SlicedMetric precedent
        object.__setattr__(self, "_template", metric.clone())
        self._template.reset()
        m = self._template
        if mode == "ring":
            for name, red in m._reductions.items():
                default = jnp.asarray(m._defaults[name])
                ringed = jnp.broadcast_to(default, (self.window,) + default.shape)
                if red is dim_zero_sum:
                    fx: Any = ring_sum_fx()
                elif red is dim_zero_max:
                    fx = "max"
                elif red is dim_zero_min:
                    fx = "min"
                else:  # merge_like (validated)
                    fx = ring_merge_fx(red)
                self.add_state(name, default=jnp.array(ringed), dist_reduce_fx=fx)
            # literal state names (== the RING_* module constants, pinned by
            # test) so the tracelint interpreter serializes these leaves —
            # and their ring reducers — into the fusibility manifest
            self.add_state("_ring_rows", default=jnp.zeros(self.window, jnp.int32), dist_reduce_fx="ring")
            self.add_state("_ring_count", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="max")
        else:
            for name in m._reductions:
                default = jnp.asarray(m._defaults[name])
                if jnp.issubdtype(default.dtype, jnp.integer) or default.dtype == jnp.bool_:
                    # a decayed count is fractional by construction — an
                    # integer leaf would truncate alpha to 0 and silently
                    # reset instead of decaying
                    default = default.astype(jnp.float32)
                self.add_state(name, default=default, dist_reduce_fx="decay")
            self.add_state("_decay_weight", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="decay")
        # pad-and-mask contract: the wrapper performs its own slot-aware
        # pad correction (or threads n_valid into a masking template), so
        # bucketed fused dispatches stay exact — see _update/_pad_correct
        self.__fused_mask_valid__ = True
        # host-side ring clock for freshness stamps: wall time of each live
        # bucket's FIRST eager write (telemetry-enabled eager updates only —
        # fused/traced updates have no host hook, so stamps are best-effort
        # and a stamp-free ring folds as identity)
        self._bucket_wall: List[Optional[float]] = [None] * max(self.window, 1)
        self._last_fold_buckets = 0
        self._last_fold_oldest_wall: Optional[float] = None
        # --- incremental read plane (ring mode; docs/incremental_reads.md)
        # Prefix-fold memo: window start bucket -> (highest completed bucket
        # folded, left-associated fold over the non-empty completed buckets
        # in that range, or None when all were empty). A completed bucket's
        # row is immutable until overwritten a full ring later, and every
        # queryable window satisfies w <= R, so a memoized prefix is
        # bit-identical to refolding it — reads extend the prefix by newly
        # completed buckets instead of refolding the whole window.
        self._fold_memo: "OrderedDict[int, Tuple[int, Optional[Dict[str, Array]]]]" = OrderedDict()
        # Final folded-state memo: (window, before) -> (ring clock, state).
        # The clock advances on every rotation, so an equal clock means the
        # rows — and therefore the fold — are identical: repeat reads at an
        # idle clock are pure cache hits.
        self._wstate_memo: "OrderedDict[Tuple[int, int], Tuple[int, Dict[str, Array]]]" = OrderedDict()
        self._last_fold_fanin = 0
        self._last_read_cache_hit = False
        self._readers = ReaderCache()
        _LIVE_WINDOWED.add(self)

    # ------------------------------------------------------------------
    # construction-time validation
    # ------------------------------------------------------------------
    @staticmethod
    def _validate_windowable(metric: Metric, mode: str) -> None:
        cls_name = type(metric).__name__
        if getattr(metric, "__jit_unsafe__", False):
            raise MetricsUserError(
                f"`{cls_name}` declares `__jit_unsafe__` — its update cannot trace, so it"
                " cannot run inside the windowed ring/decay kernel."
            )
        if metric._children:
            raise MetricsUserError(
                f"`{cls_name}` is a wrapper metric (child registry"
                f" {sorted(dict(metric._iter_child_metrics()))}); window the inner"
                " metric directly instead of the wrapper."
            )
        for name, red in metric._reductions.items():
            default = metric._defaults[name]
            if isinstance(default, list):
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` is a list ('cat') state; unbounded"
                    " concatenation has no fixed-shape ring row. Use the metric's"
                    " sketched mode (fixed-capacity merge leaves window exactly)."
                )
            if name in _RESERVED:
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` collides with a reserved windowed"
                    " state name"
                )
            merge_like = bool(getattr(red, "merge_like", False))
            if mode == "decay":
                if red is not dim_zero_sum:
                    hint = (
                        " (extrema cannot forget and sketch weights must not be scaled"
                        " — use mode='ring')"
                        if red in (dim_zero_max, dim_zero_min) or merge_like
                        else ""
                    )
                    raise MetricsUserError(
                        f"`{cls_name}` state `{name}` has reducer"
                        f" `{_reducer_name(red)}`; exponential decay is only exact for"
                        f" sum-reduced leaves{hint}. A mean-style metric should"
                        " accumulate sum-reduced numerator/denominator leaves."
                    )
            elif red not in (dim_zero_sum, dim_zero_max, dim_zero_min) and not merge_like:
                hint = (
                    " (the auto mean-merge counter has no per-bucket fold)"
                    if name == _AUTO_COUNT
                    else ""
                )
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` has reducer"
                    f" `{_reducer_name(red)}`; only sum/max/min/merge-reduced array"
                    f" states have an exact per-bucket ring fold{hint}. A mean-style"
                    " metric should accumulate sum-reduced numerator/denominator"
                    " leaves (see MeanMetric)."
                )

    # ------------------------------------------------------------------
    # update
    # ------------------------------------------------------------------
    @property
    def wrapped(self) -> Metric:
        """The wrapped template metric (its states are placeholders)."""
        return self._template

    @property
    def bucket_counts(self) -> Array:
        """Updates absorbed per ring bucket, ``[R]`` int32 (ring mode)."""
        if self.mode != "ring":
            raise MetricsUserError("`bucket_counts` is a ring-mode query")
        return jnp.asarray(getattr(self, RING_ROWS))

    @property
    def decay_weight(self) -> Array:
        """Effective decayed sample weight ``sum_i alpha^i`` (decay mode)."""
        if self.mode != "decay":
            raise MetricsUserError("`decay_weight` is a decay-mode query")
        return jnp.asarray(getattr(self, DECAY_WEIGHT))

    def _pad_correct(
        self,
        new: Dict[str, Array],
        args: Any,
        fkw: Dict[str, Any],
        n_valid: Optional[Array],
        m: Metric,
    ) -> Dict[str, Array]:
        """Remove the edge-pad rows' contribution from the template's
        sum-reduced leaves: pads replicate the last real row (the fused
        bucketing contract), so their contribution is ``k_pad *
        delta(last_row)`` — subtracted HERE, where the live ring slot is
        known, instead of by the fused kernel's generic correction (which
        probes from the default state and would land at slot 0)."""
        if n_valid is None:
            return new
        leaves, treedef = jax.tree_util.tree_flatten((args, fkw))
        b = None
        for x in leaves:
            if isinstance(x, (jnp.ndarray, np.ndarray)) and getattr(x, "ndim", 0) >= 1:
                b = int(x.shape[0])  # static leading dim (shape read)
                break
        if b is None:
            return new
        k_pad = jnp.asarray(b, jnp.int32) - jnp.asarray(n_valid, jnp.int32)
        pad_leaves = []
        for x in leaves:
            if isinstance(x, (jnp.ndarray, np.ndarray)) and getattr(x, "ndim", 0) >= 1:
                pad_leaves.append(x[-1:])
            else:
                pad_leaves.append(x)
        pa, pkw = jax.tree_util.tree_unflatten(treedef, pad_leaves)
        init = {k: jnp.asarray(v) for k, v in m._defaults.items()}
        d = m.update_state(dict(init), *pa, **pkw)
        out = dict(new)
        for name, red in m._reductions.items():
            if red is dim_zero_sum:
                delta = d[name] - init[name]
                out[name] = out[name] - delta * k_pad.astype(jnp.result_type(delta))
        return out

    def _update(self, *args: Any, **kwargs: Any) -> None:
        m = self._template
        n_valid = kwargs.pop("n_valid", None)
        template_masks = bool(getattr(m, "__fused_mask_valid__", False))
        fkw = m._filter_kwargs(**kwargs)
        call_kw = fkw
        if template_masks and n_valid is not None:
            # the template owns its merge-leaf pad masking (weight-0 sketch
            # inserts) — but its SUM companions (e.g. a sketched curve's
            # n_seen) still count the full padded batch, so the k * delta
            # correction below applies to them either way; the pad probe
            # runs on `fkw` (no n_valid) so the single-row delta is the
            # full unmasked contribution being removed
            call_kw = dict(fkw)
            call_kw["n_valid"] = n_valid

        if self.mode == "decay":
            base = {
                name: jnp.asarray(self._alpha, jnp.asarray(getattr(self, name)).dtype)
                * jnp.asarray(getattr(self, name))
                for name in m._defaults
            }
            new = m.update_state(base, *args, **call_kw)
            new = self._pad_correct(new, args, fkw, n_valid, m)
            for name in m._defaults:
                # keep the registered (float-promoted) dtype: the template's
                # update may hand back its own integer arithmetic
                dtype = jnp.asarray(self._defaults[name]).dtype
                object.__setattr__(self, name, jnp.asarray(new[name]).astype(dtype))
            w = jnp.asarray(getattr(self, DECAY_WEIGHT))
            object.__setattr__(self, DECAY_WEIGHT, jnp.asarray(self._alpha, w.dtype) * w + 1.0)
            return

        count = jnp.asarray(getattr(self, RING_COUNT))
        k, r = self.updates_per_bucket, self.window
        if _TELEMETRY.enabled and not isinstance(count, jax.core.Tracer):
            # eager path with a concrete clock: stamp the bucket's first
            # write so window folds can report their wall-clock reach
            c = int(count)  # tracelint: disable=TL-TRACE — the isinstance(Tracer) guard above makes this eager-only
            s = (c // k) % r
            if c % k == 0 or self._bucket_wall[s] is None:
                self._bucket_wall[s] = time.time()
        slot = (count // k) % r
        fresh = (count % k) == 0
        defaults = {name: jnp.asarray(v) for name, v in m._defaults.items()}
        base = {}
        for name in m._defaults:
            leaf = jnp.asarray(getattr(self, name))
            # first update of a bucket restores the slot to defaults, so a
            # wrapped (expired) bucket self-evicts before accumulating
            base[name] = jnp.where(fresh, defaults[name], leaf[slot])
        new = m.update_state(base, *args, **call_kw)
        new = self._pad_correct(new, args, fkw, n_valid, m)
        for name in m._defaults:
            leaf = jnp.asarray(getattr(self, name))
            object.__setattr__(self, name, leaf.at[slot].set(new[name].astype(leaf.dtype)))
        rows = jnp.asarray(getattr(self, RING_ROWS))
        object.__setattr__(
            self, RING_ROWS, rows.at[slot].set(jnp.where(fresh, 0, rows[slot]) + 1)
        )
        object.__setattr__(self, RING_COUNT, count + 1)

    # ------------------------------------------------------------------
    # incremental read plane: install hooks
    # ------------------------------------------------------------------
    def _mark_state_written(self) -> None:
        # out-of-band installs (reset/restore/load/group-borrow) replace
        # states wholesale — the fold memos describe rows that no longer
        # exist, so drop them; only ring rotations keep them warm
        super()._mark_state_written()
        memo = getattr(self, "_fold_memo", None)
        if memo is not None:
            memo.clear()
            self._wstate_memo.clear()

    def _mark_fused_written(self) -> None:
        # a fused/async apply traces _update, so the kernel performed
        # exactly the eager ring rotation: completed buckets stay immutable
        # and the prefix-fold memo stays warm. Advance the epoch clock
        # without the foreign-write memo wipe. (The final-state memo keys
        # on the ring clock, so it self-invalidates as the clock advances.)
        self._update_called = True
        self._write_epoch += 1
        self._computed = None

    def set_dtype(self, dst_type) -> "Metric":
        # memoized folds hold the OLD dtype's bits; extending them after a
        # cast would mix dtypes in one fold
        out = super().set_dtype(dst_type)
        self._fold_memo.clear()
        self._wstate_memo.clear()
        return out

    # ------------------------------------------------------------------
    # window folds / compute
    # ------------------------------------------------------------------
    def _window_rows(self, window: int, before: int = 0) -> List[Dict[str, Array]]:
        """The last ``window`` buckets' row states ending ``before`` buckets
        back, oldest first. Host-side (compute is an eager, host-driven
        cycle like every other metric's) — requires a concrete clock."""
        m = self._template
        count = int(getattr(self, RING_COUNT))
        if count == 0:
            return []
        k, r = self.updates_per_bucket, self.window
        cur = (count - 1) // k - before
        if cur < 0:
            return []
        lo = max(cur - window + 1, 0)
        if (count - 1) // k - lo >= r:
            raise MetricsUserError(
                f"window of {window} bucket(s) ending {before} back reaches past the"
                f" ring span ({r} buckets); those buckets were already evicted"
            )
        rows: List[Dict[str, Array]] = []
        counts = np.asarray(getattr(self, RING_ROWS))
        walls: List[float] = []
        for b in range(lo, cur + 1):
            if counts[b % r] <= 0:
                continue  # a bucket `before` skipped past (never filled)
            rows.append({name: jnp.asarray(getattr(self, name))[b % r] for name in m._defaults})
            w_b = self._bucket_wall[b % r]
            if w_b is not None:
                walls.append(w_b)
        # read-event side channel: how many ring buckets this fold covered
        # and how far back (wall clock) the oldest one reaches
        self._last_fold_buckets = len(rows)
        self._last_fold_oldest_wall = min(walls) if walls else None
        return rows

    def window_state(self, window: Optional[int] = None, *, before: int = 0) -> Dict[str, Array]:
        """The wrapped metric's state folded over the last ``window``
        buckets (default: the whole ring) ending ``before`` buckets back —
        the unit :mod:`metrics_tpu.observability.drift` compares. Rows fold
        oldest-first through the wrapped reducers (``merge_states``), so
        sum leaves are exact and sketch leaves keep arrival order.

        Every direct call is a READ: with telemetry enabled it emits one
        typed ``read`` event (kind ``"window"``) carrying the ring buckets
        folded and a :class:`FreshnessStamp` with the fold's wall-clock
        reach (``ring_span_s``). The internal fold ``_compute`` runs is
        not re-counted — plain ``compute()`` emits its own read event."""
        if not _TELEMETRY.enabled:  # disabled read path stays ONE bool check
            return self._window_state_impl(window, before=before)
        t0 = time.perf_counter()
        state = self._window_state_impl(window, before=before)
        _TELEMETRY.record_read(
            "window",
            self,
            duration_s=time.perf_counter() - t0,
            ring_buckets=self._last_fold_buckets,
            cache_hit=self._last_read_cache_hit,
            fanin=self._last_fold_fanin,
            freshness=self._window_freshness(),
        )
        return state

    def _window_state_impl(self, window: Optional[int] = None, *, before: int = 0) -> Dict[str, Array]:
        if self.mode != "ring":
            raise MetricsUserError(
                "window_state() is a ring-mode query; decay mode keeps one decayed state"
            )
        w = self.window if window is None else window
        if not isinstance(w, int) or w < 1:
            raise MetricsUserError(f"`window` must be a positive int, got {w!r}")
        if w > self.window:
            # the same strict-eviction contract `before` over-reach gets: a
            # silently clamped answer would report an R-bucket value labeled
            # as a wider window
            raise MetricsUserError(
                f"`window` of {w} bucket(s) exceeds the ring span ({self.window});"
                " construct the metric with a larger `window` to query it"
            )
        if not isinstance(before, int) or before < 0:
            raise MetricsUserError(f"`before` must be a non-negative int, got {before!r}")
        m = self._template
        if not self._is_synced and not isinstance(
            jnp.asarray(getattr(self, RING_COUNT)), jax.core.Tracer
        ):
            return self._window_state_incremental(w, before)
        # synced (cross-rank) rows describe a different stream than the
        # local fold memos — fold cold without reading or writing them
        rows = self._window_rows(w, before)
        self._last_fold_fanin = len(rows)
        self._last_read_cache_hit = False
        if not rows:
            return {name: jnp.array(v) for name, v in m._defaults.items()}
        state = rows[0]
        for row in rows[1:]:
            state = m.merge_states(state, row)
        return state

    def _window_state_incremental(self, w: int, before: int) -> Dict[str, Array]:
        """Memoized window fold (local states, concrete clock).

        The fold over buckets ``[lo, cur]`` splits at the current bucket:
        completed buckets ``[lo, cur-1]`` are immutable (a ring slot is only
        overwritten a full ring later, and ``w <= R`` keeps every queryable
        bucket ahead of that), so their left-associated prefix fold is
        memoized per window start and extended only by newly completed
        buckets; the still-filling bucket ``cur`` merges on top per read.
        The merge op sequence is identical to the cold oldest-first fold,
        so the result is bit-identical."""
        m = self._template
        count = int(getattr(self, RING_COUNT))
        k, r = self.updates_per_bucket, self.window
        cur = (count - 1) // k - before
        if count == 0 or cur < 0:
            self._last_fold_buckets = 0
            self._last_fold_oldest_wall = None
            self._last_fold_fanin = 0
            self._last_read_cache_hit = False
            return {name: jnp.array(v) for name, v in m._defaults.items()}
        lo = max(cur - w + 1, 0)
        if (count - 1) // k - lo >= r:
            raise MetricsUserError(
                f"window of {w} bucket(s) ending {before} back reaches past the"
                f" ring span ({r} buckets); those buckets were already evicted"
            )
        counts = np.asarray(getattr(self, RING_ROWS))
        live = [b for b in range(lo, cur + 1) if counts[b % r] > 0]
        walls = [x for x in (self._bucket_wall[b % r] for b in live) if x is not None]
        self._last_fold_buckets = len(live)
        self._last_fold_oldest_wall = min(walls) if walls else None
        if not live:
            self._last_fold_fanin = 0
            self._last_read_cache_hit = False
            return {name: jnp.array(v) for name, v in m._defaults.items()}
        # repeat read at an idle clock: identical rows, identical fold
        hit = self._wstate_memo.get((w, before))
        if hit is not None and hit[0] == count:
            self._wstate_memo.move_to_end((w, before))
            self._last_fold_fanin = 0
            self._last_read_cache_hit = True
            return dict(hit[1])
        # prefix fold over the completed buckets [lo, cur-1]
        stored = self._fold_memo.get(lo)
        if stored is not None and stored[0] <= cur - 1:
            prev_hi, prefix = stored
        else:
            # no memo for this window start, or a `before`-shifted read
            # whose window ends before the stored prefix does (never
            # truncate a longer prefix — refold this read from scratch)
            prev_hi, prefix = lo - 1, None
        fold = [b for b in live if prev_hi < b <= cur - 1]
        fanin = len(fold)
        if fold:
            if prefix is None and len(fold) >= 2 and self._aot_foldable():
                prefix = self._fold_rows_aot([b % r for b in fold])
            else:
                for b in fold:
                    row = {name: jnp.asarray(getattr(self, name))[b % r] for name in m._defaults}
                    prefix = row if prefix is None else m.merge_states(prefix, row)
        if cur - 1 >= lo and (stored is None or stored[0] < cur - 1):
            self._fold_memo[lo] = (cur - 1, prefix)
            self._fold_memo.move_to_end(lo)
            while len(self._fold_memo) > _FOLD_MEMO_MAX:
                self._fold_memo.popitem(last=False)
        state = prefix
        if counts[cur % r] > 0:
            row = {name: jnp.asarray(getattr(self, name))[cur % r] for name in m._defaults}
            state = row if state is None else m.merge_states(state, row)
            fanin += 1
        self._last_fold_fanin = fanin
        self._last_read_cache_hit = False
        self._wstate_memo[(w, before)] = (count, state)
        self._wstate_memo.move_to_end((w, before))
        while len(self._wstate_memo) > _FOLD_MEMO_MAX:
            self._wstate_memo.popitem(last=False)
        # shallow copy: callers may treat the dict as theirs; the memoized
        # leaves are immutable arrays, the dict must not be shared
        return dict(state)

    def _aot_foldable(self) -> bool:
        """Pure sum/max/min templates refold through one pre-lowered
        executable; merge-like (sketch) leaves fold eagerly so their
        per-merge telemetry accounting keeps firing."""
        m = self._template
        return all(
            red in (dim_zero_sum, dim_zero_max, dim_zero_min)
            for red in m._reductions.values()
        )

    def _fold_rows_aot(self, slots: List[int]) -> Dict[str, Array]:
        """Refold ``n`` completed buckets through one AOT-compiled
        executable: the left-associated per-leaf merge sequence is unrolled
        inside the trace (XLA preserves float op order), so the result is
        bit-identical to the eager ``merge_states`` loop while the host
        pays one dispatch instead of ``n``. Keyed on ``n`` — bounded by
        the ring span ``R``."""
        m = self._template
        n = len(slots)
        reds = dict(m._reductions)

        def build():
            def fold(stacked: Dict[str, Array]) -> Dict[str, Array]:
                state = {name: v[0] for name, v in stacked.items()}
                for i in range(1, n):
                    for name, red in reds.items():
                        a, b = state[name], stacked[name][i]
                        if red is dim_zero_sum:
                            state[name] = a + b
                        elif red is dim_zero_max:
                            state[name] = jnp.maximum(a, b)
                        else:
                            state[name] = jnp.minimum(a, b)
                return state

            return fold

        idx = jnp.asarray(np.asarray(slots, np.int32))
        stacked = {name: jnp.asarray(getattr(self, name))[idx] for name in m._defaults}
        reader = self._readers.get("window_fold", build, stacked, bucket=n)
        return dict(reader(stacked))

    def _compute(self) -> Any:
        m = self._template
        if self.mode == "decay":
            return m.compute_state({name: getattr(self, name) for name in m._defaults})
        # the un-instrumented fold: the enclosing Metric.compute() emits the
        # read event and picks the fold size up through _read_extras()
        return m.compute_state(self._window_state_impl())

    def compute(self, *, window: Optional[int] = None, before: Optional[int] = None) -> Any:
        """The wrapped metric over the window.

        With no arguments: the whole ring (or the decayed state) through
        the ordinary :meth:`Metric.compute` cycle (caching, distributed
        sync). ``window=w`` evaluates the last ``w`` buckets only —
        local states, no sync, no cache; ``before=b`` shifts the window
        end ``b`` buckets back (how drift comparators read a reference
        window). Ring mode only."""
        if window is None and before is None:
            return super().compute()
        if self.mode != "ring":
            raise MetricsUserError("compute(window=...) is a ring-mode query")
        m = self._template
        return _squeeze_if_scalar(
            m.compute_state(self.window_state(window, before=before or 0))
        )

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def _window_freshness(self, now: Optional[float] = None) -> FreshnessStamp:
        """Stamp for the most recent window fold: the oldest in-window
        bucket's first-write wall time bounds the window's reach
        (``ring_span_s``); identity components when the ring was filled
        through a traced (fused) path that leaves no host stamps."""
        now = time.time() if now is None else now
        oldest = self._last_fold_oldest_wall
        return FreshnessStamp(
            min_event_t=oldest,
            max_event_t=self._ingest_last_t,
            ring_span_s=max(0.0, now - oldest) if oldest is not None else 0.0,
        )

    def freshness_stamp(self, now: Optional[float] = None) -> FreshnessStamp:
        """Ring-aware stamp: data older than the live ring was evicted, so
        ``min_event_t`` is the oldest LIVE bucket's first write, not the
        first ingest since reset, and ``ring_span_s`` is the ring's
        wall-clock reach."""
        base = super().freshness_stamp(now)
        if self.mode != "ring":
            return base
        walls = [w for w in self._bucket_wall if w is not None]
        if not walls:
            return base
        oldest = min(walls)
        now = time.time() if now is None else now
        return FreshnessStamp(
            min_event_t=oldest if base.min_event_t is None else max(base.min_event_t, oldest),
            max_event_t=base.max_event_t,
            ring_span_s=max(0.0, now - oldest),
        )

    def _read_extras(self) -> Dict[str, Any]:
        if self.mode != "ring":
            return {}
        return {
            "ring_buckets": self._last_fold_buckets,
            "cache_hit": self._last_read_cache_hit,
            "fanin": self._last_fold_fanin,
        }

    def reset(self) -> None:
        super().reset()
        self._bucket_wall = [None] * max(self.window, 1)
        self._last_fold_buckets = 0
        self._last_fold_oldest_wall = None

    def state_footprint(self, include_children: bool = True) -> Dict[str, int]:
        """Per-state bytes with every key under ``windowed/`` — the
        telemetry recorder splits on the prefix so the ``R``-fold window
        cost tracks under a distinct ``<Metric>[windowed]`` high-water-mark
        label instead of masquerading as base-state growth."""
        base = super().state_footprint(include_children=include_children)
        return {f"{WINDOWED_FOOTPRINT_PREFIX}{k}": v for k, v in base.items()}

    def __repr__(self) -> str:
        inner = type(self._template).__name__
        if self.mode == "decay":
            return f"{type(self).__name__}({inner}(), mode='decay', decay={self._alpha})"
        return (
            f"{type(self).__name__}({inner}(), window={self.window},"
            f" updates_per_bucket={self.updates_per_bucket})"
        )
