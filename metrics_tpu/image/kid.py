"""Kernel Inception Distance (polynomial MMD over feature subsets).

Behavior parity with /root/reference/torchmetrics/image/kid.py:29-269.
``feature`` accepts any callable ``imgs -> [N, d]`` or an int depth for the
bundled Flax InceptionV3 (see fid.py).
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD^2 estimate from kernel matrices. Reference kid.py:29-47."""
    m = k_xx.shape[0]

    kt_xx_sum = jnp.sum(k_xx) - jnp.sum(jnp.diag(k_xx))
    kt_yy_sum = jnp.sum(k_yy) - jnp.sum(jnp.diag(k_yy))
    k_xy_sum = jnp.sum(k_xy)

    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    return value - 2 * k_xy_sum / (m**2)


def poly_kernel(
    f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial kernel. Reference kid.py:50-56."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (jnp.matmul(f1, f2.T, precision=jax.lax.Precision.HIGHEST) * gamma + coef) ** degree


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial-kernel MMD. Reference kid.py:59-66."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """Computes KID (mean and std of polynomial MMD over random subsets)."""

    __jit_unsafe__ = True
    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        seed: Optional[int] = None,
        feature_extractor_weights_path: str = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        rank_zero_warn(
            "Metric `KernelInceptionDistance` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )

        if isinstance(feature, int):
            valid_int_input = (64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.models.inception import build_fid_inception

            self.inception = build_fid_inception(feature, feature_extractor_weights_path)
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError("Got unknown input to argument `feature`")

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        self._rng = np.random.RandomState(seed)

        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def _update(self, imgs: Array, real: bool) -> None:
        features = self.inception(imgs)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def _compute(self) -> Tuple[Array, Array]:
        getattr(self.inception, "finalize", lambda: None)()  # flush async range check of the last batch
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        kid_scores_ = []
        for _ in range(self.subsets):
            perm = self._rng.permutation(n_samples_real)
            f_real = real_features[perm[: self.subset_size]]
            perm = self._rng.permutation(n_samples_fake)
            f_fake = fake_features[perm[: self.subset_size]]
            kid_scores_.append(poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid_scores = jnp.stack(kid_scores_)
        # ddof=1: reference kid.py returns torch.std (unbiased) over subsets
        return jnp.mean(kid_scores), jnp.std(kid_scores, ddof=1)
