"""Retrieval fall-out.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
fall_out.py:20-61.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs, _check_retrieval_k

Array = jax.Array


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of non-relevant documents retrieved in the top k.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_fall_out(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    k = preds.shape[-1] if k is None else k
    _check_retrieval_k(k)

    target = 1 - target
    if not jnp.sum(target):
        return jnp.asarray(0.0, dtype=preds.dtype)

    relevant = jnp.sum(target[jnp.argsort(-preds, axis=-1)][:k]).astype(jnp.float32)
    return relevant / jnp.sum(target)
