"""BERTScore parity vs the reference implementation.

No network: a tiny randomly-initialized BERT + WordPiece tokenizer is built
locally, saved to disk, and loaded twice — as a torch model for the
reference oracle (/root/reference/torchmetrics/functional/text/bert.py) and
as a Flax model for our implementation. Sentences are pre-sorted by token
length because the reference returns scores in length-sorted order (its
dataloader sorts and never restores input order).
"""
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from metrics_tpu.functional.text.bert import bert_score
from metrics_tpu.text.bert import BERTScore
from tests.helpers.reference import load_reference_module

_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "hello", "there", "general", "kenobi", "master", "the", "cat", "sat",
    "on", "a", "mat", "dog", "ran", "fast", "big", "red", "house",
]

# strictly increasing token lengths -> the reference's length sort is identity
_PREDS = ["hello there", "the cat sat on a mat", "the big red dog ran fast on the mat"]
_TARGET = ["hello there", "a cat sat on the mat", "the big red cat ran fast on a mat"]


def _own_tokenizer(tokenizer, tensors):
    """Adapt an AutoTokenizer to the (text, max_length) user-tokenizer protocol."""

    def call(texts, max_length):
        return tokenizer(texts, padding=True, max_length=max_length, truncation=True, return_tensors=tensors)

    return call


@pytest.fixture(scope="module")
def tiny_bert_dir(tmp_path_factory):
    import torch
    from transformers import BertConfig, BertModel, BertTokenizerFast

    directory = tmp_path_factory.mktemp("tiny_bert")
    vocab_file = directory / "vocab.txt"
    vocab_file.write_text("\n".join(_VOCAB))
    tokenizer = BertTokenizerFast(vocab_file=str(vocab_file), do_lower_case=True)
    tokenizer.save_pretrained(str(directory))

    torch.manual_seed(0)
    config = BertConfig(
        vocab_size=len(_VOCAB),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    model = BertModel(config).eval()
    model.save_pretrained(str(directory))
    return str(directory)


def _reference_scores(model_dir, preds, target, **kwargs):
    import torch
    from transformers import AutoTokenizer, BertModel

    ref_bert = load_reference_module("torchmetrics.functional.text.bert")
    tokenizer = AutoTokenizer.from_pretrained(model_dir)
    model = BertModel.from_pretrained(model_dir).eval()
    with torch.no_grad():
        return ref_bert.bert_score(
            preds,
            target,
            model=model,
            user_tokenizer=tokenizer,
            num_threads=0,
            **kwargs,
        )


@pytest.fixture(scope="module")
def flax_model(tiny_bert_dir):
    from transformers import FlaxBertModel

    return FlaxBertModel.from_pretrained(tiny_bert_dir, from_pt=True)


@pytest.mark.parametrize("idf", [False, True])
def test_bert_score_matches_reference(tiny_bert_dir, flax_model, idf):
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)
    got = bert_score(
        _PREDS, _TARGET, model=flax_model,
        user_tokenizer=tokenizer, idf=idf, num_layers=2, batch_size=2, max_length=32,
    )
    want = _reference_scores(tiny_bert_dir, _PREDS, _TARGET, idf=idf, num_layers=2, batch_size=2, max_length=32)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(got[key], want[key], atol=2e-4, err_msg=key)


def test_bert_score_all_layers(tiny_bert_dir, flax_model):
    want = _reference_scores(tiny_bert_dir, _PREDS, _TARGET, all_layers=True, batch_size=2, max_length=32)
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)
    got = bert_score(
        _PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer,
        all_layers=True, batch_size=2, max_length=32,
    )
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            np.asarray(got[key]).reshape(-1), np.asarray(want[key]).reshape(-1), atol=2e-4, err_msg=key
        )


def test_bert_score_identical_sentences_near_one(flax_model, tiny_bert_dir):
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)
    got = bert_score(["hello there"], ["hello there"], model=flax_model, user_tokenizer=tokenizer)
    assert got["f1"][0] == pytest.approx(1.0, abs=1e-5)


def test_bert_score_user_forward_fn(flax_model, tiny_bert_dir):
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)

    def forward_fn(model, batch):
        out = model(input_ids=batch["input_ids"], attention_mask=batch["attention_mask"],
                    output_hidden_states=True)
        return out.hidden_states[-1]

    got = bert_score(
        _PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer, user_forward_fn=forward_fn
    )
    direct = bert_score(_PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer)
    # the plain (texts, max_length) user-tokenizer protocol also works
    protocol = bert_score(
        _PREDS, _TARGET, model=flax_model, user_tokenizer=_own_tokenizer(tokenizer, "np")
    )
    np.testing.assert_allclose(protocol["f1"], direct["f1"], atol=1e-6)
    np.testing.assert_allclose(got["f1"], direct["f1"], atol=1e-6)


def test_bert_score_class_accumulates(flax_model, tiny_bert_dir):
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)
    metric = BERTScore(model=flax_model, user_tokenizer=tokenizer, batch_size=2)
    metric.update(_PREDS[:1], _TARGET[:1])
    metric.update(_PREDS[1:], _TARGET[1:])
    got = metric.compute()
    whole = bert_score(_PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer, batch_size=2)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(got[key], whole[key], atol=1e-5, err_msg=key)


def test_bert_score_errors():
    with pytest.raises(ValueError, match="same"):
        bert_score(["a"], ["a", "b"], model=lambda i, m: None)
    with pytest.raises(ValueError, match="model"):
        bert_score(["a"], ["b"])  # no model, no local path
    with pytest.raises(ValueError, match="user_tokenizer|tokenizer"):
        BERTScore()  # no tokenizer and no local path
    out = bert_score([], [], model=lambda i, m: None, return_hash=True)
    assert out["precision"] == [0.0] and "hash" in out


def test_bert_score_rescale_with_local_baseline(flax_model, tiny_bert_dir, tmp_path):
    """Baseline rescaling from a LOCAL csv (the reference downloads these;
    here the (x - b) / (1 - b) transform is checked against a manual
    computation; reference bert.py:440-456)."""
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tiny_bert_dir)
    raw = bert_score(_PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer, num_layers=2)

    baseline = 0.25
    csv_path = tmp_path / "baseline.csv"
    # bert-score baseline format: header row, then one row per layer:
    # layer_index, P, R, F  (num_layers=2 -> row index 2 must exist)
    lines = ["LAYER,P,R,F"] + [f"{i},{baseline},{baseline},{baseline}" for i in range(4)]
    csv_path.write_text("\n".join(lines))

    rescaled = bert_score(
        _PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer, num_layers=2,
        rescale_with_baseline=True, baseline_path=str(csv_path),
    )
    for key in ("precision", "recall", "f1"):
        want = (np.asarray(raw[key]) - baseline) / (1 - baseline)
        np.testing.assert_allclose(rescaled[key], want, atol=1e-6, err_msg=key)

    with pytest.raises(ValueError, match="baseline_path"):
        bert_score(_PREDS, _TARGET, model=flax_model, user_tokenizer=tokenizer,
                   rescale_with_baseline=True)
