"""MetricCollection tests: construction, compute groups, prefix/postfix.

Mirrors /root/reference/tests/bases/test_collections.py in spirit.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import (
    Accuracy,
    CohenKappa,
    ConfusionMatrix,
    MeanSquaredError,
    Precision,
    Recall,
)
from metrics_tpu.collections import MetricCollection
from tests.helpers.testers import NUM_CLASSES

_rng = np.random.RandomState(42)
_preds = jnp.asarray(_rng.randint(0, 3, 32))
_target = jnp.asarray(_rng.randint(0, 3, 32))


def test_list_construction():
    mc = MetricCollection([Accuracy(), Precision(num_classes=3, average="macro")])
    res = mc(_preds, _target)
    assert set(res.keys()) == {"Accuracy", "Precision"}


def test_args_construction():
    mc = MetricCollection(Accuracy(), Precision(num_classes=3, average="macro"))
    assert set(mc.keys(keep_base=True)) == {"Accuracy", "Precision"}


def test_dict_construction():
    mc = MetricCollection(
        {"micro": Recall(num_classes=3, average="micro"), "macro": Recall(num_classes=3, average="macro")}
    )
    res = mc(_preds, _target)
    assert set(res.keys()) == {"micro", "macro"}


def test_duplicate_names_raise():
    with pytest.raises(ValueError, match="two metrics both named"):
        MetricCollection([Accuracy(), Accuracy()])


def test_not_a_metric_raises():
    with pytest.raises(ValueError):
        MetricCollection([Accuracy(), "not-a-metric"])
    with pytest.raises(ValueError):
        MetricCollection({"a": "not-a-metric"})


def test_prefix_postfix():
    mc = MetricCollection([Accuracy()], prefix="train_", postfix="_step")
    res = mc(_preds, _target)
    assert list(res.keys()) == ["train_Accuracy_step"]
    clone = mc.clone(prefix="val_")
    res2 = clone(_preds, _target)
    assert list(res2.keys()) == ["val_Accuracy_step"]
    with pytest.raises(ValueError):
        MetricCollection([Accuracy()], prefix=5)


def test_compute_groups_discovered():
    """Precision and Recall (same StatScores state) must merge into one group;
    MeanSquaredError stays separate."""
    mc = MetricCollection(
        [
            Precision(num_classes=3, average="macro"),
            Recall(num_classes=3, average="macro"),
        ]
    )
    mc.update(_preds, _target)
    groups = mc.compute_groups
    assert len(groups) == 1 and set(groups[0]) == {"Precision", "Recall"}

    # values must match individually-updated metrics across further updates
    p2 = jnp.asarray(_rng.randint(0, 3, 32))
    t2 = jnp.asarray(_rng.randint(0, 3, 32))
    mc.update(p2, t2)
    res = mc.compute()

    p_ref = Precision(num_classes=3, average="macro")
    r_ref = Recall(num_classes=3, average="macro")
    for p, t in [(_preds, _target), (p2, t2)]:
        p_ref.update(p, t)
        r_ref.update(p, t)
    np.testing.assert_allclose(np.asarray(res["Precision"]), np.asarray(p_ref.compute()), atol=1e-6)
    np.testing.assert_allclose(np.asarray(res["Recall"]), np.asarray(r_ref.compute()), atol=1e-6)


def test_compute_groups_not_merged_when_states_differ():
    mc = MetricCollection(
        [Accuracy(), ConfusionMatrix(num_classes=3)]
    )
    mc.update(_preds, _target)
    assert len(mc.compute_groups) == 2


def test_compute_groups_not_merged_when_hyperparams_differ():
    # states coincide on the first batch only by chance of the update path;
    # differing update-time hyperparameters must keep the metrics separate
    mc = MetricCollection({"lo": Accuracy(threshold=0.3), "hi": Accuracy(threshold=0.7)})
    probs = jnp.asarray([0.35, 0.5, 0.65, 0.2])
    tgt = jnp.asarray([0, 1, 1, 0])
    mc.update(probs, tgt)
    assert len(mc.compute_groups) == 2
    mc.update(probs, tgt)
    res = mc.compute()
    lo_ref, hi_ref = Accuracy(threshold=0.3), Accuracy(threshold=0.7)
    for _ in range(2):
        lo_ref.update(probs, tgt)
        hi_ref.update(probs, tgt)
    np.testing.assert_allclose(np.asarray(res["lo"]), np.asarray(lo_ref.compute()))
    np.testing.assert_allclose(np.asarray(res["hi"]), np.asarray(hi_ref.compute()))


def test_compute_groups_user_specified():
    mc = MetricCollection(
        Precision(num_classes=3, average="macro"),
        Recall(num_classes=3, average="macro"),
        MeanSquaredError(),
        compute_groups=[["Precision", "Recall"], ["MeanSquaredError"]],
    )
    assert len(mc.compute_groups) == 2
    with pytest.raises(ValueError):
        MetricCollection(Accuracy(), compute_groups=[["NotPresent"]])


def test_compute_groups_disabled():
    mc = MetricCollection([Precision(num_classes=3, average="macro"), Recall(num_classes=3, average="macro")],
                          compute_groups=False)
    mc.update(_preds, _target)
    assert mc.compute_groups == {}


def test_reset_keeps_groups_and_correctness():
    mc = MetricCollection([Precision(num_classes=3, average="macro"), Recall(num_classes=3, average="macro")])
    mc.update(_preds, _target)
    assert len(mc.compute_groups) == 1
    mc.reset()
    mc.update(_preds, _target)
    res = mc.compute()
    p_ref = Precision(num_classes=3, average="macro")
    p_ref.update(_preds, _target)
    np.testing.assert_allclose(np.asarray(res["Precision"]), np.asarray(p_ref.compute()), atol=1e-6)


def test_state_dict_roundtrip():
    mc = MetricCollection([Accuracy(), CohenKappa(num_classes=3)])
    mc.update(_preds, _target)
    sd = mc.state_dict()
    mc2 = MetricCollection([Accuracy(), CohenKappa(num_classes=3)])
    mc2.load_state_dict(sd)
    res1, res2 = mc.compute(), mc2.compute()
    for k in res1:
        np.testing.assert_allclose(np.asarray(res1[k]), np.asarray(res2[k]), atol=1e-6)


def test_collection_kwarg_filtering():
    """Kwargs not in a metric's update signature are filtered out."""
    mc = MetricCollection([Accuracy()])
    res = mc(_preds, target=_target, unused_kwarg=123)
    assert "Accuracy" in res


def test_add_metrics_and_clone_prefix():
    """Parity with reference test_collections.py:234-246 add_metrics and
    clone-with-prefix behaviors."""
    col = MetricCollection([Accuracy()])
    col.add_metrics({"prec": Precision(num_classes=NUM_CLASSES, average="macro")})
    col.add_metrics(Recall(num_classes=NUM_CLASSES, average="macro"))
    assert set(col.keys()) == {"Accuracy", "prec", "Recall"}

    cloned = col.clone(prefix="val_")
    assert set(cloned.keys()) == {"val_Accuracy", "val_prec", "val_Recall"}
    preds = jnp.asarray(_rng.rand(16, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(_rng.randint(0, NUM_CLASSES, 16))
    cloned.update(preds, target)
    out = cloned.compute()
    assert set(out.keys()) == {"val_Accuracy", "val_prec", "val_Recall"}
    # clone is independent: original remains un-updated
    import pytest as _pytest
    with _pytest.warns(UserWarning, match="before"):
        col.compute()


def test_collection_repr_and_order():
    col = MetricCollection([Accuracy(), MeanSquaredError()])
    rep = repr(col)
    assert "Accuracy" in rep and "MeanSquaredError" in rep
    # insertion order is preserved (reference test_metric_collection_same_order)
    assert list(col.keys()) == ["Accuracy", "MeanSquaredError"]


def test_error_on_wrong_compute_groups_spec():
    with pytest.raises(ValueError, match="compute_groups"):
        MetricCollection([Accuracy(), MeanSquaredError()], compute_groups=[["Accuracy", "NotThere"]])
