"""Pallas TPU kernel: tiled pairwise box IoU.

The N x M IoU matrix is the detection hot op (reference delegates it to
torchvision's C++/CUDA box_iou, map.py:367; SURVEY §2.9 flags it as a
Pallas-tile candidate). The jnp broadcast version materializes
``[N, M, 4]``-shaped intermediates in HBM for large N*M; this kernel streams
``(128, 128)`` output tiles through VMEM with the coordinate columns held as
``[4, tile]`` blocks, so the broadcast happens entirely on-chip (VPU
elementwise, f32 (8, 128) tiling).

Use :func:`box_iou_tiled` (host wrapper: pads to tile multiples, slices
back). `interpret=True` runs the same kernel on CPU for tests.
"""
import functools
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from metrics_tpu.ops.dispatch import dispatch, register_kernel

try:  # TPU-specific memory spaces; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

Array = jax.Array
ArrayLike = Union[Array, np.ndarray]

_TILE = 128


def _iou_tile_kernel(b1_ref, b2_ref, out_ref):
    """One (TILE, TILE) IoU tile from [4, TILE] coordinate blocks."""
    x11, y11, x12, y12 = (b1_ref[i, :][:, None] for i in range(4))  # [TILE, 1]
    x21, y21, x22, y22 = (b2_ref[i, :][None, :] for i in range(4))  # [1, TILE]

    inter_w = jnp.maximum(jnp.minimum(x12, x22) - jnp.maximum(x11, x21), 0.0)
    inter_h = jnp.maximum(jnp.minimum(y12, y22) - jnp.maximum(y11, y21), 0.0)
    inter = inter_w * inter_h
    area1 = (x12 - x11) * (y12 - y11)
    area2 = (x22 - x21) * (y22 - y21)
    union = area1 + area2 - inter
    # padded slots have zero area; keep them 0 instead of 0/0 NaN
    out_ref[:, :] = jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def box_iou_tiled(boxes1: ArrayLike, boxes2: ArrayLike, interpret: bool = False) -> Array:
    """Pairwise IoU ``[N, 4] x [M, 4] -> [N, M]`` via the Pallas tile kernel.

    Pads N and M up to multiples of 128 (padding contributes zero-area boxes
    whose IoU is defined as 0 here) and slices the result back.
    """
    boxes1 = jnp.asarray(boxes1, jnp.float32)
    boxes2 = jnp.asarray(boxes2, jnp.float32)
    n, m = boxes1.shape[0], boxes2.shape[0]
    n_pad = -(-max(n, 1) // _TILE) * _TILE
    m_pad = -(-max(m, 1) // _TILE) * _TILE

    b1 = jnp.zeros((4, n_pad), jnp.float32).at[:, :n].set(boxes1.T)
    b2 = jnp.zeros((4, m_pad), jnp.float32).at[:, :m].set(boxes2.T)

    ms = {"memory_space": _VMEM} if (not interpret and _VMEM is not None) else {}
    kwargs = {
        "in_specs": [
            pl.BlockSpec((4, _TILE), lambda i, j: (0, i), **ms),
            pl.BlockSpec((4, _TILE), lambda i, j: (0, j), **ms),
        ],
        "out_specs": pl.BlockSpec((_TILE, _TILE), lambda i, j: (i, j), **ms),
    }

    iou = pl.pallas_call(
        _iou_tile_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, m_pad), jnp.float32),
        grid=(n_pad // _TILE, m_pad // _TILE),
        interpret=interpret,
        **kwargs,
    )(b1, b2)
    return iou[:n, :m]


def _iou_unit_kernel(b1_ref, b2_ref, out_ref):
    """One unit's [D_pad, G_pad] IoU tile from [1, 4, D_pad]/[1, 4, G_pad]
    coordinate blocks (the batched grid walks units)."""
    x11, y11, x12, y12 = (b1_ref[0, i, :][:, None] for i in range(4))  # [D_pad, 1]
    x21, y21, x22, y22 = (b2_ref[0, i, :][None, :] for i in range(4))  # [1, G_pad]

    inter_w = jnp.maximum(jnp.minimum(x12, x22) - jnp.maximum(x11, x21), 0.0)
    inter_h = jnp.maximum(jnp.minimum(y12, y22) - jnp.maximum(y11, y21), 0.0)
    inter = inter_w * inter_h
    area1 = (x12 - x11) * (y12 - y11)
    area2 = (x22 - x21) * (y22 - y21)
    union = area1 + area2 - inter
    out_ref[0, :, :] = jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def box_iou_batched_tiled(boxes1: ArrayLike, boxes2: ArrayLike, interpret: bool = False) -> Array:
    """Batched pairwise IoU ``[U, D, 4] x [U, G, 4] -> [U, D, G]``.

    The detection matching kernel's shape (functional/detection/mean_ap.py):
    one grid step per (image, class) unit, coordinates staged as
    ``[1, 4, D_pad]`` VMEM blocks, D/G padded to the f32 VPU lane tiling
    (8, 128). COCO-scale units (D<=128, G<=32) fit one tile each.
    """
    boxes1 = jnp.asarray(boxes1, jnp.float32)
    boxes2 = jnp.asarray(boxes2, jnp.float32)
    u, d, g = boxes1.shape[0], boxes1.shape[1], boxes2.shape[1]
    # sublane x lane tiling: pad D (second-minor) to 8, G (minor) to 128
    d_pad = -(-max(d, 1) // 8) * 8
    g_pad = -(-max(g, 1) // 128) * 128

    b1 = jnp.zeros((u, 4, d_pad), jnp.float32).at[:, :, :d].set(jnp.swapaxes(boxes1, 1, 2))
    b2 = jnp.zeros((u, 4, g_pad), jnp.float32).at[:, :, :g].set(jnp.swapaxes(boxes2, 1, 2))

    ms = {"memory_space": _VMEM} if (not interpret and _VMEM is not None) else {}
    iou = pl.pallas_call(
        _iou_unit_kernel,
        out_shape=jax.ShapeDtypeStruct((u, d_pad, g_pad), jnp.float32),
        grid=(u,),
        in_specs=[
            pl.BlockSpec((1, 4, d_pad), lambda i: (i, 0, 0), **ms),
            pl.BlockSpec((1, 4, g_pad), lambda i: (i, 0, 0), **ms),
        ],
        out_specs=pl.BlockSpec((1, d_pad, g_pad), lambda i: (i, 0, 0), **ms),
        interpret=interpret,
    )(b1, b2)
    return iou[:, :d, :g]


def _box_iou_route(boxes1: Array, boxes2: Array, min_elems: int = 1 << 20) -> bool:
    """Route predicate for the ``"box_iou"`` registry entry.

    Measured on-chip (see BASELINE.md "Pallas box-IoU A/B"): for the 2-D
    [N, 4] x [M, 4] case the tile kernel is bit-exact vs the jnp broadcast
    and performs on par with it (XLA already fuses the broadcast chain into
    one kernel, so there are no HBM intermediates to save at these sizes).
    For the BATCHED [U, D, 4] x [U, G, 4] case — the detection matching
    kernel's shape — the unit-grid Pallas kernel avoids the [U, D, G, 4]
    broadcast intermediates; the route accepts it above ``min_elems``
    output elements, where the measured win holds. The Pallas kernels
    compute in float32; under x64 a float64 result would silently lose
    precision vs the jnp fallback, so f64 problems always take the
    fallback — values AND dtype are dispatch-invariant.
    """
    out_dtype = jnp.result_type(boxes1.dtype, boxes2.dtype, jnp.float32)
    if jnp.issubdtype(out_dtype, jnp.floating) and out_dtype == jnp.float64:
        return False
    if boxes1.ndim == 2 and boxes2.ndim == 2:
        return boxes1.shape[0] * boxes2.shape[0] >= min_elems
    if boxes1.ndim == 3 and boxes2.ndim == 3:
        return (
            boxes1.shape[0] == boxes2.shape[0]
            and boxes1.shape[0] * boxes1.shape[1] * boxes2.shape[1] >= min_elems
            # the unit tile pads G to 128 lanes and D to 8 sublanes; the
            # measured on-chip win (BASELINE.md) holds when the lane padding
            # waste is <= 4x (G >= 32): 1.13x at [4096, 128, 32], 1.54x at
            # [1024, 128, 128], but 0.48x at [16384, 64, 16] where 8x lane
            # waste dominates
            and boxes2.shape[1] >= 32
            and boxes1.shape[1] >= 8
        )
    return False


def _box_iou_pallas(
    boxes1: Array, boxes2: Array, min_elems: int = 1 << 20, interpret: bool = False
) -> Array:
    # IoU is a ratio: both paths produce floating point. Match the jnp
    # fallback's promotion (true division promotes ints to float) so the
    # dispatch never changes dtype or values.
    out_dtype = jnp.result_type(boxes1.dtype, boxes2.dtype, jnp.float32)
    if not jnp.issubdtype(out_dtype, jnp.floating):
        out_dtype = jnp.float32
    # a forced-interpret dispatch bypasses the route predicate; shapes the
    # kernels cannot take (mixed ndim, mismatched batch, f64 precision)
    # still belong to the fallback
    if out_dtype == jnp.float64:
        return _box_iou_jnp(boxes1, boxes2, min_elems)
    if boxes1.ndim == 2 and boxes2.ndim == 2:
        return box_iou_tiled(boxes1, boxes2, interpret=interpret).astype(out_dtype)
    if boxes1.ndim == 3 and boxes2.ndim == 3 and boxes1.shape[0] == boxes2.shape[0]:
        return box_iou_batched_tiled(boxes1, boxes2, interpret=interpret).astype(out_dtype)
    return _box_iou_jnp(boxes1, boxes2, min_elems)


def _box_iou_jnp(boxes1: Array, boxes2: Array, min_elems: int = 1 << 20) -> Array:
    from metrics_tpu.functional.detection.box_ops import box_iou as _jnp_box_iou

    return _jnp_box_iou(boxes1, boxes2)


register_kernel(
    "box_iou",
    pallas_fn=_box_iou_pallas,
    jnp_fn=_box_iou_jnp,
    route=_box_iou_route,
)


def box_iou_dispatch(boxes1: ArrayLike, boxes2: ArrayLike, min_elems: int = 1 << 20) -> Array:
    """Pairwise box IoU through the ops kernel registry: the Pallas tile
    kernels on TPU where :func:`_box_iou_route` predicts a win, the jnp
    broadcast everywhere else (and always under ``METRICS_TPU_NO_PALLAS``).
    Values and dtype are dispatch-invariant."""
    return dispatch("box_iou", jnp.asarray(boxes1), jnp.asarray(boxes2), min_elems)
