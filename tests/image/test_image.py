"""Image metrics vs the reference TorchMetrics implementation on torch-CPU
(the reference's own oracles — skimage/torch_fidelity — are not available in
this image, so the mounted reference serves as the oracle, mirroring its
tests' parametrizations)."""
import sys
import types

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import (
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.functional import (
    image_gradients,
    multiscale_structural_similarity_index_measure,
    peak_signal_noise_ratio,
    structural_similarity_index_measure,
    universal_image_quality_index,
)


@pytest.fixture(scope="module")
def reference():
    if "pkg_resources" not in sys.modules:
        stub = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        stub.DistributionNotFound = DistributionNotFound
        stub.get_distribution = get_distribution
        sys.modules["pkg_resources"] = stub
    sys.path.insert(0, "/root/reference")
    import torchmetrics

    yield torchmetrics
    sys.path.remove("/root/reference")


_rng = np.random.RandomState(42)
PREDS = _rng.rand(4, 3, 32, 32).astype(np.float32)
TARGET = (0.7 * PREDS + 0.3 * _rng.rand(4, 3, 32, 32)).astype(np.float32)


def test_psnr_parity(reference):
    import torch

    for kwargs in [{}, {"data_range": 1.0}, {"base": 2.0}, {"data_range": 1.0, "dim": (1, 2, 3)}]:
        got = peak_signal_noise_ratio(jnp.asarray(PREDS), jnp.asarray(TARGET), **kwargs)
        want = reference.functional.peak_signal_noise_ratio(
            torch.from_numpy(PREDS), torch.from_numpy(TARGET), **kwargs
        )
        np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-4, err_msg=str(kwargs))


def test_psnr_class_parity(reference):
    import torch

    m = PeakSignalNoiseRatio()
    ref = reference.PeakSignalNoiseRatio()
    for i in range(2):
        m.update(jnp.asarray(PREDS[i * 2:(i + 1) * 2]), jnp.asarray(TARGET[i * 2:(i + 1) * 2]))
        ref.update(torch.from_numpy(PREDS[i * 2:(i + 1) * 2]), torch.from_numpy(TARGET[i * 2:(i + 1) * 2]))
    np.testing.assert_allclose(np.asarray(m.compute()), ref.compute().numpy(), atol=1e-4)


def test_ssim_parity(reference):
    import torch

    for kwargs in [{}, {"data_range": 1.0}, {"kernel_size": (7, 7), "sigma": (1.0, 1.0)}]:
        got = structural_similarity_index_measure(jnp.asarray(PREDS), jnp.asarray(TARGET), **kwargs)
        want = reference.functional.structural_similarity_index_measure(
            torch.from_numpy(PREDS), torch.from_numpy(TARGET), **kwargs
        )
        np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-4, err_msg=str(kwargs))


def test_ssim_class_parity(reference):
    import torch

    m = StructuralSimilarityIndexMeasure()
    ref = reference.StructuralSimilarityIndexMeasure()
    m.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ref.update(torch.from_numpy(PREDS), torch.from_numpy(TARGET))
    np.testing.assert_allclose(np.asarray(m.compute()), ref.compute().numpy(), atol=1e-4)


def test_ms_ssim_parity(reference):
    import torch

    preds = _rng.rand(1, 2, 256, 256).astype(np.float32)
    target = (0.8 * preds + 0.2 * _rng.rand(1, 2, 256, 256)).astype(np.float32)
    for kwargs in [{}, {"normalize": "relu"}, {"normalize": "simple"}]:
        got = multiscale_structural_similarity_index_measure(jnp.asarray(preds), jnp.asarray(target), **kwargs)
        want = reference.functional.multiscale_structural_similarity_index_measure(
            torch.from_numpy(preds), torch.from_numpy(target), **kwargs
        )
        np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-4, err_msg=str(kwargs))


def test_uqi_parity(reference):
    import torch

    got = universal_image_quality_index(jnp.asarray(PREDS), jnp.asarray(TARGET))
    want = reference.functional.universal_image_quality_index(
        torch.from_numpy(PREDS), torch.from_numpy(TARGET)
    )
    np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-4)

    m = UniversalImageQualityIndex()
    m.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    np.testing.assert_allclose(np.asarray(m.compute()), want.numpy(), atol=1e-4)


def test_image_gradients():
    img = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
    dy, dx = image_gradients(img)
    np.testing.assert_allclose(np.asarray(dy[0, 0, :-1]), 4.0)
    np.testing.assert_allclose(np.asarray(dy[0, 0, -1]), 0.0)
    np.testing.assert_allclose(np.asarray(dx[0, 0, :, :-1]), 1.0)
    with pytest.raises(RuntimeError):
        image_gradients(jnp.ones((4, 4)))
    with pytest.raises(TypeError):
        image_gradients([[1.0]])


def test_ssim_invalid_inputs():
    with pytest.raises(ValueError):
        structural_similarity_index_measure(jnp.ones((4, 4)), jnp.ones((4, 4)))
    with pytest.raises(TypeError):
        structural_similarity_index_measure(
            jnp.ones((1, 1, 8, 8), dtype=jnp.float32), jnp.ones((1, 1, 8, 8), dtype=jnp.bfloat16)
        )
    with pytest.raises(ValueError):
        structural_similarity_index_measure(
            jnp.ones((1, 1, 8, 8)), jnp.ones((1, 1, 8, 8)), kernel_size=(4, 4)
        )
    with pytest.raises(ValueError):
        multiscale_structural_similarity_index_measure(
            jnp.ones((1, 1, 16, 16)), jnp.ones((1, 1, 16, 16))
        )


def test_ssim_jit():
    import jax

    got = jax.jit(structural_similarity_index_measure)(jnp.asarray(PREDS), jnp.asarray(TARGET))
    eager = structural_similarity_index_measure(jnp.asarray(PREDS), jnp.asarray(TARGET))
    np.testing.assert_allclose(np.asarray(got), np.asarray(eager), atol=1e-6)


def test_psnr_merge_states():
    m = PeakSignalNoiseRatio(data_range=1.0)
    s1 = m.update_state(m.init_state(), jnp.asarray(PREDS[:2]), jnp.asarray(TARGET[:2]))
    s2 = m.update_state(m.init_state(), jnp.asarray(PREDS[2:]), jnp.asarray(TARGET[2:]))
    merged = m.merge_states(s1, s2)
    both = m.update_state(s1, jnp.asarray(PREDS[2:]), jnp.asarray(TARGET[2:]))
    np.testing.assert_allclose(
        np.asarray(m.compute_state(merged)), np.asarray(m.compute_state(both)), atol=1e-5
    )
