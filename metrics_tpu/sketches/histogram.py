"""Static-edge weighted histogram: the degenerate-but-exact sketch.

When the downstream statistic only ever reads BINNED aggregates (top-label
calibration error bins confidences into ``n_bins`` before comparing
accuracy and confidence), the fixed-shape streaming state is not an
approximation at all: per-bin weighted sums are sufficient statistics, so
the converted metric is exact for every stream length at ``O(n_bins)``
memory — and because the state leaves are plain ``"sum"``-reduced arrays,
they ride every existing layer (fused dispatch with exact pad-and-mask
correction, ``SlicedMetric`` per-leaf scatter, ``sync_pytree_in_mesh``'s
fused all-reduce round) with zero new plumbing.

Contract mirrors the other sketches: ``init -> leaf``, pure jit-safe
``insert``, trivial ``merge`` (addition). The bin-index convention is the
calibration kernel's ``searchsorted(side='left') - 1`` (see
``functional/classification/calibration_error.py``) so binned states are
bit-compatible with the exact compute's bucketize.
"""
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def hist_init(n_bins: int, n_stats: int = 1) -> Array:
    """Fresh ``[n_stats, n_bins]`` zero histogram (rows are independent
    per-bin weighted sums, e.g. count / confidence-sum / accuracy-sum)."""
    if not (isinstance(n_bins, int) and n_bins > 0):
        raise ValueError(f"`n_bins` must be a positive int, got {n_bins}")
    if not (isinstance(n_stats, int) and n_stats > 0):
        raise ValueError(f"`n_stats` must be a positive int, got {n_stats}")
    return jnp.zeros((n_stats, n_bins), jnp.float32)


def hist_bin_index(edges: Array, x: Array) -> Array:
    """Bin index per sample under the calibration bucketize convention."""
    n_bins = edges.shape[0] - 1
    return jnp.clip(jnp.searchsorted(edges, x, side="left") - 1, 0, n_bins - 1)


def hist_insert(
    hist: Array,
    bin_idx: Array,
    stats: Array,
    weights: Optional[Array] = None,
    n_valid: Optional[Array] = None,
) -> Array:
    """Scatter-add ``[n_stats, B]`` per-sample statistics into their bins;
    pure and jit-safe. ``n_valid`` masks trailing pad rows (fused
    pad-and-mask contract) — though for purely additive histogram states
    the fused path's ``k * delta`` sum correction is equally exact."""
    stats = jnp.asarray(stats, jnp.float32)
    if stats.ndim == 1:
        stats = stats[None, :]
    w = jnp.ones(stats.shape[1], jnp.float32) if weights is None else jnp.asarray(weights, jnp.float32)
    if n_valid is not None:
        w = w * (jnp.arange(stats.shape[1]) < n_valid)
    return hist.at[:, bin_idx].add(w[None, :] * stats)


def hist_merge(a: Array, b: Array) -> Array:
    """Histograms merge by addition (the ``"sum"`` reducer IS the merge)."""
    return a + b
