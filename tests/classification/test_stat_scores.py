"""StatScores vs sklearn multilabel_confusion_matrix oracle."""
import numpy as np
import pytest
from sklearn.metrics import multilabel_confusion_matrix

from metrics_tpu.classification import StatScores
from metrics_tpu.functional import stat_scores
from tests.classification.inputs import _input_multiclass, _input_multiclass_prob
from tests.helpers.testers import NUM_CLASSES, MetricTester


def _sk_stat_scores_macro(preds, target):
    preds, target = np.asarray(preds), np.asarray(target)
    if preds.ndim == target.ndim + 1:
        preds = np.argmax(preds, axis=1)
    mcm = multilabel_confusion_matrix(target, preds, labels=np.arange(NUM_CLASSES))
    tn, fp, fn, tp = mcm[:, 0, 0], mcm[:, 0, 1], mcm[:, 1, 0], mcm[:, 1, 1]
    return np.stack([tp, fp, tn, fn, tp + fn], axis=-1)


def _sk_stat_scores_micro(preds, target):
    per_class = _sk_stat_scores_macro(preds, target)
    return per_class.sum(axis=0)


@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_multiclass.preds, _input_multiclass.target),
        (_input_multiclass_prob.preds, _input_multiclass_prob.target),
    ],
)
class TestStatScores(MetricTester):
    def test_stat_scores_macro(self, preds, target):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=StatScores,
            sk_metric=_sk_stat_scores_macro,
            metric_args={"reduce": "macro", "num_classes": NUM_CLASSES},
        )

    def test_stat_scores_micro(self, preds, target):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=StatScores,
            sk_metric=_sk_stat_scores_micro,
            metric_args={"reduce": "micro", "num_classes": NUM_CLASSES},
        )

    def test_stat_scores_fn(self, preds, target):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=stat_scores,
            sk_metric=_sk_stat_scores_macro,
            metric_args={"reduce": "macro", "num_classes": NUM_CLASSES},
        )


def test_stat_scores_invalid_args():
    with pytest.raises(ValueError):
        StatScores(reduce="invalid")
    with pytest.raises(ValueError):
        StatScores(reduce="macro")  # num_classes missing
    with pytest.raises(ValueError):
        StatScores(mdmc_reduce="invalid")


@pytest.mark.parametrize("reduce", ["micro", "macro"])
def test_negative_ignore_index_raises(reduce):
    """Negative ignore_index must fail loudly in StatScores-family metrics
    that don't infer the input mode (silent corruption guard); Accuracy's
    mode-inferring drop path keeps supporting it."""
    import jax.numpy as jnp

    from metrics_tpu.classification import Accuracy, Precision

    preds = jnp.array([0, 1, 2, 1])
    target = jnp.array([0, 1, 2, -1])
    m = StatScores(reduce=reduce, num_classes=3, ignore_index=-1)
    with pytest.raises(ValueError, match="negative"):
        m.update(preds, target)
    p = Precision(average="macro", num_classes=3, ignore_index=-1)
    with pytest.raises(ValueError, match="negative"):
        p.update(preds, target)

    acc = Accuracy(num_classes=3, ignore_index=-1)
    assert float(acc(preds, target)) == 1.0
