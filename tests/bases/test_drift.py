"""Drift observatory (ISSUE 12): PSI/KL/JS/TV scores, the DriftRule's
freeze-then-compare lifecycle as the seventh standard alarm class, the
``metrics_tpu_drift_score`` Prometheus family, and aggregate-payload
carry-through (incl. the mixed-version-fleet identity contract).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.observability import get_recorder
from metrics_tpu.observability.drift import (
    DRIFT_STATS,
    categorical_drift,
    histogram_drift,
    js_divergence_hist,
    kl_divergence_hist,
    normalize_histogram,
    psi_divergence,
    reference_edges,
    sketch_drift,
    state_drift,
    total_variation,
)
from metrics_tpu.observability.health import DriftRule, HealthMonitor, default_rules
from metrics_tpu.observability.recorder import SERIES_SCORES
from metrics_tpu.observability.timeseries import TimeSeriesRegistry
from metrics_tpu.sketches.quantile import qsketch_init, qsketch_insert

T0 = 50_000.0


@pytest.fixture
def recorder():
    rec = get_recorder()
    rec.reset()
    rec.enable()
    try:
        yield rec
    finally:
        rec.disable()
        rec.detach_timeseries()
        rec.reset()


def _registry(**kwargs):
    kwargs.setdefault("bucket_seconds", 1.0)
    kwargs.setdefault("n_buckets", 60)
    kwargs.setdefault("sketch_capacity", 128)
    return TimeSeriesRegistry(**kwargs)


def _sketch_of(values, capacity=256):
    sk = qsketch_init(capacity)
    return qsketch_insert(sk, jnp.asarray(np.asarray(values, np.float32)))


# ---------------------------------------------------------------------------
# score math
# ---------------------------------------------------------------------------

class TestScores:
    def test_identical_histograms_score_zero(self):
        h = jnp.asarray([10.0, 20.0, 30.0, 40.0])
        assert psi_divergence(h, h) == pytest.approx(0.0, abs=1e-6)
        assert kl_divergence_hist(h, h) == pytest.approx(0.0, abs=1e-6)
        assert js_divergence_hist(h, h) == pytest.approx(0.0, abs=1e-6)
        assert total_variation(h, h) == pytest.approx(0.0, abs=1e-6)

    def test_known_values_and_bounds(self):
        p = [80.0, 20.0]
        q = [20.0, 80.0]
        # PSI closed form: (0.8-0.2)ln(4) + (0.2-0.8)ln(1/4) = 1.2*ln 4
        assert psi_divergence(p, q) == pytest.approx(1.2 * np.log(4.0), rel=1e-3)
        assert total_variation(p, q) == pytest.approx(0.6, rel=1e-3)
        assert 0.0 < js_divergence_hist(p, q) <= np.log(2.0) + 1e-6
        # KL is asymmetric; JS/TV/PSI symmetric
        assert psi_divergence(p, q) == pytest.approx(psi_divergence(q, p), rel=1e-6)
        assert total_variation(p, q) == pytest.approx(total_variation(q, p), rel=1e-6)

    def test_empty_sides_are_finite(self):
        """Relative smoothing: one-sided-empty bins contribute large-but-
        finite terms; two empty histograms compare as identical uniform."""
        assert np.isfinite(psi_divergence([0.0, 10.0], [10.0, 0.0]))
        assert psi_divergence([0.0, 0.0], [0.0, 0.0]) == pytest.approx(0.0, abs=1e-6)

    def test_normalize_histogram_floors_bins(self):
        p = np.asarray(normalize_histogram([0.0, 100.0]))
        assert p.sum() == pytest.approx(1.0, rel=1e-6)
        assert p[0] > 0  # floored, never exactly zero

    def test_histogram_drift_reports_all_stats(self):
        out = histogram_drift([5.0, 5.0], [9.0, 1.0])
        assert set(out) == set(DRIFT_STATS)
        assert all(np.isfinite(v) for v in out.values())

    def test_categorical_drift_confusion_matrices(self):
        ref = jnp.asarray([[50.0, 5.0], [5.0, 40.0]])
        live_same = ref * 3.0  # scale-invariant
        assert categorical_drift(ref, live_same)["tv"] == pytest.approx(0.0, abs=1e-4)
        live_flipped = jnp.asarray([[5.0, 50.0], [40.0, 5.0]])
        assert categorical_drift(ref, live_flipped)["tv"] > 0.5
        with pytest.raises(ValueError, match="same-shaped"):
            categorical_drift(jnp.zeros((2, 2)), jnp.zeros((3, 3)))


# ---------------------------------------------------------------------------
# sketch comparisons
# ---------------------------------------------------------------------------

class TestSketchDrift:
    def test_same_distribution_scores_low_shifted_scores_high(self):
        rng = np.random.RandomState(0)
        ref = _sketch_of(rng.normal(0.3, 0.1, 2000).clip(0, 1))
        same = _sketch_of(rng.normal(0.3, 0.1, 2000).clip(0, 1))
        shifted = _sketch_of(rng.normal(0.8, 0.1, 2000).clip(0, 1))
        edges = reference_edges(ref, n_bins=10)
        low = sketch_drift(ref, same, edges)
        high = sketch_drift(ref, shifted, edges)
        assert low["psi"] < 0.1 < high["psi"]
        assert low["tv"] < 0.1 < high["tv"]

    def test_reference_edges_validation(self):
        with pytest.raises(ValueError, match="empty sketch"):
            reference_edges(qsketch_init(16))
        with pytest.raises(ValueError, match="n_bins"):
            reference_edges(_sketch_of([1.0, 2.0]), n_bins=1)

    def test_state_drift_over_windowed_folds(self):
        """The windowed-metric integration: reference vs live window folds
        of a ring-of-sketches AUROC diverge when the score stream shifts."""
        from metrics_tpu import AUROC, WindowedMetric

        rng = np.random.RandomState(1)
        wm = WindowedMetric(AUROC(pos_label=1, sketch_capacity=256), window=6, updates_per_bucket=1)
        for _ in range(3):
            wm.update(
                jnp.asarray(rng.normal(0.3, 0.1, 64).clip(0, 1).astype(np.float32)),
                jnp.asarray((rng.rand(64) < 0.4).astype(np.int32)),
            )
        for _ in range(3):
            wm.update(
                jnp.asarray(rng.normal(0.8, 0.1, 64).clip(0, 1).astype(np.float32)),
                jnp.asarray((rng.rand(64) < 0.4).astype(np.int32)),
            )
        scores = state_drift(wm.wrapped, wm.window_state(3, before=3), wm.window_state(3))
        assert "csketch" in scores
        assert scores["csketch"]["psi"] > 0.5
        assert 0.0 < scores["csketch"]["tv"] <= 1.0

    def test_state_drift_accepts_the_wrapper_itself(self):
        """Passing the WindowedMetric (not .wrapped) must not silently
        skip its categorical sum leaves — the tagged ring reducers are
        sum-shaped and the window folds are template-shaped."""
        from metrics_tpu import ConfusionMatrix, WindowedMetric

        rng = np.random.RandomState(8)
        wm = WindowedMetric(ConfusionMatrix(num_classes=2), window=6, updates_per_bucket=1)
        for _ in range(3):
            t = jnp.asarray(rng.randint(0, 2, 64).astype(np.int32))
            wm.update(t, t)  # diagonal mass
        for _ in range(3):
            t = jnp.asarray(rng.randint(0, 2, 64).astype(np.int32))
            wm.update(1 - t, t)  # flipped: off-diagonal mass
        scores = state_drift(wm, wm.window_state(3, before=3), wm.window_state(3))
        assert "confmat" in scores and scores["confmat"]["tv"] > 0.5

    def test_window_past_ring_span_raises_not_clamps(self):
        from metrics_tpu import MeanSquaredError, WindowedMetric
        from metrics_tpu.utils.exceptions import MetricsUserError

        wm = WindowedMetric(MeanSquaredError(), window=4)
        wm.update(jnp.asarray([1.0]), jnp.asarray([0.0]))
        with pytest.raises(MetricsUserError, match="exceeds the ring span"):
            wm.compute(window=100)

    def test_state_drift_skips_reservoir_leaves(self):
        """Reservoir leaves pack [Gumbel priority, payload] rows — reading
        the priority column as a weight scores identical distributions as
        drifted, so non-quantile sketch kinds are skipped."""
        from metrics_tpu import SpearmanCorrCoef

        rng = np.random.RandomState(9)
        a = SpearmanCorrCoef()
        b = SpearmanCorrCoef()
        for m in (a, b):
            x = rng.rand(128).astype(np.float32)
            m.update(jnp.asarray(x), jnp.asarray((x + rng.rand(128) * 0.1).astype(np.float32)))
        scores = state_drift(
            a,
            {k: getattr(a, k) for k in a._defaults},
            {k: getattr(b, k) for k in b._defaults},
        )
        assert "rsketch" not in scores

    def test_state_drift_categorical_sum_leaves(self):
        from metrics_tpu import ConfusionMatrix

        ref_m = ConfusionMatrix(num_classes=2)
        ref_m.update(jnp.asarray([0, 0, 1, 1]), jnp.asarray([0, 0, 1, 1]))
        live_m = ConfusionMatrix(num_classes=2)
        live_m.update(jnp.asarray([1, 1, 0, 0]), jnp.asarray([0, 0, 1, 1]))
        scores = state_drift(
            ref_m,
            {"confmat": getattr(ref_m, "confmat")},
            {"confmat": getattr(live_m, "confmat")},
        )
        assert scores["confmat"]["tv"] == pytest.approx(1.0, abs=1e-3)


# ---------------------------------------------------------------------------
# DriftRule lifecycle
# ---------------------------------------------------------------------------

def _feed(reg, dist, t0, seconds, rate=20, per=32, rng=None):
    rng = rng or np.random.RandomState(0)
    t = t0
    for _ in range(int(seconds * rate)):
        for v in dist(rng, per):
            reg.observe(SERIES_SCORES, float(v), t=t)
        t += 1.0 / rate
    return t


def _healthy(rng, n):
    return np.clip(rng.normal(0.3, 0.1, n), 0, 1)


def _shifted(rng, n):
    return np.clip(rng.normal(0.8, 0.08, n), 0, 1)


class TestDriftRule:
    def test_fires_on_shift_and_clears_on_recovery(self, recorder):
        reg = _registry()
        rule = DriftRule("score_drift", SERIES_SCORES, stat="psi", threshold=0.25,
                         window_s=5.0, freeze_after=100, min_count=16)
        mon = HealthMonitor([rule], registry=reg)
        rng = np.random.RandomState(2)
        t = _feed(reg, _healthy, T0, 2.0, rng=rng)
        snap = mon.evaluate(now=t)
        assert not snap.firing and "frozen" in snap.alarms[0].detail
        snap = mon.evaluate(now=t)  # healthy live vs healthy reference
        assert not snap.firing and snap.alarms[0].value < 0.25

        t2 = _feed(reg, _shifted, t + 10, 6.0, rng=rng)
        snap = mon.evaluate(now=t2)
        assert snap.firing and snap.alarms[0].value > 0.25
        assert snap.status == "warn"

        t3 = _feed(reg, _healthy, t2 + 10, 6.0, rng=rng)
        snap = mon.evaluate(now=t3)
        assert not snap.firing
        assert mon.fired_and_cleared() == ["score_drift"]
        # scores landed on the recorder as gauges
        assert any(k.startswith(f"{SERIES_SCORES}|psi") for k in recorder.drift_scores())

    def test_scores_land_on_the_monitor_recorder_override(self):
        """A monitor constructed with recorder= routes DriftRule's score
        gauges there, like every other health family — not to the process
        default."""
        from metrics_tpu.observability import MetricRecorder

        mine = MetricRecorder("mine")
        mine.enable()
        reg = _registry()
        rule = DriftRule("d", SERIES_SCORES, threshold=0.25, window_s=5.0,
                         freeze_after=50, min_count=16)
        mon = HealthMonitor([rule], registry=reg, recorder=mine)
        rng = np.random.RandomState(6)
        t = _feed(reg, _healthy, T0, 2.0, rng=rng)
        mon.evaluate(now=t)  # freeze
        mon.evaluate(now=t)  # score
        assert any(k.endswith("|psi") for k in mine.drift_scores())
        assert not get_recorder().drift_scores()  # default untouched (disabled)

    def test_record_scores_sampling_covers_the_batch_tail(self, recorder):
        """Ceil-stride sampling: the last region of an ordered batch must
        be represented (floor stride + truncation always dropped it)."""
        reg = recorder.attach_timeseries(bucket_seconds=1.0, n_buckets=16, sketch_capacity=64)
        recorder.record_scores(np.arange(100, dtype=np.float64), max_samples=32)
        s = reg.get(SERIES_SCORES)
        assert s.count(None) <= 32
        assert s.value_max(None) >= 96  # the tail region was sampled

    def test_collecting_reference_never_fires(self):
        reg = _registry()
        rule = DriftRule("d", SERIES_SCORES, freeze_after=10_000)
        firing, value, detail = rule.evaluate(reg, now=T0)
        assert not firing and "absent" in detail
        rng = np.random.RandomState(3)
        t = _feed(reg, _shifted, T0, 1.0, rng=rng)
        firing, value, detail = rule.evaluate(reg, now=t)
        assert not firing and "collecting reference" in detail

    def test_explicit_freeze_reference(self):
        """The serving loop's phase-boundary freeze: bypasses the count
        gate so a cold-cache crawl cannot push the baseline into a fault
        window."""
        reg = _registry()
        rule = DriftRule("d", SERIES_SCORES, threshold=0.25, window_s=5.0,
                         freeze_after=10_000, min_count=16)
        assert not rule.freeze_reference(reg)  # absent series: no-op
        rng = np.random.RandomState(4)
        t = _feed(reg, _healthy, T0, 1.0, rng=rng)
        assert rule.freeze_reference(reg, now=t)
        t2 = _feed(reg, _shifted, t + 10, 6.0, rng=rng)
        firing, value, _ = rule.evaluate(reg, now=t2)
        assert firing and value > 0.25

    def test_reset_reference_rebaselines(self):
        reg = _registry()
        rule = DriftRule("d", SERIES_SCORES, threshold=0.25, window_s=5.0,
                         freeze_after=50, min_count=16)
        rng = np.random.RandomState(5)
        t = _feed(reg, _healthy, T0, 2.0, rng=rng)
        rule.evaluate(reg, now=t)  # freeze on healthy
        t2 = _feed(reg, _shifted, t + 10, 6.0, rng=rng)
        assert rule.evaluate(reg, now=t2)[0]
        rule.reset_reference()
        # re-freezes on the (shifted) present: drift is relative to "then"
        rule.evaluate(reg, now=t2)
        firing, value, _ = rule.evaluate(reg, now=t2)
        assert not firing and value < 0.25

    def test_validation(self):
        with pytest.raises(ValueError, match="stat"):
            DriftRule("d", SERIES_SCORES, stat="chi2")
        with pytest.raises(ValueError, match="window_s"):
            DriftRule("d", SERIES_SCORES, window_s=0)
        with pytest.raises(ValueError, match="freeze_after"):
            DriftRule("d", SERIES_SCORES, freeze_after=0)
        with pytest.raises(ValueError, match="n_bins"):
            DriftRule("d", SERIES_SCORES, n_bins=1)

    def test_default_rules_seventh_class(self):
        rules = default_rules()
        names = [r.name for r in rules]
        assert "score_drift" in names
        drift = next(r for r in rules if r.name == "score_drift")
        assert isinstance(drift, DriftRule)
        # absent series: the monitor evaluates clean (no scores recorded)
        mon = HealthMonitor(rules, registry=_registry())
        snap = mon.evaluate(now=T0)
        assert snap.status == "ok" and not snap.firing


# ---------------------------------------------------------------------------
# exporters + aggregate carry-through
# ---------------------------------------------------------------------------

class TestExportAndAggregate:
    def test_prometheus_family_and_summary(self, recorder):
        from metrics_tpu.observability.exporters import render_prometheus, summary

        recorder.record_drift_score(SERIES_SCORES, "psi", 0.37)
        page = render_prometheus(recorder)
        assert 'metrics_tpu_drift_score{metric="scores",stat="psi"} 0.37' in page
        text = summary(recorder)
        assert "drift scores" in text and "scores [psi]: 0.37" in text
        # the JSONL stream carries the score trajectory
        assert any(e.get("type") == "drift" for e in recorder.events())

    def test_aggregate_carry_through_and_max_merge(self, recorder):
        from metrics_tpu.observability.aggregate import counter_payload, merge_payloads
        from metrics_tpu.observability.exporters import render_prometheus

        recorder.record_drift_score(SERIES_SCORES, "psi", 0.2)
        local = counter_payload(recorder)
        other = dict(local)
        other = {**local, "process": 1, "drift_scores": {f"{SERIES_SCORES}|psi": 0.9}}
        merged = merge_payloads([local, other])
        assert merged["drift_scores"][f"{SERIES_SCORES}|psi"] == 0.9  # max wins
        page = render_prometheus(aggregate=merged)
        # payloads carry snapshot provenance (ISSUE 13), so per-rank
        # samples label host alongside process
        host = f',host="{local["host"]}"' if local.get("host") else ""
        assert f'metrics_tpu_drift_score{{metric="scores",stat="psi",process="0"{host}}} 0.2' in page
        assert f'metrics_tpu_drift_score{{metric="scores",stat="psi",process="1"{host}}} 0.9' in page

    def test_mixed_version_fleet_missing_drift_family_is_identity(self, recorder):
        """ISSUE 12 satellite: a rank on an older build (no drift/windowed
        families at all) merges as identity and still renders."""
        from metrics_tpu.observability.aggregate import counter_payload, merge_payloads
        from metrics_tpu.observability.exporters import render_prometheus

        recorder.record_drift_score(SERIES_SCORES, "js", 0.11)
        bare = {"process": 7}  # ancient build: no families at all
        local = counter_payload(recorder)
        merged = merge_payloads([bare, local])
        assert merged["drift_scores"] == {f"{SERIES_SCORES}|js": 0.11}
        page = render_prometheus(aggregate=merged)
        host = f',host="{local["host"]}"' if local.get("host") else ""
        assert f'metrics_tpu_drift_score{{metric="scores",stat="js",process="0"{host}}} 0.11' in page


# ---------------------------------------------------------------------------
# record_scores feed
# ---------------------------------------------------------------------------

class TestRecordScores:
    def test_feeds_bounded_sample_into_series(self, recorder):
        reg = recorder.attach_timeseries(bucket_seconds=1.0, n_buckets=16, sketch_capacity=64)
        recorder.record_scores(np.linspace(0, 1, 1000), max_samples=16)
        s = reg.get(SERIES_SCORES)
        assert s is not None and s.count(None) == 16

    def test_noop_when_detached(self, recorder):
        recorder.detach_timeseries()
        recorder.record_scores([0.5, 0.5])  # must not raise
