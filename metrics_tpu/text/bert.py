"""Modular BERTScore.

Behavior parity with /root/reference/torchmetrics/text/bert.py:40-212: the
class tokenizes at update time and accumulates ``input_ids``/``attention_mask``
list states for both corpora (device-synced), then delegates to the
functional pipeline at compute time.
"""
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.bert import _tokenize, bert_score

Array = jax.Array


class BERTScore(Metric):
    """Accumulating BERTScore (precision/recall/f1 per sentence pair).

    Requires either a ``model`` callable (Flax transformers model or
    ``(input_ids, attention_mask) -> [batch, seq, dim]``) plus
    ``user_tokenizer``, or a LOCAL ``model_name_or_path`` checkpoint.
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Any = None,
        user_forward_fn: Optional[Callable] = None,
        idf: bool = False,
        max_length: int = 512,
        batch_size: int = 64,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.model = model
        self.user_forward_fn = user_forward_fn
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path

        if user_tokenizer is not None:
            self.tokenizer = user_tokenizer
            self.user_tokenizer = True
        else:
            if model_name_or_path is None:
                raise ValueError(
                    "`BERTScore` needs either `user_tokenizer` (+ `model`) or a LOCAL"
                    " `model_name_or_path` checkpoint — this environment cannot download"
                    " the default model."
                )
            from transformers import AutoTokenizer, FlaxAutoModel

            self.tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
            self.user_tokenizer = False
            if self.model is None:
                # load once; _compute would otherwise re-read the checkpoint per call
                self.model = FlaxAutoModel.from_pretrained(model_name_or_path)
            if num_layers is not None and hasattr(self.model, "config") and (
                num_layers > self.model.config.num_hidden_layers
            ):
                raise ValueError(
                    f"num_layers={num_layers} is forbidden for {model_name_or_path}."
                    f" Please use num_layers <= {self.model.config.num_hidden_layers}"
                )

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _update(self, preds: List[str], target: List[str]) -> None:
        if isinstance(preds, str):
            preds = [preds]
        elif not isinstance(preds, list):
            preds = list(preds)
        if isinstance(target, str):
            target = [target]
        elif not isinstance(target, list):
            target = list(target)
        # truncation=False at update time (reference text/bert.py:205-220)
        preds_tok = _tokenize(preds, self.tokenizer, self.max_length, self.user_tokenizer, truncation=False)
        target_tok = _tokenize(target, self.tokenizer, self.max_length, self.user_tokenizer, truncation=False)
        for state, tok in (
            (self.preds_input_ids, preds_tok["input_ids"]),
            (self.preds_attention_mask, preds_tok["attention_mask"]),
            (self.target_input_ids, target_tok["input_ids"]),
            (self.target_attention_mask, target_tok["attention_mask"]),
        ):
            self._append_uniform(state, np.asarray(tok))

    def _append_uniform(self, state: List[Array], tok: np.ndarray) -> None:
        """Append keeping ALL chunks in a state the same width, so the "cat"
        list states concatenate across updates on a rank (dist sync
        pre-concatenates list states; ragged widths would crash there).
        truncation=False can exceed max_length, in which case the narrower
        chunks already stored are re-padded to the new width. NOTE:
        cross-RANK sync additionally requires all ranks to agree on the
        width — guaranteed at max_length unless truncation=False meets
        longer-than-max_length inputs on some rank only (the reference has
        the same constraint)."""
        width = max(self.max_length, tok.shape[1], *(int(c.shape[1]) for c in state))
        if tok.shape[1] < width:
            tok = np.pad(tok, ((0, 0), (0, width - tok.shape[1])))
        for i, chunk in enumerate(state):
            if chunk.shape[1] < width:
                state[i] = jnp.pad(chunk, ((0, 0), (0, width - chunk.shape[1])))
        state.append(jnp.asarray(tok))

    @staticmethod
    def _pad_cat(chunks: List[Array]) -> np.ndarray:
        """Concatenate [N_i, S_i] chunks along N (chunks may still be ragged
        when truncation=False produced sequences beyond max_length)."""
        max_len = max(int(c.shape[1]) for c in chunks)
        return np.concatenate(
            [np.pad(np.asarray(c), ((0, 0), (0, max_len - c.shape[1]))) for c in chunks]
        )

    @staticmethod
    def _trim(tok: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Trim the uniform max_length padding back to the longest attended
        sequence (the reference's _input_data_collator, bert.py:116-126)."""
        width = max(int(np.max(np.sum(tok["attention_mask"], axis=1))), 1)
        return {k: v[:, :width] for k, v in tok.items()}

    def _compute(self) -> Dict[str, Union[List[float], str]]:
        preds = self._trim({
            "input_ids": self._pad_cat(self.preds_input_ids),
            "attention_mask": self._pad_cat(self.preds_attention_mask),
        })
        target = self._trim({
            "input_ids": self._pad_cat(self.target_input_ids),
            "attention_mask": self._pad_cat(self.target_attention_mask),
        })
        return bert_score(
            preds,
            target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            all_layers=self.all_layers,
            model=self.model,
            user_forward_fn=self.user_forward_fn,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
        )
