"""Retrieval average precision.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
average_precision.py:20-58.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_average_precision(preds: Array, target: Array) -> Array:
    """Average precision of a single query's ranking.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.2, 0.3, 0.5])
        >>> target = jnp.array([True, False, True])
        >>> retrieval_average_precision(preds, target)
        Array(0.8333334, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not jnp.sum(target):
        return jnp.asarray(0.0, dtype=preds.dtype)

    target = target[jnp.argsort(-preds, axis=-1)]
    positions = jnp.arange(1, len(target) + 1, dtype=jnp.float32)[target > 0]
    return jnp.mean((jnp.arange(len(positions), dtype=jnp.float32) + 1) / positions)
