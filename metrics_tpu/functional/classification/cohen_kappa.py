"""Cohen's kappa from the confusion matrix.

Behavior parity with /root/reference/torchmetrics/functional/classification/
cohen_kappa.py:22-131.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import (
    _confusion_matrix_compute,
    _confusion_matrix_update,
)

Array = jax.Array

_cohen_kappa_update = _confusion_matrix_update


def _cohen_kappa_compute(confmat: Array, weights: Optional[str] = None) -> Array:
    confmat = _confusion_matrix_compute(confmat)
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[0]
    sum0 = jnp.sum(confmat, axis=0, keepdims=True)
    sum1 = jnp.sum(confmat, axis=1, keepdims=True)
    expected = sum1 @ sum0 / jnp.sum(sum0)

    if weights is None:
        w_mat = jnp.ones_like(confmat) - jnp.eye(n_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        w_mat = jnp.broadcast_to(jnp.arange(n_classes, dtype=confmat.dtype), (n_classes, n_classes))
        if weights == "linear":
            w_mat = jnp.abs(w_mat - w_mat.T)
        else:
            w_mat = jnp.power(w_mat - w_mat.T, 2.0)
    else:
        raise ValueError(
            f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'"
        )

    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def cohen_kappa(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[str] = None,
    threshold: float = 0.5,
) -> Array:
    """Computes Cohen's kappa (inter-annotator agreement).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> cohen_kappa(preds, target, num_classes=2)
        Array(0.5, dtype=float32)
    """
    confmat = _cohen_kappa_update(preds, target, num_classes, threshold)
    return _cohen_kappa_compute(confmat, weights)
