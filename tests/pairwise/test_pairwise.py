"""Pairwise metrics vs sklearn oracles."""
import numpy as np
import pytest
from sklearn.metrics.pairwise import (
    cosine_similarity as sk_cosine,
    euclidean_distances as sk_euclidean,
    linear_kernel as sk_linear,
    manhattan_distances as sk_manhattan,
)

import jax.numpy as jnp

from metrics_tpu.functional import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.RandomState(42)
X = _rng.rand(12, 5).astype(np.float32)
Y = _rng.rand(8, 5).astype(np.float32)


@pytest.mark.parametrize(
    "tpu_fn, sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
        (pairwise_linear_similarity, sk_linear),
        (pairwise_manhattan_distance, sk_manhattan),
    ],
)
def test_pairwise_two_inputs(tpu_fn, sk_fn):
    got = tpu_fn(jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(got), sk_fn(X, Y), atol=1e-5)


@pytest.mark.parametrize(
    "tpu_fn, sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
        (pairwise_linear_similarity, sk_linear),
        (pairwise_manhattan_distance, sk_manhattan),
    ],
)
def test_pairwise_single_input_zero_diagonal(tpu_fn, sk_fn):
    got = np.asarray(tpu_fn(jnp.asarray(X)))
    expected = sk_fn(X, X)
    np.fill_diagonal(expected, 0)
    np.testing.assert_allclose(got, expected, atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_pairwise_reduction(reduction):
    got = pairwise_euclidean_distance(jnp.asarray(X), jnp.asarray(Y), reduction=reduction)
    full = sk_euclidean(X, Y)
    expected = full.mean(-1) if reduction == "mean" else full.sum(-1)
    np.testing.assert_allclose(np.asarray(got), expected, atol=1e-4)
    with pytest.raises(ValueError):
        pairwise_euclidean_distance(jnp.asarray(X), reduction="bad")


def test_pairwise_invalid_shapes():
    with pytest.raises(ValueError):
        pairwise_cosine_similarity(jnp.ones(5))
    with pytest.raises(ValueError):
        pairwise_cosine_similarity(jnp.ones((4, 5)), jnp.ones((4, 3)))


def test_pairwise_jit():
    import jax

    got = jax.jit(pairwise_euclidean_distance)(jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(got), sk_euclidean(X, Y), atol=1e-5)
