"""Structured trace spans: nested, context-local timing regions emitted
through the :class:`MetricRecorder` event stream, plus a Chrome/Perfetto
trace-event exporter.

PR 1's recorder answers *what ran and for how long*, but its rows are flat:
an ``update`` inside a ``MetricCollection.forward`` inside a distributed
sync is three unrelated events. Spans restore the nesting — every span has
an id and a parent id maintained on a ``contextvars`` stack (so concurrent
threads and async tasks each see their own ancestry), and every OTHER event
recorded while a span is active carries that span's id, re-attaching the
flat rows to the tree.

The runtime opens spans for you: ``Metric.update/compute/forward/sync``,
``MetricCollection.update/forward/compute``, and the transport hooks
(``gather_all_arrays`` / ``sync_in_mesh`` / ``all_gather_replicated``) are
spans whenever the default recorder is enabled. User code adds its own::

    from metrics_tpu.observability import get_recorder, span
    get_recorder().enable()
    with span("eval_epoch", epoch=3):
        ...  # metric traffic nests under this span

Zero-overhead contract: entering a span while the recorder is disabled
costs one attribute check; no ids are drawn, no clocks read, nothing
recorded.

``export_perfetto(path)`` renders the span log as trace-event JSON that
``chrome://tracing`` / https://ui.perfetto.dev load directly.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional

from metrics_tpu.observability.recorder import _DEFAULT_RECORDER, _SPAN_STACK, current_span_id
from metrics_tpu.utils.prints import _process_index

__all__ = ["span", "current_span_id", "export_perfetto"]

#: process-wide monotonically increasing span ids; ``itertools.count`` is
#: atomic under the GIL, so concurrent threads never share an id
_SPAN_IDS = itertools.count(1)


class span:
    """Context manager marking one nested timing region.

    ``with span("name", **attributes):`` records a ``span`` event on exit
    carrying ``span_id`` / ``parent_id`` / ``name`` / ``dur_ms`` / ``tid``
    plus the given JSON-safe attributes. Nestable: the parent link follows
    the ``contextvars`` ancestry, so spans opened in different threads (or
    asyncio tasks) cannot interleave each other's stacks. Each instance
    marks ONE region — use a fresh ``span(...)`` per ``with`` block (an
    instance holds per-entry state, so re-entering the same object while
    it is active would corrupt the ancestry stack; nesting distinct
    instances, including same-named ones, is the supported shape).
    """

    __slots__ = ("name", "attributes", "_recorder", "_token", "_t0", "span_id", "parent_id")

    def __init__(self, name: str, recorder: Optional[Any] = None, **attributes: Any) -> None:
        self.name = name
        self.attributes = attributes
        self._recorder = recorder
        self._token = None
        self.span_id: Optional[int] = None
        self.parent_id: Optional[int] = None

    def __enter__(self) -> "span":
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        if not rec.enabled:  # disabled spans cost this ONE check
            return self
        stack = _SPAN_STACK.get()
        self.span_id = next(_SPAN_IDS)
        self.parent_id = stack[-1] if stack else None
        self._token = _SPAN_STACK.set(stack + (self.span_id,))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._token is None:
            return
        dur_s = time.perf_counter() - self._t0
        _SPAN_STACK.reset(self._token)
        self._token = None
        rec = self._recorder if self._recorder is not None else _DEFAULT_RECORDER
        event: Dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "dur_ms": round(dur_s * 1e3, 4),
            "tid": threading.get_ident(),
        }
        if self.attributes:
            event["attributes"] = self.attributes
        if exc and exc[0] is not None:
            event["error"] = getattr(exc[0], "__name__", str(exc[0]))
        rec.record_event("span", **event)


def _resolve(recorder: Optional[Any]) -> Any:
    return recorder if recorder is not None else _DEFAULT_RECORDER


def export_perfetto(path: str, recorder: Optional[Any] = None) -> Optional[str]:
    """Write the recorded span log as Chrome/Perfetto trace-event JSON.

    Every ``span`` event becomes one complete ("X") trace event with
    microsecond ``ts``/``dur``; nesting renders from ts/dur containment per
    (pid, tid) track, exactly how the contextvars stack nested them.
    Duration-carrying lifecycle events (``update``/``compute``/``forward``),
    ``sync``/``compile`` rows, and the async-pipeline transitions
    (``enqueue``/``dequeue``/``flush`` — which carry the recording thread's
    id) are included too, so the Perfetto view shows the same stream the
    JSONL export does. The recorder's tid -> thread-name map is emitted as
    ``thread_name``/``process_name`` metadata, so the async worker's rows
    land on their own LABELED track (``metrics-tpu-async-update``) instead
    of interleaving with the main thread. Rank-zero gated: returns the
    path written, or ``None`` on non-zero ranks.
    """
    if _process_index() != 0:
        return None
    rec = _resolve(recorder)
    pid = _process_index()
    all_events = rec.events()
    # spans carry the real thread id; other rows only carry the enclosing
    # span's id — resolve them onto the same track so ts/dur containment
    # (Perfetto's nesting rule is per (pid, tid)) actually nests them
    span_tid = {
        ev["span_id"]: ev.get("tid", 0) for ev in all_events if ev.get("type") == "span"
    }
    trace_events: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"metrics_tpu rank {pid} ({rec.name})"},
        }
    ]
    for tid, tname in sorted(rec.thread_names().items()):
        trace_events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": int(tid), "args": {"name": tname}}
        )
    for ev in all_events:
        etype = ev.get("type")
        dur_ms = ev.get("dur_ms")
        if etype == "span":
            name = ev.get("name", "span")
        elif etype in ("update", "compute", "forward"):
            name = f"{ev.get('metric', '?')}.{etype}"
        elif etype in ("sync", "metric_sync", "compile"):
            name = f"{etype}:{ev.get('source') or ev.get('metric') or ev.get('entry') or '?'}"
            if dur_ms is None:
                dur_ms = ev.get("compile_ms", 0.0)
        elif etype in ("enqueue", "dequeue", "flush"):
            # async-pipeline transitions: stamped with the recording
            # thread's id, so dequeues render on the worker's labeled track
            name = f"async.{etype}"
            if ev.get("batch_index") is not None:
                name = f"{name}[{ev['batch_index']}]"
        else:
            continue
        dur_ms = float(dur_ms or 0.0)
        # events carry their END time relative to recorder start ("t");
        # the trace event starts dur earlier
        end_us = float(ev.get("t", 0.0)) * 1e6
        args = {
            k: v
            for k, v in ev.items()
            if k not in ("type", "t", "dur_ms", "tid", "name") and _json_safe(v)
        }
        trace_events.append(
            {
                "name": name,
                "cat": etype,
                "ph": "X",
                "ts": round(max(end_us - dur_ms * 1e3, 0.0), 3),
                "dur": round(dur_ms * 1e3, 3),
                "pid": pid,
                "tid": int(ev.get("tid") or span_tid.get(ev.get("span_id"), 0)),
                "args": args,
            }
        )
    doc = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"recorder": rec.name},
    }
    from metrics_tpu.observability.exporters import _atomic_write

    _atomic_write(path, json.dumps(doc))
    return path


def _json_safe(value: Any) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):
        return False
