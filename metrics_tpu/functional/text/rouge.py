"""ROUGE score (parity: /root/reference/torchmetrics/functional/text/rouge.py).

Rouge-N via clipped n-gram hits, Rouge-L/Lsum via longest common subsequence
(the LCS DP is the row-vectorized kernel in helper.py, replacing the
reference's pure-Python cell loop at rouge.py:76-91).
"""
import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _lcs
from metrics_tpu.utils.imports import _NLTK_AVAILABLE

Array = jax.Array

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


def _regex_sent_tokenize(x: str) -> List[str]:
    """Offline fallback sentence splitter: break after ./!/? followed by space."""
    sentences = re.split(r"(?<=[.!?])\s+", x.strip())
    return [s for s in sentences if s]


_PUNKT_USABLE: Optional[bool] = None  # resolved once on first rougeLsum use


def _punkt_usable() -> bool:
    """Probe (once) whether nltk sentence tokenization actually works: the
    required resource is punkt_tab on nltk>=3.8.2, punkt before that, and
    either may need a network download that an air-gapped host can't do."""
    global _PUNKT_USABLE
    if _PUNKT_USABLE is None:
        import nltk

        try:
            nltk.sent_tokenize("probe. probe.")
            _PUNKT_USABLE = True
        except LookupError:
            for resource in ("punkt_tab", "punkt"):
                try:
                    nltk.download(resource, quiet=True, force=False)
                except Exception:
                    pass
            try:
                nltk.sent_tokenize("probe. probe.")
                _PUNKT_USABLE = True
            except LookupError:
                _PUNKT_USABLE = False
    return _PUNKT_USABLE


def _add_newline_to_end_of_each_sentence(x: str) -> str:
    """Sentence-split with nltk and re-join with newlines (rougeLsum prep).

    When the nltk punkt model is unavailable (offline environment, no
    downloaded corpora) falls back to a regex splitter — identical on
    ordinary prose; a deliberate divergence from the reference (which
    requires a network download at rouge.py:41-46).
    """
    if not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("ROUGE-Lsum calculation requires that `nltk` is installed. Use `pip install nltk`.")
    x = re.sub("<n>", "", x)  # remove pegasus newline char
    if _punkt_usable():
        import nltk

        return "\n".join(nltk.sent_tokenize(x))
    return "\n".join(_regex_sent_tokenize(x))


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """Precision/recall/F1 from hit (or LCS) counts (rouge.py:55-73)."""
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    fmeasure = 2 * precision * recall / (precision + recall)
    return dict(precision=precision, recall=recall, fmeasure=fmeasure)


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase/strip non-alphanumerics, tokenize, optionally stem (rouge.py:96-133)."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    """Rouge-N precision/recall/F1 via clipped n-gram counts (rouge.py:136-161)."""

    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        ngrams: Counter = Counter()
        for ngram in (tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)):
            ngrams[ngram] += 1
        return ngrams

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    hits = sum(min(pred_ngrams[w], target_ngrams[w]) for w in set(pred_ngrams))
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    """Rouge-L precision/recall/F1 via LCS length (rouge.py:164-178)."""
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    return _compute_metrics(_lcs(pred, target), pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sentence rouge scores with avg/best multi-reference accumulation
    (rouge.py:181-296)."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}

    for pred_raw, target_raw in zip(preds, target):
        result_inner: Dict[Union[int, str], Dict[str, float]] = {key: {} for key in rouge_keys_values}
        result_avg: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}
        list_results = []
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        if "Lsum" in rouge_keys_values:
            pred_lsum = _normalize_and_tokenize_text(
                _add_newline_to_end_of_each_sentence(pred_raw), stemmer, normalizer, tokenizer
            )

        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            if "Lsum" in rouge_keys_values:
                target_lsum = _normalize_and_tokenize_text(
                    _add_newline_to_end_of_each_sentence(target_raw_inner), stemmer, normalizer, tokenizer
                )

            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    score = _rouge_n_score(pred, tgt, rouge_key)
                else:
                    score = _rouge_l_score(
                        pred if rouge_key != "Lsum" else pred_lsum,
                        tgt if rouge_key != "Lsum" else target_lsum,
                    )
                result_inner[rouge_key] = score
                result_avg[rouge_key].append(score)
            list_results.append(result_inner.copy())

        if accumulate == "best":
            key_curr = rouge_keys_values[0]
            all_fmeasure = [v[key_curr]["fmeasure"] for v in list_results]
            highest_idx = int(np.argmax(all_fmeasure))
            for rouge_key in rouge_keys_values:
                results[rouge_key].append(list_results[highest_idx][rouge_key])
        elif accumulate == "avg":
            for rouge_key in rouge_keys_values:
                metrics = result_avg[rouge_key]
                results[rouge_key].append(
                    {
                        score_type: float(np.mean([m[score_type] for m in metrics]))
                        for score_type in ("fmeasure", "precision", "recall")
                    }
                )
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[float]]) -> Dict[str, Array]:
    """Mean over per-sentence scores (rouge.py:296-310)."""
    results: Dict[str, Array] = {}
    if sentence_results == {}:
        return results
    for rouge_key, scores in sentence_results.items():
        results[rouge_key] = jnp.asarray(np.mean(scores), jnp.float32)
    return results


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """Calculate ROUGE score for automatic summarization.

    Example:
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> from pprint import pprint
        >>> pprint(rouge_score(preds, target, rouge_keys=("rouge1",)))  # doctest: +ELLIPSIS
        {'rouge1_fmeasure': Array(0.75, dtype=float32),
         'rouge1_precision': Array(0.75, dtype=float32),
         'rouge1_recall': Array(0.75, dtype=float32)}
    """
    if use_stemmer:
        if not _NLTK_AVAILABLE:
            raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
        import nltk

    stemmer = nltk.stem.porter.PorterStemmer() if use_stemmer else None

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS.keys():
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )

    output: Dict[str, List[float]] = {
        f"rouge{rouge_key}_{tp}": [] for rouge_key in rouge_keys_values for tp in ["fmeasure", "precision", "recall"]
    }
    for rouge_key, metrics in sentence_results.items():
        for metric in metrics:
            for tp, value in metric.items():
                output[f"rouge{rouge_key}_{tp}"].append(value)
    return _rouge_score_compute(output)
