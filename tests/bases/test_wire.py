"""Wire-format tests (ISSUE 13 tentpole): dtype-stable bit-exact leaf
round-trips, the schema-versioned provenance header, the canonical
states/states-key shapes, telemetry payload normalization, and the
WireError boundary (bad magic / future schema / corrupt leaves) the
collector's fold_error accounting relies on."""
import json

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanSquaredError, MetricCollection
from metrics_tpu.aggregation import SumMetric
from metrics_tpu.classification import Accuracy
from metrics_tpu.observability.wire import (
    WIRE_MAGIC,
    WIRE_SCHEMA_VERSION,
    WireError,
    decode_snapshot,
    encode_snapshot,
    manifest_fingerprint,
    snapshot_states,
    states_key,
)


def _round_trip(states):
    blob = encode_snapshot(publisher="p", seq=0, t=100.0, states=states)
    return decode_snapshot(blob).states


class TestLeafCodec:
    @pytest.mark.parametrize(
        "dtype",
        [np.int32, np.int64, np.float32, np.float64, np.uint8, np.bool_],
    )
    def test_array_round_trip_bit_exact(self, dtype):
        rng = np.random.RandomState(0)
        arr = (rng.rand(3, 5) * 100).astype(dtype)
        out = _round_trip({"m": {"x": arr}})["m"]["x"]
        assert out.dtype == arr.dtype
        assert out.shape == arr.shape
        assert np.array_equal(out, arr)

    def test_int64_values_survive_json(self):
        # JSON numbers would round 2**53+1; raw-buffer leaves must not
        big = np.asarray([2**53 + 1, -(2**62)], np.int64)
        out = _round_trip({"m": {"x": big}})["m"]["x"]
        assert np.array_equal(out, big)

    def test_float32_bits_survive(self):
        vals = np.asarray([0.1, 1e-38, 3.4e38, np.inf, -np.inf], np.float32)
        out = _round_trip({"m": {"x": vals}})["m"]["x"]
        assert out.tobytes() == vals.tobytes()

    def test_jax_array_leaves_decode_as_numpy(self):
        out = _round_trip({"m": {"x": jnp.asarray([1, 2, 3], jnp.int32)}})["m"]["x"]
        assert isinstance(out, np.ndarray)
        assert np.array_equal(out, np.asarray([1, 2, 3], np.int32))

    def test_python_scalars_and_list_states(self):
        states = {"m": {"n": 7, "f": 0.5, "cat": [np.ones((2,), np.float32), np.zeros((3,), np.float32)]}}
        out = _round_trip(states)["m"]
        assert out["n"] == 7 and out["f"] == 0.5
        assert len(out["cat"]) == 2
        assert np.array_equal(out["cat"][0], np.ones((2,), np.float32))
        assert np.array_equal(out["cat"][1], np.zeros((3,), np.float32))

    def test_zero_dim_array(self):
        out = _round_trip({"m": {"x": np.asarray(3.5, np.float32)}})["m"]["x"]
        assert out.shape == () and float(out) == 3.5


class TestHeader:
    def test_provenance_fields(self):
        blob = encode_snapshot(
            publisher="pub0", seq=17, t=123.5, host="h0", process=3, tier="rack"
        )
        snap = decode_snapshot(blob)
        assert snap.publisher == "pub0"
        assert snap.seq == 17
        assert snap.t == 123.5
        assert snap.host == "h0"
        assert snap.process == 3
        assert snap.tier == "rack"
        assert snap.schema == WIRE_SCHEMA_VERSION
        assert snap.key == ("pub0", 17)

    def test_manifest_hash_rides_the_header(self):
        fp = manifest_fingerprint()
        snap = decode_snapshot(encode_snapshot(publisher="p", seq=0, t=1.0))
        assert snap.manifest_hash == fp

    def test_manifest_fingerprint_stable_and_short(self):
        fp = manifest_fingerprint()
        assert fp == manifest_fingerprint()
        assert fp == "" or (len(fp) == 16 and int(fp, 16) >= 0)

    def test_mode_validated(self):
        with pytest.raises(ValueError, match="mode"):
            encode_snapshot(publisher="p", seq=0, mode="increment")
        with pytest.raises(ValueError, match="publisher"):
            encode_snapshot(publisher="", seq=0)
        with pytest.raises(ValueError, match="seq"):
            encode_snapshot(publisher="p", seq=-1)

    def test_telemetry_normalizes_to_list(self):
        one = {"process": 0, "call_counts": {}}
        snap = decode_snapshot(encode_snapshot(publisher="p", seq=0, telemetry=one))
        assert snap.telemetry == [one]
        snap = decode_snapshot(encode_snapshot(publisher="p", seq=0, telemetry=[one, one]))
        assert len(snap.telemetry) == 2


class TestWireErrorBoundary:
    def test_garbage_bytes(self):
        with pytest.raises(WireError):
            decode_snapshot(b"not json at all")

    def test_truncated_json(self):
        blob = encode_snapshot(publisher="p", seq=0)
        with pytest.raises(WireError):
            decode_snapshot(blob[: len(blob) // 2])

    def test_foreign_magic(self):
        with pytest.raises(WireError, match="magic"):
            decode_snapshot(json.dumps({"magic": "something-else", "schema": 1}).encode())

    def test_future_schema_refused(self):
        doc = json.loads(encode_snapshot(publisher="p", seq=0).decode())
        doc["schema"] = WIRE_SCHEMA_VERSION + 1
        with pytest.raises(WireError, match="newer"):
            decode_snapshot(json.dumps(doc).encode())

    def test_corrupt_array_leaf(self):
        doc = json.loads(
            encode_snapshot(
                publisher="p", seq=0, states={"m": {"x": np.ones((2,), np.float32)}}
            ).decode()
        )
        doc["states"]["m"]["x"]["__arr__"]["data"] = "!!!not-base64!!!"
        with pytest.raises(WireError):
            decode_snapshot(json.dumps(doc).encode())

    def test_incomplete_header(self):
        with pytest.raises(WireError, match="incomplete"):
            decode_snapshot(
                json.dumps({"magic": WIRE_MAGIC, "schema": 1, "publisher": "p"}).encode()
            )


class TestStatesHelpers:
    def test_snapshot_states_metric(self):
        m = SumMetric()
        m.update(jnp.asarray([2.0, 3.0]))
        states = snapshot_states(m)
        assert list(states) == ["SumMetric"]
        assert float(np.asarray(states["SumMetric"]["value"])) == 5.0

    def test_snapshot_states_collection(self):
        col = MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})
        col.update(jnp.asarray([1, 0]), jnp.asarray([1, 1]))
        states = snapshot_states(col)
        assert set(states) == {"acc", "mse"}
        key = states_key(col)
        assert key["acc"]["class"].endswith("Accuracy")
        assert sorted(key["acc"]["states"]) == sorted(states["acc"])

    def test_states_key_detects_layout_skew(self):
        # scalar-state config skew is structurally invisible (documented:
        # the manifest fingerprint + deployment discipline own it) ...
        a = states_key(MetricCollection({"acc": Accuracy(num_classes=2)}))
        b = states_key(MetricCollection({"acc": Accuracy(num_classes=3)}))
        assert a == b
        # ... but a different metric class, or a config that changes a
        # state's SHAPE, changes the key — the skew that would otherwise
        # poison a fold with a broadcast error is refused at ingest
        c = states_key(MetricCollection({"acc": SumMetric()}))
        assert a != c
        from metrics_tpu.classification import ConfusionMatrix

        d2 = states_key(MetricCollection({"cm": ConfusionMatrix(num_classes=2)}))
        d3 = states_key(MetricCollection({"cm": ConfusionMatrix(num_classes=3)}))
        assert d2 != d3

    def test_leaf_key_scalar_normalization(self):
        # the eager counter fast path leaves a Python int where another
        # publisher holds an int32 array — same key, never layout skew
        from metrics_tpu.observability.wire import _leaf_key

        assert _leaf_key(7) == _leaf_key(np.asarray(7, np.int32)) == "int"
        assert _leaf_key(0.5) == _leaf_key(np.asarray(0.5, np.float32)) == "float"
        assert _leaf_key([]) == "list"
        assert _leaf_key(np.zeros((3, 2), np.float32)) == "<f4[3, 2]"

    def test_collection_states_round_trip_bit_exact(self):
        col = MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})
        col.update(jnp.asarray([1, 0, 1]), jnp.asarray([1, 1, 0]))
        states = snapshot_states(col)
        blob = encode_snapshot(
            publisher="p", seq=0, states=states, states_template=col, telemetry=None
        )
        snap = decode_snapshot(blob)
        for mname, tree in states.items():
            for sname, leaf in tree.items():
                got = snap.states[mname][sname]
                want = np.asarray(leaf)
                assert np.array_equal(np.asarray(got), want), (mname, sname)
                assert np.asarray(got).dtype == want.dtype
        assert snap.states_key == states_key(col)
