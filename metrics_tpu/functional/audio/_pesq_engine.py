"""In-repo ITU-T P.862 (PESQ) engine — host-side numpy DSP.

The reference delegates PESQ to the external C ``pesq`` package
(/root/reference/torchmetrics/functional/audio/pesq.py:1-50,
/root/reference/torchmetrics/audio/pesq.py:25). This module implements the
P.862 pipeline in-repo so the metric computes without any external scorer:

1.  **Level alignment** — both signals are scaled so their 350–3250 Hz
    band-filtered power equals the P.862 target level (1e7 in the 16-bit
    internal domain).
2.  **Input filtering** — narrow-band mode applies the standard IRS receive
    characteristic (piecewise log-frequency gain curve, applied in the FFT
    domain); wide-band mode applies the P.862.2 100 Hz high-pass only.
3.  **Time alignment** — crude delay from the cross-correlation of 4 ms
    log-energy envelopes, refined per detected utterance by a windowed
    full-band cross-correlation (handles constant and piecewise-constant
    delay; sample-level jitter within an utterance is not re-split).
4.  **Perceptual model** — Hann-windowed 32 ms frames with 50 % overlap,
    power spectra binned into Bark bands, partial frequency compensation of
    the reference and short-term gain compensation of the degraded signal,
    Zwicker-law loudness mapping above a frequency-dependent hearing
    threshold.
5.  **Disturbance aggregation** — per-frame symmetric (L2 over bands) and
    asymmetric (L1 over bands, asymmetry factor with the P.862 3/12 clamps)
    disturbances, deadzone of 0.25·min(loudness), L6-within / L2-across
    320 ms chunks, silent-frame down-weighting, raw score
    ``4.5 − 0.1·D − 0.0309·DA`` and the P.862.1 (NB) / P.862.2 (WB)
    MOS-LQO mappings.

Parity note: the algorithmic structure, constants, and mappings above follow
the published P.862 family of recommendations. The Bark band layout and the
absolute hearing threshold are DERIVED from the published psychoacoustic
formulas (Zwicker band-rate transform, Terhardt threshold) rather than
transcribed from the ITU reference tables, so scores track the official
implementation closely but are not guaranteed bit-exact; the gated test in
``tests/audio/test_pesq_engine.py`` asserts agreement against the ``pesq``
binding wherever that package is installed.
"""
from typing import Tuple

import numpy as np

_EPS = 1e-12

# P.862 internal domain: inputs in [-1, 1] are scaled to 16-bit, then level-
# aligned so the band-filtered power hits TARGET_POWER (≈ −20 dBFS RMS),
# which the model equates with a 79 dB SPL listening level.
_TARGET_POWER = 1e7
_LISTENING_LEVEL_DB = 79.0

# standard IRS receive characteristic (frequency Hz -> gain dB), applied in
# narrow-band mode to both signals; piecewise-linear in log-frequency
_IRS_FREQ_HZ = np.array(
    [0.0, 50.0, 100.0, 125.0, 160.0, 200.0, 250.0, 300.0, 350.0, 400.0, 500.0,
     600.0, 700.0, 800.0, 1000.0, 1300.0, 1600.0, 2000.0, 2500.0, 3000.0,
     3250.0, 3500.0, 4000.0, 5000.0, 6300.0, 8000.0]
)
_IRS_GAIN_DB = np.array(
    [-200.0, -40.0, -20.0, -12.0, -6.0, 0.0, 4.0, 6.0, 8.0, 10.0, 11.0,
     12.0, 12.0, 12.0, 12.0, 12.0, 12.0, 12.0, 12.0, 12.0,
     12.0, 4.0, -200.0, -200.0, -200.0, -200.0]
)


def _bark(f_hz: np.ndarray) -> np.ndarray:
    """Zwicker critical-band rate transform (Hz -> Bark)."""
    f = np.asarray(f_hz, np.float64)
    return 13.0 * np.arctan(0.00076 * f) + 3.5 * np.arctan((f / 7500.0) ** 2)


def _hearing_threshold_db(f_hz: np.ndarray) -> np.ndarray:
    """Terhardt absolute threshold of hearing (dB SPL)."""
    f_khz = np.maximum(np.asarray(f_hz, np.float64), 20.0) / 1000.0
    return (
        3.64 * f_khz ** -0.8
        - 6.5 * np.exp(-0.6 * (f_khz - 3.3) ** 2)
        + 1e-3 * f_khz ** 4
    )


def _frame_params(fs: int) -> Tuple[int, int, int]:
    """(frame length, hop, number of Bark bands) — 32 ms Hann frames with
    50% overlap (256/128 samples at 8 kHz, 512/256 at 16 kHz), the P.862
    frame grid; 20-frame disturbance chunks then span 320 ms."""
    if fs == 8000:
        return 256, 128, 42
    return 512, 256, 49


def _band_edges(fs: int, n_fft: int, n_bands: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """FFT-bin -> Bark-band layout: (bin band index, band centre Hz, band width Bark).

    Bands are uniform on the Bark axis between 100 Hz and the model bandwidth
    (4 kHz narrow-band domain, 8 kHz wide-band domain) — the formula-derived
    counterpart of the ITU band tables (42/49 bands, see module docstring).
    """
    f_max = min(fs / 2.0, 8000.0) if n_bands == 49 else min(fs / 2.0, 4000.0)
    z_lo, z_hi = _bark(100.0), _bark(f_max)
    edges_z = np.linspace(z_lo, z_hi, n_bands + 1)
    freqs = np.fft.rfftfreq(n_fft, 1.0 / fs)
    z = _bark(freqs)
    band_of_bin = np.searchsorted(edges_z, z, side="right") - 1
    band_of_bin[(z < z_lo) | (z >= z_hi)] = -1
    centre_z = 0.5 * (edges_z[:-1] + edges_z[1:])
    # invert the Bark transform numerically for the band centre frequencies
    grid_f = np.linspace(20.0, fs / 2.0, 4096)
    centre_hz = np.interp(centre_z, _bark(grid_f), grid_f)
    width_z = np.diff(edges_z)
    return band_of_bin, centre_hz, width_z


def _stft_power(x: np.ndarray, n_fft: int, hop: int) -> np.ndarray:
    """[frames, bins] Hann-windowed power spectra."""
    n_frames = max((len(x) - n_fft) // hop + 1, 0)
    if n_frames == 0:
        return np.zeros((0, n_fft // 2 + 1))
    idx = np.arange(n_fft)[None, :] + hop * np.arange(n_frames)[:, None]
    window = np.hanning(n_fft)
    spec = np.fft.rfft(x[idx] * window, axis=1)
    # normalize so a full-scale tone's band power matches its time power
    return (np.abs(spec) ** 2) / (np.sum(window ** 2) / 2.0) / (n_fft / 2.0)


def _band_powers(power_spec: np.ndarray, band_of_bin: np.ndarray, n_bands: int) -> np.ndarray:
    """[frames, bands] mean bin power per Bark band."""
    out = np.zeros((power_spec.shape[0], n_bands))
    counts = np.zeros(n_bands)
    for b in range(n_bands):
        sel = band_of_bin == b
        counts[b] = max(int(sel.sum()), 1)
        out[:, b] = power_spec[:, sel].sum(axis=1)
    return out / counts


def _fft_filter(x: np.ndarray, fs: int, freqs_hz: np.ndarray, gains_db: np.ndarray) -> np.ndarray:
    """Zero-phase FFT-domain filter with a piecewise response (log-f interp)."""
    n = len(x)
    spec = np.fft.rfft(x)
    f = np.fft.rfftfreq(n, 1.0 / fs)
    log_f = np.log10(np.maximum(f, 1.0))
    gain_db = np.interp(log_f, np.log10(np.maximum(freqs_hz, 1.0)), gains_db)
    spec *= 10.0 ** (gain_db / 20.0)
    return np.fft.irfft(spec, n=n)


def _bandpass_power(x: np.ndarray, fs: int, lo: float = 350.0, hi: float = 3250.0) -> float:
    spec = np.fft.rfft(x)
    f = np.fft.rfftfreq(len(x), 1.0 / fs)
    band = (f >= lo) & (f <= hi)
    return float(np.sum(np.abs(spec[band]) ** 2) / (len(x) ** 2) * 2.0)


def _level_align(x: np.ndarray, fs: int) -> np.ndarray:
    power = _bandpass_power(x, fs)
    return x * np.sqrt(_TARGET_POWER / max(power, _EPS))


# ---------------------------------------------------------------------------
# time alignment
# ---------------------------------------------------------------------------


def _log_envelope(x: np.ndarray, sub: int) -> np.ndarray:
    n = len(x) // sub
    frames = x[: n * sub].reshape(n, sub)
    return np.log10(np.maximum(np.sum(frames ** 2, axis=1), 1.0))


def _crude_delay(ref: np.ndarray, deg: np.ndarray, fs: int) -> int:
    """Whole-file delay estimate (samples) from 4 ms log-energy envelopes."""
    sub = fs // 250  # 4 ms subframes
    er = _log_envelope(ref, sub)
    ed = _log_envelope(deg, sub)
    er = er - er.mean()
    ed = ed - ed.mean()
    corr = np.correlate(ed, er, mode="full")
    return (int(np.argmax(np.abs(corr))) - (len(er) - 1)) * sub


def _utterances(ref: np.ndarray, fs: int) -> list:
    """Active (start, end) sample ranges: VAD on the 4 ms envelope with
    200 ms gap joining and a 300 ms minimum utterance length."""
    sub = fs // 250
    env = _log_envelope(ref, sub)
    threshold = env.max() - 3.0  # 30 dB below peak energy
    active = env > threshold
    join = int(0.2 * 250)  # 200 ms in subframes
    min_len = int(0.3 * 250)
    spans, start = [], None
    gap = 0
    for i, a in enumerate(active):
        if a:
            if start is None:
                start = i
            gap = 0
        elif start is not None:
            gap += 1
            if gap > join:
                spans.append((start, i - gap + 1))
                start, gap = None, 0
    if start is not None:
        spans.append((start, len(active)))
    spans = [(s * sub, e * sub) for s, e in spans if e - s >= min_len]
    return spans or [(0, len(ref))]


def _fine_delay(ref_seg: np.ndarray, deg: np.ndarray, seg_start: int, crude: int, fs: int) -> int:
    """Refine the delay for one utterance: windowed cross-correlation of the
    raw waveforms around the crude estimate (±25 ms)."""
    radius = fs // 40
    lo = seg_start + crude - radius
    hi = seg_start + crude + len(ref_seg) + radius
    window = _shifted(deg, 0, lo, hi)
    corr = np.correlate(window, ref_seg, mode="valid")
    return crude - radius + int(np.argmax(np.abs(corr)))


def _shifted(deg: np.ndarray, delay: int, start: int, end: int) -> np.ndarray:
    """``deg[start+delay : end+delay]`` zero-padded where outside the file.

    Both slice bounds are clamped into ``[0, len(deg)]`` — a negative stop
    must not re-index from the file end — so the result always has exactly
    ``end - start`` samples even when the window lies entirely outside.
    """
    n = end - start
    src_lo, src_hi = start + delay, end + delay
    lo = min(max(src_lo, 0), len(deg))
    hi = min(max(src_hi, lo), len(deg))
    core = deg[lo:hi]
    pad_lo = min(max(0, -src_lo), n)
    return np.pad(core, (pad_lo, n - pad_lo - len(core)))


def _align(ref: np.ndarray, deg: np.ndarray, fs: int) -> np.ndarray:
    """Return the degraded signal re-timed onto the reference's clock.

    Crude whole-file delay everywhere as the baseline (so inter-utterance
    regions stay aligned rather than zero-filled), refined per detected
    utterance.
    """
    crude = _crude_delay(ref, deg, fs)
    aligned = _shifted(deg, crude, 0, len(ref))
    for start, end in _utterances(ref, fs):
        delay = _fine_delay(ref[start:end], deg, start, crude, fs)
        aligned[start:end] = _shifted(deg, delay, start, end)
    return aligned


# ---------------------------------------------------------------------------
# perceptual model
# ---------------------------------------------------------------------------


def _loudness(band_power: np.ndarray, threshold: np.ndarray) -> np.ndarray:
    """Zwicker-law specific loudness per Bark band (P.862 §10.2.2.5 form)."""
    gamma = 0.23
    ratio = band_power / threshold
    loud = (threshold / 0.5) ** gamma * ((0.5 + 0.5 * ratio) ** gamma - 1.0)
    return np.where(band_power > threshold, loud, 0.0)


def _raw_pesq(ref: np.ndarray, deg: np.ndarray, fs: int, mode: str) -> float:
    n_fft, hop, n_bands = _frame_params(fs)
    band_of_bin, centre_hz, width_z = _band_edges(fs, n_fft, n_bands)

    # hearing threshold in internal power units: TARGET_POWER <-> 79 dB SPL
    thr_db = _hearing_threshold_db(centre_hz)
    threshold = _TARGET_POWER * 10.0 ** ((thr_db - _LISTENING_LEVEL_DB) / 10.0)

    ref_bp = _band_powers(_stft_power(ref, n_fft, hop), band_of_bin, n_bands)
    deg_bp = _band_powers(_stft_power(deg, n_fft, hop), band_of_bin, n_bands)
    n_frames = min(len(ref_bp), len(deg_bp))
    if n_frames == 0:
        raise ValueError(f"Signals too short for PESQ: need at least {n_fft} samples, got {len(ref)}")
    ref_bp, deg_bp = ref_bp[:n_frames], deg_bp[:n_frames]

    # partial frequency compensation: move the REFERENCE through the system's
    # linear response, estimated from speech-active frames, clipped to ±20 dB
    active = ref_bp.sum(axis=1) > 1e4
    if not active.any():
        active = np.ones(n_frames, bool)
    band_ratio = (deg_bp[active].mean(axis=0) + 1e3) / (ref_bp[active].mean(axis=0) + 1e3)
    ref_eq = ref_bp * np.clip(band_ratio, 0.01, 100.0)

    # short-term gain compensation of the degraded signal (smoothed frame
    # audible-power ratio, clipped to [3e-4, 5])
    aud_ref = np.sum(np.maximum(ref_eq - threshold, 0.0), axis=1)
    aud_deg = np.sum(np.maximum(deg_bp - threshold, 0.0), axis=1)
    gain = (aud_ref + 5e3) / (aud_deg + 5e3)
    smoothed = np.empty_like(gain)
    prev = 1.0
    for i, g in enumerate(gain):  # first-order smoothing, P.862 β = 0.8
        prev = 0.8 * prev + 0.2 * g
        smoothed[i] = prev
    deg_eq = deg_bp * np.clip(smoothed, 3e-4, 5.0)[:, None]

    loud_ref = _loudness(ref_eq, threshold)
    loud_deg = _loudness(deg_eq, threshold)

    # disturbance with 0.25·min deadzone
    diff = loud_deg - loud_ref
    dead = 0.25 * np.minimum(loud_deg, loud_ref)
    disturbance = np.sign(diff) * np.maximum(np.abs(diff) - dead, 0.0)

    # asymmetry factor: additive distortions count, removals mostly don't
    asym = ((deg_eq + 50.0) / (ref_eq + 50.0)) ** 1.2
    asym = np.where(asym < 3.0, 0.0, np.minimum(asym, 12.0))

    w = width_z / width_z.sum()
    frame_d = np.sqrt(np.sum(w * disturbance ** 2, axis=1))
    frame_da = np.sum(w * np.abs(disturbance) * asym, axis=1)

    # silent frames carry less weight (audible-power based, exponent 0.04)
    weight = ((aud_ref + 1e5) / _TARGET_POWER) ** 0.04
    frame_d = np.minimum(frame_d / weight, 45.0)
    frame_da = np.minimum(frame_da / weight, 45.0)

    def _lpq(values: np.ndarray, p: float, chunk: int = 20) -> float:
        """L_p within 320 ms chunks, L2 across chunks (P.862 (p, 2) norm)."""
        n_chunks = int(np.ceil(len(values) / chunk))
        chunks = np.zeros(n_chunks)
        for c in range(n_chunks):
            part = values[c * chunk: (c + 1) * chunk]
            chunks[c] = np.mean(part ** p) ** (1.0 / p)
        return float(np.sqrt(np.mean(chunks ** 2)))

    d_sym = _lpq(frame_d, 6.0)
    d_asym = _lpq(frame_da, 1.0)
    return 4.5 - 0.1 * d_sym - 0.0309 * d_asym


def _mos_lqo(raw: float, mode: str) -> float:
    if mode == "wb":  # P.862.2 mapping
        return 0.999 + 4.0 / (1.0 + np.exp(-1.3669 * raw + 3.8224))
    # P.862.1 narrow-band mapping
    return 0.999 + 4.0 / (1.0 + np.exp(-1.4945 * raw + 4.6607))


def pesq(ref: np.ndarray, deg: np.ndarray, fs: int, mode: str) -> float:
    """ITU-T P.862 PESQ MOS-LQO of ``deg`` against clean ``ref``.

    Args:
        ref: clean reference utterance, 1-D float array (any consistent scale).
        deg: degraded utterance, same sampling rate.
        fs: 8000 or 16000.
        mode: ``"nb"`` (IRS-filtered narrow-band, P.862.1 mapping) or
            ``"wb"`` (100 Hz high-pass, P.862.2 mapping; fs must be 16000).
    """
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("nb", "wb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    if mode == "wb" and fs == 8000:
        raise ValueError("Wide-band PESQ ('wb') requires fs=16000")
    ref = np.asarray(ref, np.float64).reshape(-1)
    deg = np.asarray(deg, np.float64).reshape(-1)
    n_fft = _frame_params(fs)[0]
    if len(ref) < 2 * n_fft or len(deg) < 2 * n_fft:
        raise ValueError(
            f"Signals too short for PESQ at fs={fs}: need at least {2 * n_fft} samples"
        )

    # 16-bit internal domain + level alignment
    ref = _level_align(ref * 32768.0, fs)
    deg = _level_align(deg * 32768.0, fs)

    # input filtering
    if mode == "nb":
        ref = _fft_filter(ref, fs, _IRS_FREQ_HZ, _IRS_GAIN_DB)
        deg = _fft_filter(deg, fs, _IRS_FREQ_HZ, _IRS_GAIN_DB)
    else:
        hp_f = np.array([0.0, 50.0, 100.0, 150.0, fs / 2.0])
        hp_g = np.array([-200.0, -24.0, -3.0, 0.0, 0.0])
        ref = _fft_filter(ref, fs, hp_f, hp_g)
        deg = _fft_filter(deg, fs, hp_f, hp_g)

    deg = _align(ref, deg, fs)
    raw = _raw_pesq(ref, deg, fs, mode)
    return float(_mos_lqo(raw, mode))
