"""tracelint reporters: human text and machine JSON.

The JSON schema is stable (version-tagged) so CI annotators and editors can
consume it:

```json
{
  "version": 1,
  "tool": "tracelint",
  "violations": [
    {"rule": "TL-TRACE", "path": "a.py", "line": 3, "col": 4,
     "message": "...", "snippet": "...", "baselined": false}
  ],
  "summary": {"files": 10, "new": 1, "baselined": 0, "suppressed": 0,
              "rules": ["TL-COLLECTIVE", "..."],
              "by_rule": {"TL-TRACE": 1}}
}
```

``by_rule`` counts NEW violations per rule id (omitting zero-count rules),
so CI annotators can tell WHICH invariant regressed without walking the
violation list.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import List, Sequence

from .engine import Violation

JSON_SCHEMA_VERSION = 1


def render_text(
    new: Sequence[Violation],
    baselined: Sequence[Violation] = (),
    suppressed_count: int = 0,
    n_files: int = 0,
    stale_count: int = 0,
) -> str:
    """Human report: new violations with fix hints, then a summary line."""
    out: List[str] = []
    if new:
        out.append("tracelint: NEW violations (fix, suppress with a justified")
        out.append("`# tracelint: disable=RULE-ID` pragma, or re-baseline):")
        for v in new:
            out.append(f"  {v.render()}")
            if v.snippet:
                out.append(f"      {v.snippet}")
    summary = (
        f"tracelint: {n_files} files, {len(new)} new, {len(baselined)} baselined,"
        f" {suppressed_count} suppressed"
    )
    if new:
        by_rule = Counter(v.rule for v in new)
        summary += " (" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items())) + ")"
    if stale_count:
        summary += f", {stale_count} stale baseline entr{'y' if stale_count == 1 else 'ies'} (run --baseline-update)"
    out.append(summary)
    return "\n".join(out) + "\n"


def render_json(
    new: Sequence[Violation],
    baselined: Sequence[Violation] = (),
    suppressed_count: int = 0,
    n_files: int = 0,
    rules: Sequence[str] = (),
    stale_count: int = 0,
) -> str:
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "tracelint",
        "violations": [
            {**v.to_dict(), "baselined": False} for v in new
        ] + [
            {**v.to_dict(), "baselined": True} for v in baselined
        ],
        "summary": {
            "files": n_files,
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": suppressed_count,
            "stale_baseline_entries": stale_count,
            "rules": sorted(rules),
            "by_rule": dict(sorted(Counter(v.rule for v in new).items())),
        },
    }
    return json.dumps(payload, indent=2) + "\n"
