"""In-repo ITU-T P.862 PESQ engine tests.

No exact oracle ships in this environment (the ``pesq`` C binding is not
installed), so the engine is pinned the STOI way
(tests/audio/test_stoi_pesq.py): published fixed points of the algorithm
(identity MOS-LQO ceilings under the P.862.1/P.862.2 mappings), behavioral
invariants the spec mandates (SNR monotonicity, level/delay invariance from
the alignment stages, score range), batched/class wiring, and a gated
bit-parity sweep against the ``pesq`` binding wherever it is installed.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.audio import PerceptualEvaluationSpeechQuality
from metrics_tpu.functional.audio import perceptual_evaluation_speech_quality
from metrics_tpu.functional.audio._pesq_engine import pesq as engine_pesq

# raw score 4.5 through the P.862.1 / P.862.2 mappings — the exact ceilings
# the official implementation reports for identical signals
_NB_CEILING = 0.999 + 4.0 / (1.0 + np.exp(-1.4945 * 4.5 + 4.6607))  # 4.5488...
_WB_CEILING = 0.999 + 4.0 / (1.0 + np.exp(-1.3669 * 4.5 + 3.8224))  # 4.6436...


def _speechlike(rng, n, fs):
    t = np.arange(n) / fs
    envelope = np.clip(np.sin(2 * np.pi * 2.5 * t), 0, None)
    carrier = sum(np.sin(2 * np.pi * f0 * t + rng.uniform(0, 6)) for f0 in (220, 450, 900, 1800))
    return ((envelope * carrier + 0.01 * rng.standard_normal(n)) * 0.1).astype(np.float64)


@pytest.mark.parametrize("fs,mode", [(8000, "nb"), (16000, "nb"), (16000, "wb")])
def test_identity_hits_mapping_ceiling(fs, mode):
    clean = _speechlike(np.random.default_rng(0), 3 * fs, fs)
    ceiling = _WB_CEILING if mode == "wb" else _NB_CEILING
    assert engine_pesq(clean, clean, fs, mode) == pytest.approx(ceiling, abs=1e-3)


@pytest.mark.parametrize("fs,mode", [(8000, "nb"), (16000, "nb"), (16000, "wb")])
def test_monotone_in_snr(fs, mode):
    rng = np.random.default_rng(1)
    clean = _speechlike(rng, 3 * fs, fs)
    noise = rng.standard_normal(len(clean)) * np.std(clean)
    scores = [engine_pesq(clean, clean + noise * 10 ** (-snr / 20), fs, mode) for snr in (30, 20, 10, 0)]
    assert scores[0] > scores[1] > scores[2] > scores[3]
    assert all(1.0 <= s <= _WB_CEILING + 1e-6 for s in scores)


@pytest.mark.parametrize("fs,mode", [(8000, "nb"), (16000, "wb")])
def test_level_and_delay_invariance(fs, mode):
    """Level alignment and time alignment must absorb pure gain / pure delay."""
    rng = np.random.default_rng(2)
    clean = _speechlike(rng, 3 * fs, fs)
    noise = rng.standard_normal(len(clean)) * np.std(clean) * 0.1
    deg = clean + noise
    base = engine_pesq(clean, deg, fs, mode)

    assert engine_pesq(clean, 0.25 * deg, fs, mode) == pytest.approx(base, abs=0.05)
    delayed = np.concatenate([np.zeros(fs // 100), deg])[: len(deg)]  # 10 ms
    assert engine_pesq(clean, delayed, fs, mode) == pytest.approx(base, abs=0.15)


def test_heavier_distortion_classes_rank_correctly():
    """Additive noise must hurt more than the same-energy removal (the P.862
    asymmetry factor weights added disturbance harder than deletions)."""
    fs = 8000
    rng = np.random.default_rng(3)
    clean = _speechlike(rng, 3 * fs, fs)
    noise = rng.standard_normal(len(clean)) * np.std(clean) * 10 ** (-10 / 20)
    added = engine_pesq(clean, clean + noise, fs, "nb")
    muffled = engine_pesq(clean, clean * 0.9, fs, "nb")  # mild attenuation only
    assert muffled > added


def test_validation_errors():
    x = np.zeros(4000)
    with pytest.raises(ValueError, match="fs"):
        engine_pesq(x, x, 44100, "nb")
    with pytest.raises(ValueError, match="mode"):
        engine_pesq(x, x, 8000, "xb")
    with pytest.raises(ValueError, match="Wide-band"):
        engine_pesq(x, x, 8000, "wb")
    with pytest.raises(ValueError, match="too short"):
        engine_pesq(np.zeros(100), np.zeros(100), 8000, "nb")


def test_functional_batched_and_class_average():
    fs = 8000
    rng = np.random.default_rng(4)
    clean = np.stack([_speechlike(rng, 2 * fs, fs) for _ in range(3)])
    deg = clean + 0.05 * rng.standard_normal(clean.shape) * np.std(clean)

    batched = perceptual_evaluation_speech_quality(jnp.asarray(deg), jnp.asarray(clean), fs, "nb")
    assert batched.shape == (3,)
    assert all(1.0 <= float(v) <= _NB_CEILING + 1e-6 for v in batched)

    metric = PerceptualEvaluationSpeechQuality(fs=fs, mode="nb")
    metric.update(jnp.asarray(deg[:2]), jnp.asarray(clean[:2]))
    metric.update(jnp.asarray(deg[2]), jnp.asarray(clean[2]))
    np.testing.assert_allclose(float(metric.compute()), float(jnp.mean(batched)), atol=1e-5)

    with pytest.raises(ValueError, match="shape"):
        perceptual_evaluation_speech_quality(jnp.zeros((2, 4000)), jnp.zeros((3, 4000)), fs, "nb")


_FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _read_scores(name):
    import csv

    path = os.path.join(_FIXDIR, name)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return {row["item_id"]: float(row["score"]) for row in csv.DictReader(fh)}


def test_stored_corpus_fixture():
    """UNCONDITIONAL stored-oracle fixture (the BERTScore baseline-csv
    pattern, scripts/make_pesq_oracle.py) over the deterministic 15-item
    corpus in tests/audio/pesq_corpus.py:

    1. the engine's scores are pinned to the committed csv (drift pin: any
       numeric change to the engine fails here and must regenerate the
       fixture deliberately);
    2. ordering/range contracts hold on every (fs, mode) config;
    3. when ``pesq_official_scores.csv`` exists (written by the generator
       in any environment with the official binding), every item must agree
       with the official implementation within 0.5 MOS and the corpus mean
       within 0.25 — asserted from the stored values, no binding needed.
    """
    from tests.audio.pesq_corpus import score_with

    got = score_with(engine_pesq)
    pinned = _read_scores("pesq_engine_scores.csv")
    assert pinned is not None, "run scripts/make_pesq_oracle.py to create the fixture"
    assert set(got) == set(pinned)
    for item, score in got.items():
        assert score == pytest.approx(pinned[item], abs=1e-4), item

    for prefix in ("nb8000", "nb16000", "wb16000"):
        order = [got[f"{prefix}_{d}"] for d in ("clean", "snr20", "snr10", "snr05")]
        assert order == sorted(order, reverse=True), (prefix, order)
        assert all(1.0 <= s <= 4.7 for s in order), (prefix, order)

    official = _read_scores("pesq_official_scores.csv")
    if official is not None:
        diffs = [abs(got[item] - official[item]) for item in sorted(official)]
        assert max(diffs) <= 0.5, dict(zip(sorted(official), diffs))
        assert float(np.mean(diffs)) <= 0.25, diffs


def test_parity_vs_pesq_binding():
    """Oracle sweep against the C binding — runs wherever ``pesq`` exists.

    The engine's band layout is formula-derived (module docstring): close,
    not bit-exact. Asserted contract: same degradation ORDERING (more noise
    never scores higher) and absolute agreement within 0.5 MOS — a bound
    chosen for the approximation, not a bit-parity claim.
    """
    reference = pytest.importorskip("pesq")
    fs = 8000
    rng = np.random.default_rng(5)
    clean = _speechlike(rng, 4 * fs, fs)
    noise = rng.standard_normal(len(clean)) * np.std(clean)
    got_scores, want_scores = [], []
    for snr in (20, 10, 5):
        deg = clean + noise * 10 ** (-snr / 20)
        want_scores.append(reference.pesq(fs, clean.astype(np.float32), deg.astype(np.float32), "nb"))
        got_scores.append(engine_pesq(clean, deg, fs, "nb"))
    assert sorted(got_scores, reverse=True) == got_scores  # monotone in SNR
    for got, want in zip(got_scores, want_scores):
        assert got == pytest.approx(want, abs=0.5)
