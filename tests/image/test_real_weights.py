"""Real-pretrained-weight parity tests — gated on artifact availability.

These run wherever ``scripts/fetch_and_convert_weights.py`` has produced its
artifacts (``METRICS_TPU_WEIGHTS`` env var, default ``~/.cache/metrics_tpu/
weights``) AND the torch oracle packages are installed; everywhere else they
skip. They close the loop the converter unit tests (random-initialized torch
mirrors, tests/image/test_fid_kid_is.py) cannot: feature parity and metric
parity from the ACTUAL published weights, the thing FID is famously
sensitive to (reference image/fid.py:26-57, SURVEY hard-part 6).
"""
import os
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

WEIGHTS_DIR = Path(os.environ.get("METRICS_TPU_WEIGHTS", "~/.cache/metrics_tpu/weights")).expanduser()

INCEPTION_NPZ = WEIGHTS_DIR / "inception_fid.npz"
LPIPS_ALEX_NPZ = WEIGHTS_DIR / "lpips_alex.npz"


def _require(path: Path) -> str:
    if not path.exists():
        pytest.skip(
            f"weight artifact {path} not present — run scripts/fetch_and_convert_weights.py"
        )
    return str(path)


def _torch_fid_inception():
    """The torch FID InceptionV3 oracle, from whichever backend is installed."""
    try:
        from torch_fidelity.feature_extractor_inceptionv3 import FeatureExtractorInceptionV3

        net = FeatureExtractorInceptionV3("inception-v3-compat", ["2048"])

        def forward(x_uint8):  # [N,3,299,299] uint8 torch tensor -> [N,2048]
            import torch

            with torch.no_grad():
                return net(x_uint8)[0].numpy()

        return forward
    except Exception:
        pass
    try:
        from pytorch_fid.inception import InceptionV3

        net = InceptionV3([3]).eval()

        def forward(x_uint8):
            import torch

            with torch.no_grad():
                out = net(x_uint8.float() / 255.0)[0]
            return out.squeeze(-1).squeeze(-1).numpy()

        return forward
    except Exception:
        pytest.skip("neither torch_fidelity nor pytorch_fid is installed for the oracle")


def test_fid_real_weight_feature_parity():
    """Converted Flax extractor matches the torch original's 2048-d features
    on real weights (the converter unit test only proves random mirrors)."""
    torch = pytest.importorskip("torch")
    path = _require(INCEPTION_NPZ)
    from metrics_tpu.models.inception import build_fid_inception

    extract = build_fid_inception(2048, weights_path=path)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (4, 3, 299, 299), dtype=np.uint8)

    ours = np.asarray(extract(jnp.asarray(imgs)))
    oracle = _torch_fid_inception()(torch.as_tensor(imgs))
    # bilinear-resize-free 299x299 path: same preprocessing, tight tolerance
    np.testing.assert_allclose(ours, oracle, atol=2e-2, rtol=1e-3)
    # and the statistics FID consumes agree much tighter than per-unit noise
    np.testing.assert_allclose(ours.mean(0), oracle.mean(0), atol=2e-3)


def test_fid_value_real_weights_vs_scipy_sqrtm():
    """End-to-end FID from real weights vs the reference's f64 scipy sqrtm
    computation on the same features."""
    pytest.importorskip("torch")
    scipy_linalg = pytest.importorskip("scipy.linalg")
    path = _require(INCEPTION_NPZ)
    from metrics_tpu.image.fid import FrechetInceptionDistance

    rng = np.random.default_rng(1)
    real = rng.integers(0, 256, (16, 3, 299, 299), dtype=np.uint8)
    fake = rng.integers(0, 256, (16, 3, 299, 299), dtype=np.uint8)

    fid = FrechetInceptionDistance(feature=2048, feature_extractor_weights_path=path)
    fid.update(jnp.asarray(real), real=True)
    fid.update(jnp.asarray(fake), real=False)
    got = float(fid.compute())

    feats_real = np.asarray(fid.inception(jnp.asarray(real)), np.float64)
    feats_fake = np.asarray(fid.inception(jnp.asarray(fake)), np.float64)
    mu1, mu2 = feats_real.mean(0), feats_fake.mean(0)
    s1 = np.cov(feats_real, rowvar=False)
    s2 = np.cov(feats_fake, rowvar=False)
    covmean = scipy_linalg.sqrtm(s1 @ s2).real
    want = float(((mu1 - mu2) ** 2).sum() + np.trace(s1 + s2 - 2 * covmean))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-2)


def test_lpips_real_weight_parity():
    """Converted Flax LPIPS matches the lpips package on real weights."""
    torch = pytest.importorskip("torch")
    lpips_pkg = pytest.importorskip("lpips")
    path = _require(LPIPS_ALEX_NPZ)
    from metrics_tpu.models.lpips import build_lpips

    scorer = build_lpips("alex", weights_path=path)
    rng = np.random.default_rng(2)
    a = rng.uniform(-1, 1, (4, 3, 64, 64)).astype(np.float32)
    b = rng.uniform(-1, 1, (4, 3, 64, 64)).astype(np.float32)

    ours = np.asarray(scorer(jnp.asarray(a), jnp.asarray(b)))
    oracle_net = lpips_pkg.LPIPS(net="alex")
    with torch.no_grad():
        oracle = oracle_net(torch.as_tensor(a), torch.as_tensor(b)).squeeze().numpy()
    np.testing.assert_allclose(ours, oracle, atol=1e-4, rtol=1e-3)


def test_manifest_checksums_match_artifacts():
    """MANIFEST.json sha256 entries must match the artifacts on disk."""
    import hashlib
    import json

    manifest_path = WEIGHTS_DIR / "MANIFEST.json"
    if not manifest_path.exists():
        pytest.skip("no weight manifest present")
    manifest = json.loads(manifest_path.read_text())
    checked = 0
    for name, entry in manifest.items():
        target = WEIGHTS_DIR / name
        if entry.get("sha256") is None or not target.is_file():
            continue
        h = hashlib.sha256(target.read_bytes()).hexdigest()
        assert h == entry["sha256"], f"checksum mismatch for {name}"
        checked += 1
    if not checked:
        pytest.skip("manifest present but no hashable artifacts")
