"""Exact-mode curves with static capacity (SURVEY §7 design-3).

Verifies 1e-6 sklearn parity for exact AUROC / AveragePrecision / ROC / PRC
computed entirely INSIDE one jit (fixed-capacity buffer + valid mask, no
data-dependent shapes), including tied scores, and distributed accumulation
over the 8-virtual-device mesh via all_gather of the buffer triple.
"""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import (
    average_precision_score,
    precision_recall_curve as sk_prc,
    roc_auc_score,
    roc_curve as sk_roc,
)

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.functional.classification.exact_curve import (
    binary_auroc_fixed,
    binary_average_precision_fixed,
    binary_precision_recall_curve_fixed,
    binary_roc_fixed,
    curve_buffer_init,
    curve_buffer_merge,
    curve_buffer_update,
)
from metrics_tpu.utils.compat import shard_map

CAPACITY = 512


def _sk_prc_ref(target, preds):
    """sklearn PRC re-truncated to the REFERENCE convention: modern sklearn
    (>=1.x) keeps every trailing full-recall point, while the reference
    (functional/classification/precision_recall_curve.py:146-147) keeps only
    the first threshold achieving full recall — drop the extra leading
    (recall==1) entries from sklearn's decreasing-recall output."""
    prec, rec, thr = sk_prc(target, preds)
    k = 0
    while k + 1 < len(rec) and rec[k + 1] == 1.0:
        k += 1
    return prec[k:], rec[k:], thr[k:]


def _data(seed, n, ties=False):
    rng = np.random.default_rng(seed)
    preds = rng.random(n).astype(np.float32)
    if ties:
        preds = np.round(preds * 10) / 10  # heavy ties
    target = (rng.random(n) < 0.4).astype(np.int32)
    if target.sum() == 0:
        target[0] = 1
    if target.sum() == n:
        target[0] = 0
    return preds, target


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("ties", [False, True])
def test_auroc_ap_inside_one_jit(seed, ties):
    preds, target = _data(seed, 300, ties)

    @jax.jit
    def run(preds, target):
        state = curve_buffer_init(CAPACITY)
        # three uneven batches through the jit-safe buffer
        state = curve_buffer_update(state, preds[:100], target[:100])
        state = curve_buffer_update(state, preds[100:250], target[100:250])
        state = curve_buffer_update(state, preds[250:], target[250:])
        auroc = binary_auroc_fixed(state["preds"], state["target"], state["valid"])
        ap = binary_average_precision_fixed(state["preds"], state["target"], state["valid"])
        return auroc, ap

    auroc, ap = run(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(auroc), roc_auc_score(target, preds), atol=1e-6)
    np.testing.assert_allclose(float(ap), average_precision_score(target, preds), atol=1e-6)


@pytest.mark.parametrize("ties", [False, True])
def test_roc_curve_points_match_sklearn(ties):
    preds, target = _data(5, 200, ties)

    @jax.jit
    def run(preds, target):
        state = curve_buffer_init(CAPACITY)
        state = curve_buffer_update(state, preds, target)
        return binary_roc_fixed(state["preds"], state["target"], state["valid"])

    fpr, tpr, thr, mask = (np.asarray(v) for v in run(jnp.asarray(preds), jnp.asarray(target)))
    got_fpr, got_tpr, got_thr = fpr[mask], tpr[mask], thr[mask]

    # sklearn drops collinear points (drop_intermediate); compare on the
    # union convention instead: every sklearn point must appear in ours, and
    # trapz areas must agree exactly.
    sk_fpr, sk_tpr, sk_thr = sk_roc(target, preds, drop_intermediate=False)
    np.testing.assert_allclose(got_fpr, sk_fpr, atol=1e-6)
    np.testing.assert_allclose(got_tpr, sk_tpr, atol=1e-6)
    np.testing.assert_allclose(got_thr[1:], sk_thr[1:], atol=1e-6)  # [0] is the +1 sentinel


@pytest.mark.parametrize("ties", [False, True])
def test_prc_points_match_sklearn(ties):
    preds, target = _data(6, 200, ties)

    @jax.jit
    def run(preds, target):
        state = curve_buffer_init(CAPACITY)
        state = curve_buffer_update(state, preds, target)
        return binary_precision_recall_curve_fixed(state["preds"], state["target"], state["valid"])

    precision, recall, thr, mask, last = (
        np.asarray(v) for v in run(jnp.asarray(preds), jnp.asarray(target))
    )
    # reference order: reversed valid points, then the appended (1, 0)
    got_prec = np.concatenate([precision[mask][::-1], [last[0]]])
    got_rec = np.concatenate([recall[mask][::-1], [last[1]]])
    sk_prec, sk_rec, sk_thr = _sk_prc_ref(target, preds)
    np.testing.assert_allclose(got_prec, sk_prec, atol=1e-6)
    np.testing.assert_allclose(got_rec, sk_rec, atol=1e-6)
    np.testing.assert_allclose(thr[mask][::-1], sk_thr, atol=1e-6)


def test_buffer_capacity_drop_and_merge():
    preds, target = _data(7, 64)
    state = curve_buffer_init(32)
    state = curve_buffer_update(state, jnp.asarray(preds), jnp.asarray(target))
    assert int(jnp.sum(state["valid"])) == 32  # overflow dropped, not wrapped

    a = curve_buffer_init(32)
    a = curve_buffer_update(a, jnp.asarray(preds[:20]), jnp.asarray(target[:20]))
    b = curve_buffer_init(32)
    b = curve_buffer_update(b, jnp.asarray(preds[20:40]), jnp.asarray(target[20:40]))
    merged = curve_buffer_merge(a, b)
    auroc = binary_auroc_fixed(merged["preds"], merged["target"], merged["valid"])
    np.testing.assert_allclose(float(auroc), roc_auc_score(target[:40], preds[:40]), atol=1e-6)


def test_exact_curves_sync_over_mesh():
    """Each of 8 devices accumulates a shard; one in-jit all_gather of the
    buffer triple reproduces the global sklearn AUROC/AP on every device."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("rank",))
    preds, target = _data(8, 8 * 64)

    local_cap = 96  # > 64 so padding participates in the gather

    def step(p, t):
        state = curve_buffer_init(local_cap)
        state = curve_buffer_update(state, p[0], t[0])
        gathered = {
            k: jax.lax.all_gather(v, "rank").reshape(-1) for k, v in state.items()
        }
        auroc = binary_auroc_fixed(gathered["preds"], gathered["target"], gathered["valid"])
        ap = binary_average_precision_fixed(gathered["preds"], gathered["target"], gathered["valid"])
        return auroc[None], ap[None]

    auroc, ap = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(P("rank"), P("rank")),
            out_specs=(P("rank"), P("rank")),
        )
    )(jnp.asarray(preds).reshape(8, 64), jnp.asarray(target).reshape(8, 64))

    expected_auroc = roc_auc_score(target, preds)
    expected_ap = average_precision_score(target, preds)
    np.testing.assert_allclose(np.asarray(auroc), expected_auroc, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ap), expected_ap, atol=1e-6)


def test_degenerate_single_class_is_nan():
    state = curve_buffer_init(16)
    state = curve_buffer_update(state, jnp.asarray([0.1, 0.8]), jnp.asarray([1, 1]))
    assert np.isnan(float(binary_auroc_fixed(state["preds"], state["target"], state["valid"])))
    state = curve_buffer_init(16)
    state = curve_buffer_update(state, jnp.asarray([0.1, 0.8]), jnp.asarray([0, 0]))
    assert np.isnan(
        float(binary_average_precision_fixed(state["preds"], state["target"], state["valid"]))
    )


# ---------------------------------------------------------------------------
# modular classes in capacity mode
# ---------------------------------------------------------------------------


def test_auroc_class_capacity_mode_jit_safe():
    from metrics_tpu import AUROC

    preds, target = _data(10, 128)
    m = AUROC(capacity=256)
    assert not m.__jit_unsafe__

    @jax.jit
    def run(p, t):
        state = m.init_state()
        state = m.update_state(state, p[:64], t[:64])
        state = m.update_state(state, p[64:], t[64:])
        return m.compute_state(state)

    got = float(run(jnp.asarray(preds), jnp.asarray(target)))
    np.testing.assert_allclose(got, roc_auc_score(target, preds), atol=1e-6)

    # eager lifecycle too
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), roc_auc_score(target, preds), atol=1e-6)
    m.reset()
    assert int(jnp.sum(m.valid)) == 0


def test_average_precision_class_capacity_mode():
    from metrics_tpu import AveragePrecision

    preds, target = _data(11, 100)
    m = AveragePrecision(capacity=128)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), average_precision_score(target, preds), atol=1e-6)


def test_roc_prc_class_capacity_mode():
    from metrics_tpu import ROC, PrecisionRecallCurve

    preds, target = _data(12, 80, ties=True)
    roc = ROC(capacity=128)
    roc.update(jnp.asarray(preds), jnp.asarray(target))
    fpr, tpr, thr, mask = (np.asarray(v) for v in roc.compute())
    sk_fpr, sk_tpr, _ = sk_roc(target, preds, drop_intermediate=False)
    np.testing.assert_allclose(fpr[mask], sk_fpr, atol=1e-6)
    np.testing.assert_allclose(tpr[mask], sk_tpr, atol=1e-6)

    prc = PrecisionRecallCurve(capacity=128)
    prc.update(jnp.asarray(preds), jnp.asarray(target))
    precision, recall, thr, mask, last = (np.asarray(v) for v in prc.compute())
    sk_prec, sk_rec, _ = _sk_prc_ref(target, preds)
    np.testing.assert_allclose(np.concatenate([precision[mask][::-1], [last[0]]]), sk_prec, atol=1e-6)
    np.testing.assert_allclose(np.concatenate([recall[mask][::-1], [last[1]]]), sk_rec, atol=1e-6)


def test_capacity_overflow_raises_eagerly():
    from metrics_tpu import AUROC
    from metrics_tpu.utils.exceptions import MetricsUserError

    m = AUROC(capacity=8)
    m.update(jnp.asarray(np.random.rand(6)), jnp.asarray([0, 1, 0, 1, 0, 1]))
    with pytest.raises(MetricsUserError, match="capacity overflow"):
        m.update(jnp.asarray(np.random.rand(6)), jnp.asarray([0, 1, 0, 1, 0, 1]))


def test_capacity_mode_rejects_unsupported_configs():
    from metrics_tpu import AUROC, AveragePrecision, ROC

    with pytest.raises(ValueError, match="max_fpr"):
        AUROC(max_fpr=0.5, capacity=64)
    with pytest.raises(ValueError, match="num_classes"):
        AveragePrecision(capacity=64, multilabel=True)
    with pytest.raises(ValueError, match="capacity"):
        ROC(num_classes=5, multilabel=True)


def test_capacity_mode_ddp_sync():
    """cat-sync of the buffer triple across 2 simulated ranks."""
    from metrics_tpu import AUROC

    preds, target = _data(13, 64)
    m_other = AUROC(capacity=64)
    m_other.update(jnp.asarray(preds[32:]), jnp.asarray(target[32:]))
    other_states = iter([m_other.preds, m_other.target, m_other.valid, m_other.overflow])

    m = AUROC(capacity=64, dist_sync_fn=lambda x, group=None: [x, next(other_states)])
    m.update(jnp.asarray(preds[:32]), jnp.asarray(target[:32]))
    got = float(m.compute())
    np.testing.assert_allclose(got, roc_auc_score(target, preds), atol=1e-6)


def test_capacity_mode_pos_label_and_validation():
    from metrics_tpu import AUROC

    preds, target = _data(14, 64)
    # pos_label=0: class 0 treated as positive, parity with the unbounded path
    m = AUROC(capacity=128, pos_label=0)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), roc_auc_score(1 - target, preds), atol=1e-6)

    with pytest.raises(ValueError, match="binary"):
        bad = AUROC(capacity=64)
        bad.update(jnp.asarray(preds[:4]), jnp.asarray([0, 1, 2, 1]))
    with pytest.raises(ValueError, match="integer"):
        bad = AUROC(capacity=64)
        bad.update(jnp.asarray(preds[:4]), jnp.asarray([0.0, 1.0, 0.0, 1.0]))
    with pytest.raises(ValueError, match="float"):
        bad = AUROC(capacity=64)
        bad.update(jnp.asarray([1, 0, 1, 0]), jnp.asarray([0, 1, 0, 1]))


def test_auroc_multiclass_capacity_mode():
    """Exact multiclass one-vs-rest AUROC as a stateful jit-safe metric."""
    from metrics_tpu import AUROC

    rng = np.random.default_rng(20)
    n, c = 120, 5
    preds_np = np.round(rng.random((n, c)), 2).astype(np.float32)  # ties
    target_np = rng.integers(0, c, n).astype(np.int32)

    for avg in ("macro", "weighted", "none"):
        m = AUROC(num_classes=c, capacity=256, average=avg)
        assert not m.__jit_unsafe__
        m.update(jnp.asarray(preds_np[:50]), jnp.asarray(target_np[:50]))
        m.update(jnp.asarray(preds_np[50:]), jnp.asarray(target_np[50:]))
        got = np.asarray(m.compute())
        per_class = np.asarray([
            roc_auc_score((target_np == k).astype(int), preds_np[:, k]) for k in range(c)
        ])
        if avg == "macro":
            want = np.mean(per_class)
        elif avg == "weighted":
            counts = np.bincount(target_np, minlength=c)
            want = np.average(per_class, weights=counts)
        else:
            want = per_class
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_auroc_multiclass_capacity_inside_jit_and_sync():
    from metrics_tpu import AUROC

    rng = np.random.default_rng(21)
    n, c = 64, 4
    preds_np = rng.random((n, c)).astype(np.float32)
    target_np = rng.integers(0, c, n).astype(np.int32)

    m = AUROC(num_classes=c, capacity=64)

    @jax.jit
    def run(p, t):
        state = m.init_state()
        state = m.update_state(state, p[:32], t[:32])
        state = m.update_state(state, p[32:], t[32:])
        return m.compute_state(state)

    got = float(run(jnp.asarray(preds_np), jnp.asarray(target_np)))
    want = float(np.mean([
        roc_auc_score((target_np == k).astype(int), preds_np[:, k]) for k in range(c)
    ]))
    np.testing.assert_allclose(got, want, atol=1e-6)

    # simulated 2-rank cat-sync over the [capacity, C] buffers
    other = AUROC(num_classes=c, capacity=64)
    other.update(jnp.asarray(preds_np[32:]), jnp.asarray(target_np[32:]))
    states = iter([other.preds, other.target, other.valid, other.overflow])
    synced = AUROC(num_classes=c, capacity=64, dist_sync_fn=lambda x, group=None: [x, next(states)])
    synced.update(jnp.asarray(preds_np[:32]), jnp.asarray(target_np[:32]))
    np.testing.assert_allclose(float(synced.compute()), want, atol=1e-6)


def _mc_data(seed, n, c, ties=False):
    rng = np.random.default_rng(seed)
    preds = rng.random((n, c)).astype(np.float32)
    if ties:
        preds = np.round(preds * 10) / 10
    target = rng.integers(0, c, n).astype(np.int32)
    for k in range(c):  # every class present and absent somewhere
        target[k] = k
        target[c + k] = (k + 1) % c
    return preds, target


@pytest.mark.parametrize("ties", [False, True])
def test_multiclass_roc_prc_capacity_match_sklearn(ties):
    """Per-class one-vs-rest curves from the [capacity, C] buffer match
    sklearn's binary curves for every class."""
    from metrics_tpu import ROC, PrecisionRecallCurve

    n, c = 90, 4
    preds, target = _mc_data(30, n, c, ties)

    roc = ROC(num_classes=c, capacity=128)
    roc.update(jnp.asarray(preds[:40]), jnp.asarray(target[:40]))
    roc.update(jnp.asarray(preds[40:]), jnp.asarray(target[40:]))
    fpr, tpr, thr, mask = (np.asarray(v) for v in roc.compute())
    assert fpr.shape == (c, 129)

    prc = PrecisionRecallCurve(num_classes=c, capacity=128)
    prc.update(jnp.asarray(preds), jnp.asarray(target))
    precision, recall, pthr, pmask, last = (np.asarray(v) for v in prc.compute())
    assert precision.shape == (c, 128)

    for k in range(c):
        tgt_k = (target == k).astype(int)
        sk_fpr, sk_tpr, _ = sk_roc(tgt_k, preds[:, k], drop_intermediate=False)
        np.testing.assert_allclose(fpr[k][mask[k]], sk_fpr, atol=1e-6)
        np.testing.assert_allclose(tpr[k][mask[k]], sk_tpr, atol=1e-6)

        sk_prec, sk_rec, _ = _sk_prc_ref(tgt_k, preds[:, k])
        got_prec = np.concatenate([precision[k][pmask[k]][::-1], [last[k, 0]]])
        got_rec = np.concatenate([recall[k][pmask[k]][::-1], [last[k, 1]]])
        np.testing.assert_allclose(got_prec, sk_prec, atol=1e-6)
        np.testing.assert_allclose(got_rec, sk_rec, atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "weighted", "micro", "none"])
def test_multiclass_average_precision_capacity_match_sklearn(average):
    from metrics_tpu import AveragePrecision

    n, c = 100, 5
    preds, target = _mc_data(31, n, c)
    if average == "micro":
        # parity with the unbounded path, capacity-mode AUROC, and the
        # reference: micro is rejected for integer-label multiclass input
        # (the functional kernel keeps the OVR-micro definition for the
        # multilabel capacity mode, tested below)
        with pytest.raises(ValueError, match="micro"):
            AveragePrecision(num_classes=c, capacity=128, average=average)
        return
    m = AveragePrecision(num_classes=c, capacity=128, average=average)
    assert not m.__jit_unsafe__
    m.update(jnp.asarray(preds[:60]), jnp.asarray(target[:60]))
    m.update(jnp.asarray(preds[60:]), jnp.asarray(target[60:]))
    got = np.asarray(m.compute())

    onehot = np.eye(c, dtype=int)[target]
    per_class = np.asarray(
        [average_precision_score(onehot[:, k], preds[:, k]) for k in range(c)]
    )
    if average == "macro":
        want = per_class.mean()
    elif average == "weighted":
        want = np.average(per_class, weights=np.bincount(target, minlength=c))
    else:
        want = per_class
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_multilabel_capacity_curves_and_ap():
    from metrics_tpu import AveragePrecision, PrecisionRecallCurve, ROC

    rng = np.random.default_rng(32)
    n, c = 80, 3
    preds = rng.random((n, c)).astype(np.float32)
    target = (rng.random((n, c)) < 0.4).astype(np.int32)
    target[0] = 1  # every label present
    target[1] = 0  # ... and absent

    ap = AveragePrecision(num_classes=c, capacity=128, multilabel=True, average="macro")
    ap.update(jnp.asarray(preds), jnp.asarray(target))
    want = np.mean([average_precision_score(target[:, k], preds[:, k]) for k in range(c)])
    np.testing.assert_allclose(float(ap.compute()), want, atol=1e-6)

    # micro stays supported for multilabel capacity mode (well-defined over
    # the indicator matrix) and must match sklearn's flattened AP — this
    # value-checks the valid-mask broadcast in the micro flatten path with a
    # PARTIALLY-filled buffer (capacity > n), where a wrong broadcast would
    # pull zero-padded rows into the flattened score set
    ap_micro = AveragePrecision(num_classes=c, capacity=128, multilabel=True, average="micro")
    ap_micro.update(jnp.asarray(preds), jnp.asarray(target))
    want_micro = average_precision_score(target.ravel(), preds.ravel())
    np.testing.assert_allclose(float(ap_micro.compute()), want_micro, atol=1e-6)

    roc = ROC(num_classes=c, capacity=128, multilabel=True)
    roc.update(jnp.asarray(preds), jnp.asarray(target))
    fpr, tpr, _, mask = (np.asarray(v) for v in roc.compute())
    prc = PrecisionRecallCurve(num_classes=c, capacity=128, multilabel=True)
    prc.update(jnp.asarray(preds), jnp.asarray(target))
    precision, recall, _, pmask, last = (np.asarray(v) for v in prc.compute())
    for k in range(c):
        sk_fpr, sk_tpr, _ = sk_roc(target[:, k], preds[:, k], drop_intermediate=False)
        np.testing.assert_allclose(fpr[k][mask[k]], sk_fpr, atol=1e-6)
        np.testing.assert_allclose(tpr[k][mask[k]], sk_tpr, atol=1e-6)
        sk_prec, sk_rec, _ = _sk_prc_ref(target[:, k], preds[:, k])
        np.testing.assert_allclose(
            np.concatenate([precision[k][pmask[k]][::-1], [last[k, 0]]]), sk_prec, atol=1e-6
        )
        np.testing.assert_allclose(
            np.concatenate([recall[k][pmask[k]][::-1], [last[k, 1]]]), sk_rec, atol=1e-6
        )


def test_multiclass_ap_absent_class_excluded_from_average():
    """A class with no positives is excluded from macro/weighted averages and
    NaN in 'none' — the documented capacity-mode convention."""
    from metrics_tpu import AveragePrecision

    rng = np.random.default_rng(33)
    n, c = 40, 4
    preds = rng.random((n, c)).astype(np.float32)
    target = rng.integers(0, c - 1, n).astype(np.int32)  # class c-1 absent

    m = AveragePrecision(num_classes=c, capacity=64, average="none")
    m.update(jnp.asarray(preds), jnp.asarray(target))
    per_class = np.asarray(m.compute())
    assert np.isnan(per_class[c - 1]) and not np.isnan(per_class[: c - 1]).any()

    m2 = AveragePrecision(num_classes=c, capacity=64, average="macro")
    m2.update(jnp.asarray(preds), jnp.asarray(target))
    onehot = np.eye(c, dtype=int)[target]
    want = np.mean(
        [average_precision_score(onehot[:, k], preds[:, k]) for k in range(c - 1)]
    )
    np.testing.assert_allclose(float(m2.compute()), want, atol=1e-6)


def test_multiclass_curve_family_whole_lifecycle_in_jit_and_mesh_sync():
    """Every curve metric (ROC/PRC/AP) runs update→sync→compute inside ONE
    jitted shard_map over the 8-device mesh, reproducing global sklearn
    values from per-device shards."""
    from metrics_tpu import ROC, AveragePrecision

    n_dev = 8
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("rank",))
    n, c = n_dev * 16, 3
    preds, target = _mc_data(34, n, c)

    ap = AveragePrecision(num_classes=c, capacity=32, average="macro")
    roc = ROC(num_classes=c, capacity=32)

    def step(p, t):
        s = ap.init_state()
        s = ap.update_state(s, p[0], t[0])
        synced = {k: jax.lax.all_gather(v, "rank") for k, v in s.items()}
        synced = {
            k: v.reshape((-1,) + v.shape[2:]) for k, v in synced.items()
        }
        ap_val = ap.compute_state(synced)

        r = roc.init_state()
        r = roc.update_state(r, p[0], t[0])
        rsynced = {k: jax.lax.all_gather(v, "rank") for k, v in r.items()}
        rsynced = {k: v.reshape((-1,) + v.shape[2:]) for k, v in rsynced.items()}
        fpr, tpr, thr, mask = roc.compute_state(rsynced)
        # scalarize the curve for the parity check: exact macro AUC via trapz
        # over per-class run-end points would need the mask; assert instead on
        # the count of valid curve points, a mesh-order-invariant quantity
        n_points = jnp.sum(mask)
        return ap_val[None], n_points[None]

    ap_got, n_points = jax.jit(
        shard_map(
            step, mesh=mesh, in_specs=(P("rank"), P("rank")), out_specs=(P("rank"), P("rank"))
        )
    )(
        jnp.asarray(preds).reshape(n_dev, 16, c),
        jnp.asarray(target).reshape(n_dev, 16),
    )

    onehot = np.eye(c, dtype=int)[target]
    want = np.mean([average_precision_score(onehot[:, k], preds[:, k]) for k in range(c)])
    np.testing.assert_allclose(np.asarray(ap_got), want, atol=1e-6)
    assert (np.asarray(n_points) > 0).all()


def test_multiclass_macro_weighted_nan_when_no_class_defined():
    """A blanked valid mask (overflow poisoning under jit, or a never-updated
    buffer) must yield NaN for macro/weighted — never a plausible 0.0."""
    from metrics_tpu import AUROC, AveragePrecision
    from metrics_tpu.functional.classification.exact_curve import (
        multiclass_average_precision_fixed,
    )

    c = 3
    preds = jnp.zeros((8, c), jnp.float32)
    target = jnp.zeros((8,), jnp.int32)
    valid = jnp.zeros((8,), bool)
    for avg in ("macro", "weighted", "micro"):
        assert np.isnan(
            float(multiclass_average_precision_fixed(preds, target, valid, c, average=avg))
        )

    # overflow under jit NaN-poisons the averaged multiclass metrics too
    for cls, kwargs in ((AUROC, {}), (AveragePrecision, {})):
        m = cls(num_classes=c, capacity=4, **kwargs)
        state = m.init_state()
        upd = jax.jit(m.update_state)
        p = jnp.linspace(0.1, 0.9, 6)[:, None] * jnp.ones((1, c))
        t = jnp.asarray([0, 1, 2, 0, 1, 2])
        state = upd(state, p, t)
        assert int(state["overflow"]) > 0
        assert np.isnan(float(jax.jit(m.compute_state)(state)))


def test_buffer_update_after_merge_appends_into_free_slots():
    """curve_buffer_update writes into the first FREE slots (mask-derived),
    so updating a merged non-contiguous buffer never overwrites valid data."""
    a = curve_buffer_init(8)
    a = curve_buffer_update(a, jnp.asarray([0.1, 0.2]), jnp.asarray([0, 1]))
    b = curve_buffer_init(8)
    b = curve_buffer_update(b, jnp.asarray([0.3]), jnp.asarray([1]))
    merged = curve_buffer_merge(a, b)  # valid: [T T F...|T F...] — non-contiguous
    merged = curve_buffer_update(merged, jnp.asarray([0.4, 0.5]), jnp.asarray([0, 1]))
    valid = np.asarray(merged["valid"])
    assert valid.sum() == 5
    got = sorted(np.asarray(merged["preds"])[valid].tolist())
    np.testing.assert_allclose(got, [0.1, 0.2, 0.3, 0.4, 0.5], atol=1e-6)


def test_capacity_overflow_under_jit_is_detected():
    """Inside jit the fill count is traced and the eager raise cannot fire;
    the overflow state must make the result detectable, not silently wrong."""
    from metrics_tpu import AUROC
    from metrics_tpu.utils.exceptions import MetricsUserError

    m = AUROC(capacity=8)
    state = m.init_state()
    upd = jax.jit(m.update_state)
    p = jnp.linspace(0.05, 0.95, 6)
    t = jnp.asarray([0, 1, 0, 1, 0, 1])
    state = upd(state, p, t)
    state = upd(state, p, t)  # 12 samples into capacity 8
    assert int(state["overflow"]) > 0
    # traced compute NaN-poisons
    assert np.isnan(float(jax.jit(m.compute_state)(state)))
    # eager compute raises a descriptive error
    with pytest.raises(MetricsUserError, match="capacity overflow"):
        m.compute_state(state)
