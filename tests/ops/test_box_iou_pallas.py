"""Pallas box-IoU tile kernel vs the jnp broadcast implementation.

Runs the REAL kernel body in Pallas interpret mode on CPU (the driver's TPU
bench exercises the compiled path through box_iou_dispatch).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.functional.detection.box_ops import box_iou
from metrics_tpu.ops import box_iou_dispatch, box_iou_tiled


def _boxes(rng, n):
    x1 = rng.uniform(0, 500, n)
    y1 = rng.uniform(0, 500, n)
    w = rng.uniform(1, 200, n)
    h = rng.uniform(1, 200, n)
    return np.stack([x1, y1, x1 + w, y1 + h], 1).astype(np.float32)


@pytest.mark.parametrize("n,m", [(1, 1), (7, 13), (128, 128), (130, 257), (300, 40)])
def test_tiled_matches_jnp(n, m):
    rng = np.random.default_rng(n * 1000 + m)
    b1, b2 = _boxes(rng, n), _boxes(rng, m)
    got = np.asarray(box_iou_tiled(jnp.asarray(b1), jnp.asarray(b2), interpret=True))
    want = np.asarray(box_iou(b1, b2))
    assert got.shape == (n, m)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_tiled_identity_diagonal():
    rng = np.random.default_rng(0)
    b = _boxes(rng, 50)
    got = np.asarray(box_iou_tiled(jnp.asarray(b), jnp.asarray(b), interpret=True))
    np.testing.assert_allclose(np.diag(got), 1.0, atol=1e-6)


def test_degenerate_boxes_zero_not_nan():
    b1 = jnp.asarray([[0.0, 0.0, 0.0, 0.0], [0.0, 0.0, 10.0, 10.0]])
    b2 = jnp.asarray([[0.0, 0.0, 0.0, 0.0]])
    got = np.asarray(box_iou_tiled(b1, b2, interpret=True))
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, 0.0)


def test_dispatch_falls_back_off_tpu():
    rng = np.random.default_rng(1)
    b1, b2 = _boxes(rng, 20), _boxes(rng, 30)
    got = np.asarray(box_iou_dispatch(jnp.asarray(b1), jnp.asarray(b2)))
    np.testing.assert_allclose(got, np.asarray(box_iou(b1, b2)), atol=1e-6)
