"""Reference-parity sweep for the confusion-matrix family and StatScores.

Breadth parity with /root/reference/tests/classification/
test_{confusion_matrix,jaccard,cohen_kappa,matthews_corrcoef,
hamming_distance,stat_scores}.py: every input case the metric accepts x its
own argument axes (normalize modes, weights, absent_score/ignore_index,
reduce x mdmc_reduce x top_k), with the reference implementation as oracle
(helpers/reference.py). The sklearn-oracle files (test_confusion_family.py,
test_stat_scores.py) stay as independent ground truth; this grid covers the
argument corners those cannot express.
"""
from functools import partial

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.classification import (
    CohenKappa,
    ConfusionMatrix,
    HammingDistance,
    JaccardIndex,
    MatthewsCorrCoef,
    StatScores,
)
from metrics_tpu.functional import (
    cohen_kappa as mt_cohen_kappa,
    confusion_matrix as mt_confusion_matrix,
    hamming_distance as mt_hamming,
    jaccard_index as mt_jaccard,
    matthews_corrcoef as mt_matthews,
    stat_scores as mt_stat_scores,
)
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_logits,
    _input_multiclass_prob,
    _input_multiclass_with_missing_class,
    _input_multidim_multiclass,
    _input_multidim_multiclass_prob,
    _input_multilabel,
    _input_multilabel_logits,
    _input_multilabel_prob,
)
from tests.helpers.reference import assert_accumulated_parity, ref_oracle as _ref_oracle
from tests.helpers.testers import NUM_CLASSES, MetricTester

torch = pytest.importorskip("torch")


# (case_name, fixture, num_classes, extra_args) — the classes each fixture
# implies for the confusion-family constructors (binary -> 2)
CM_CASES = [
    ("binary_prob", _input_binary_prob, 2, {}),
    ("binary_logits", _input_binary_logits, 2, {}),
    ("binary", _input_binary, 2, {}),
    ("multiclass_prob", _input_multiclass_prob, NUM_CLASSES, {}),
    ("multiclass_logits", _input_multiclass_logits, NUM_CLASSES, {}),
    ("multiclass", _input_multiclass, NUM_CLASSES, {}),
    ("multiclass_missing_class", _input_multiclass_with_missing_class, NUM_CLASSES, {}),
    ("mdmc_prob", _input_multidim_multiclass_prob, NUM_CLASSES, {}),
    ("mdmc", _input_multidim_multiclass, NUM_CLASSES, {}),
]
CM_IDS = [c for c, *_ in CM_CASES]


@pytest.mark.parametrize("case_name, fixture, num_classes, extra", CM_CASES, ids=CM_IDS)
@pytest.mark.parametrize("normalize", [None, "true", "pred", "all"])
class TestConfusionMatrixReferenceGrid(MetricTester):
    atol = 1e-6

    def test_confusion_matrix(self, case_name, fixture, num_classes, extra, normalize):
        args = {"num_classes": num_classes, "normalize": normalize, **extra}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=ConfusionMatrix,
            sk_metric=_ref_oracle("confusion_matrix", **args),
            metric_args=args,
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_confusion_matrix_functional(self, case_name, fixture, num_classes, extra, normalize):
        args = {"num_classes": num_classes, "normalize": normalize, **extra}
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_confusion_matrix,
            sk_metric=_ref_oracle("confusion_matrix", **args),
            metric_args=args,
            atol=1e-6,
        )


@pytest.mark.parametrize(
    "case_name, fixture, num_classes",
    [(c, f, n) for c, f, n, _ in CM_CASES] + [("multilabel_prob", _input_multilabel_prob, NUM_CLASSES)],
    ids=CM_IDS + ["multilabel_prob"],
)
def test_confusion_matrix_multilabel_and_cases(case_name, fixture, num_classes):
    """Multilabel mode (reference confusion_matrix multilabel=True) plus the
    shared cases through the one-shot functional."""
    multilabel = case_name.startswith("multilabel")
    args = {"num_classes": num_classes, "multilabel": multilabel}
    oracle = _ref_oracle("confusion_matrix", **args)
    got = mt_confusion_matrix(
        jnp.asarray(fixture.preds[0]), jnp.asarray(fixture.target[0]), **args
    )
    np.testing.assert_allclose(np.asarray(got), oracle(fixture.preds[0], fixture.target[0]), atol=1e-6)


# ---------------------------------------------------------------------------
# JaccardIndex: reduction x ignore_index x absent_score
# ---------------------------------------------------------------------------

JACCARD_CASES = [
    ("binary_prob", _input_binary_prob, 2),
    ("binary", _input_binary, 2),
    ("multiclass_prob", _input_multiclass_prob, NUM_CLASSES),
    ("multiclass", _input_multiclass, NUM_CLASSES),
    ("multiclass_missing_class", _input_multiclass_with_missing_class, NUM_CLASSES),
    ("mdmc_prob", _input_multidim_multiclass_prob, NUM_CLASSES),
]


@pytest.mark.parametrize("case_name, fixture, num_classes", JACCARD_CASES, ids=[c for c, *_ in JACCARD_CASES])
@pytest.mark.parametrize("reduction", ["elementwise_mean", "none"])
class TestJaccardReferenceGrid(MetricTester):
    atol = 1e-6

    def test_jaccard(self, case_name, fixture, num_classes, reduction):
        args = {"num_classes": num_classes, "reduction": reduction}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=JaccardIndex,
            sk_metric=_ref_oracle("jaccard_index", **args),
            metric_args=args,
        )


@pytest.mark.parametrize("ignore_index", [0, 1])
@pytest.mark.parametrize("absent_score", [0.0, -1.0])
def test_jaccard_ignore_index_absent_score(ignore_index, absent_score):
    fixture = _input_multiclass_with_missing_class
    args = {
        "num_classes": NUM_CLASSES,
        "ignore_index": ignore_index,
        "absent_score": absent_score,
        "reduction": "none",
    }
    assert_accumulated_parity(JaccardIndex(**args), fixture, _ref_oracle("jaccard_index", **args))


# ---------------------------------------------------------------------------
# CohenKappa: weights x input cases
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case_name, fixture, num_classes, extra", CM_CASES[:6], ids=CM_IDS[:6])
@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
class TestCohenKappaReferenceGrid(MetricTester):
    atol = 1e-6

    def test_cohen_kappa(self, case_name, fixture, num_classes, extra, weights):
        args = {"num_classes": num_classes, "weights": weights}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=CohenKappa,
            sk_metric=_ref_oracle("cohen_kappa", **args),
            metric_args=args,
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_cohen_kappa_functional(self, case_name, fixture, num_classes, extra, weights):
        args = {"num_classes": num_classes, "weights": weights}
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_cohen_kappa,
            sk_metric=_ref_oracle("cohen_kappa", **args),
            metric_args=args,
            atol=1e-6,
        )


# ---------------------------------------------------------------------------
# MatthewsCorrCoef over every input case
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case_name, fixture, num_classes, extra", CM_CASES, ids=CM_IDS)
class TestMatthewsReferenceGrid(MetricTester):
    atol = 1e-6

    def test_matthews(self, case_name, fixture, num_classes, extra):
        args = {"num_classes": num_classes}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=MatthewsCorrCoef,
            sk_metric=_ref_oracle("matthews_corrcoef", **args),
            metric_args=args,
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_matthews_functional(self, case_name, fixture, num_classes, extra):
        args = {"num_classes": num_classes}
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_matthews,
            sk_metric=_ref_oracle("matthews_corrcoef", **args),
            metric_args=args,
            atol=1e-6,
        )


# ---------------------------------------------------------------------------
# HammingDistance over every case x threshold
# ---------------------------------------------------------------------------

HAMMING_CASES = [
    ("binary_prob", _input_binary_prob),
    ("binary", _input_binary),
    ("multilabel_prob", _input_multilabel_prob),
    ("multilabel_logits", _input_multilabel_logits),
    ("multilabel", _input_multilabel),
    ("multiclass_prob", _input_multiclass_prob),
    ("multiclass", _input_multiclass),
    ("mdmc_prob", _input_multidim_multiclass_prob),
    ("mdmc", _input_multidim_multiclass),
]


@pytest.mark.parametrize("case_name, fixture", HAMMING_CASES, ids=[c for c, _ in HAMMING_CASES])
class TestHammingReferenceGrid(MetricTester):
    atol = 1e-6

    def test_hamming(self, case_name, fixture):
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=HammingDistance,
            sk_metric=_ref_oracle("hamming_distance"),
            metric_args={},
            dist_sync_on_step=case_name.endswith("_prob"),
        )

    def test_hamming_functional(self, case_name, fixture):
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_hamming,
            sk_metric=_ref_oracle("hamming_distance"),
            metric_args={},
            atol=1e-6,
        )


@pytest.mark.parametrize("threshold", [0.25, 0.75])
def test_hamming_threshold(threshold):
    fixture = _input_multilabel_prob
    assert_accumulated_parity(
        HammingDistance(threshold=threshold), fixture, _ref_oracle("hamming_distance", threshold=threshold)
    )


# ---------------------------------------------------------------------------
# StatScores: reduce x mdmc_reduce x top_k x ignore_index
# (reference test_stat_scores.py parametrization)
# ---------------------------------------------------------------------------

SS_CASES = [
    ("binary_prob", _input_binary_prob, {"num_classes": 1}),
    ("binary", _input_binary, {"num_classes": 1, "multiclass": False}),
    ("multilabel_prob", _input_multilabel_prob, {"num_classes": NUM_CLASSES}),
    ("multilabel", _input_multilabel, {"num_classes": NUM_CLASSES, "multiclass": False}),
    ("multiclass_prob", _input_multiclass_prob, {"num_classes": NUM_CLASSES}),
    ("multiclass", _input_multiclass, {"num_classes": NUM_CLASSES}),
]


@pytest.mark.parametrize("case_name, fixture, base_args", SS_CASES, ids=[c for c, *_ in SS_CASES])
@pytest.mark.parametrize("reduce_", ["micro", "macro", "samples"])
class TestStatScoresReferenceGrid(MetricTester):
    atol = 1e-6

    def test_stat_scores(self, case_name, fixture, base_args, reduce_):
        args = {**base_args, "reduce": reduce_}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=StatScores,
            sk_metric=_ref_oracle("stat_scores", **args),
            metric_args=args,
            # samples-reduce keeps per-sample rows: a list state (no jit), and
            # the virtual-rank merge permutes batch order (ranks stride
            # batches), so the order-sensitive row output can't be compared
            # against the in-order oracle — reference ddp tests reorder the
            # oracle input the same way (testers.py:177)
            check_jit=reduce_ != "samples",
            check_merge=reduce_ != "samples",
        )

    def test_stat_scores_functional(self, case_name, fixture, base_args, reduce_):
        args = {**base_args, "reduce": reduce_}
        self.run_functional_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_functional=mt_stat_scores,
            sk_metric=_ref_oracle("stat_scores", **args),
            metric_args=args,
            atol=1e-6,
        )


@pytest.mark.parametrize("mdmc_reduce", ["global", "samplewise"])
@pytest.mark.parametrize("reduce_", ["micro", "macro"])
@pytest.mark.parametrize(
    "fixture", [_input_multidim_multiclass_prob, _input_multidim_multiclass], ids=["prob", "int"]
)
class TestStatScoresMdmcReferenceGrid(MetricTester):
    atol = 1e-6

    def test_stat_scores_mdmc(self, fixture, reduce_, mdmc_reduce):
        args = {"num_classes": NUM_CLASSES, "reduce": reduce_, "mdmc_reduce": mdmc_reduce}
        self.run_class_metric_test(
            preds=fixture.preds,
            target=fixture.target,
            metric_class=StatScores,
            sk_metric=_ref_oracle("stat_scores", **args),
            metric_args=args,
            check_jit=mdmc_reduce != "samplewise",
            check_merge=mdmc_reduce != "samplewise",
        )


@pytest.mark.parametrize("top_k", [1, 2])
def test_stat_scores_top_k(top_k):
    fixture = _input_multiclass_prob
    args = {"num_classes": NUM_CLASSES, "reduce": "macro", "top_k": top_k}
    assert_accumulated_parity(StatScores(**args), fixture, _ref_oracle("stat_scores", **args))


@pytest.mark.parametrize("ignore_index", [0, 2])
def test_stat_scores_ignore_index(ignore_index):
    fixture = _input_multiclass_prob
    args = {"num_classes": NUM_CLASSES, "reduce": "macro", "ignore_index": ignore_index}
    assert_accumulated_parity(StatScores(**args), fixture, _ref_oracle("stat_scores", **args))


# ---------------------------------------------------------------------------
# KLDivergence: log_prob x reduction grid (reference test_kl_divergence.py)
# ---------------------------------------------------------------------------

_KL_RNG = np.random.default_rng(61)
_KL_P = _KL_RNG.random((3, 16, 6)).astype(np.float32) + 1e-3
_KL_P /= _KL_P.sum(-1, keepdims=True)
_KL_Q = _KL_RNG.random((3, 16, 6)).astype(np.float32) + 1e-3
_KL_Q /= _KL_Q.sum(-1, keepdims=True)


@pytest.mark.parametrize("log_prob", [False, True])
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_kl_divergence_reference_grid(log_prob, reduction):
    from metrics_tpu.classification import KLDivergence

    p = np.log(_KL_P) if log_prob else _KL_P
    q = np.log(_KL_Q) if log_prob else _KL_Q
    args = {"log_prob": log_prob, "reduction": reduction}
    ours = KLDivergence(**args)
    oracle = _ref_oracle("kl_divergence", **args)
    for i in range(p.shape[0]):
        ours.update(jnp.asarray(p[i]), jnp.asarray(q[i]))
    want = oracle(p.reshape(-1, 6), q.reshape(-1, 6))
    np.testing.assert_allclose(np.asarray(ours.compute()), want, rtol=1e-4, atol=1e-6)


def test_kl_divergence_shape_errors_match_reference():
    from metrics_tpu.classification import KLDivergence

    m = KLDivergence()
    with pytest.raises((ValueError, RuntimeError)):
        m.update(jnp.zeros((4, 3)), jnp.zeros((4, 5)))  # mismatched shapes
    with pytest.raises(ValueError):
        m.update(jnp.zeros((4,)), jnp.zeros((4,)))  # 1-D rejected (2-D contract)


# ---------------------------------------------------------------------------
# CalibrationError: norm x n_bins vs the reference (the sklearn-free corner;
# the hand-rolled oracle sweep lives in test_confusion_family.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
@pytest.mark.parametrize("n_bins", [5, 15])
def test_calibration_error_reference_grid(norm, n_bins):
    from metrics_tpu.classification import CalibrationError

    fixture = _input_multiclass_prob
    args = {"norm": norm, "n_bins": n_bins}
    assert_accumulated_parity(
        CalibrationError(**args), fixture, _ref_oracle("calibration_error", **args)
    )


# ---------------------------------------------------------------------------
# HingeLoss: squared x multiclass_mode over probability inputs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("squared", [False, True])
@pytest.mark.parametrize("multiclass_mode", [None, "crammer-singer", "one-vs-all"])
def test_hinge_reference_grid(squared, multiclass_mode):
    from metrics_tpu.classification import HingeLoss

    fixture = _input_multiclass_logits
    args = {"squared": squared, "multiclass_mode": multiclass_mode}
    assert_accumulated_parity(
        HingeLoss(**args), fixture, _ref_oracle("hinge_loss", **args), atol=1e-4
    )
