"""Fleet observatory demo: N publisher processes, one merge-tree collector,
fault injection that trips (and clears) every fleet alarm class.

The demo ROADMAP item 3 exists for: three REAL publisher subprocesses each
run their own metric collection (integer-exact ``Accuracy`` + a running
``MeanSquaredError``) over simulated traffic and publish cumulative fleet
snapshots — metric-state pytrees plus their telemetry counter payload,
schema-versioned and provenance-stamped — into a directory-queue sink
(:class:`~metrics_tpu.observability.SnapshotSink`). The orchestrator runs
a :class:`~metrics_tpu.observability.FleetCollector` that folds the
snapshots through the same ``merge_states``/``merge_payloads`` reducers a
single job would use, tracks per-publisher liveness/lag, and feeds the
windowed ``publisher_lag_s`` / ``collector_backlog`` /
``collector_fold_errors`` series a :class:`HealthMonitor` alarms on.

Fault injection (``--inject all``, the default) drives all three fleet
alarm classes through a fire-AND-clear cycle plus the two wire-level
hazards the collector must absorb silently:

* **duplicates** — publisher 0 re-ships every 4th snapshot byte-for-byte
  (same publisher + sequence number): the collector's exactly-once dedup
  counts and drops them, and the fold is unaffected.
* **late snapshot** — publisher 1 ships one snapshot stamped far behind
  the event-time watermark: counted and dropped, never folded.
* **stalled publisher** — publisher 2 goes silent for a slice of the run:
  its lag grows past the bound (``publisher_stale`` fires) and recovers
  when it resumes (the alarm clears as the window rolls).
* **collector pause** — the orchestrator stops polling for a slice while
  publishers keep shipping: the queue piles up (``snapshot_backlog``
  fires on the post-pause poll) and drains (clears).
* **corrupt snapshot** — the orchestrator drops a garbage ``.snap`` file
  into the queue: ``fold_error`` (critical) fires and clears once the
  window rolls past it.

Artifacts land in ``--out-dir``: ``fleet.prom`` (the federated Prometheus
page: per-host-labelled families, the global fold, the collector's fleet
families, and the fleet-wide metric values), ``telemetry.jsonl``,
``health_alarms.jsonl``, ``health.txt``, and ``report.json``. Exit status
is 0 unless an ``--assert-*`` contract fails (the CI smoke leg).

Run::

    python examples/fleet_collector.py --duration 12 --inject all
"""
import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))  # repo root

os.environ.setdefault("JAX_PLATFORMS", "cpu")

INJECT_MODES = ("none", "faults", "all")

#: fault window as fractions of --duration (collector clock): the pause /
#: stall / corrupt-file injections all land inside it, the tail after it
#: gives every alarm the wall time to clear
FAULT_LO_FRAC, FAULT_HI_FRAC = 0.30, 0.55


def make_collection():
    """The shared publisher/collector template: integer-exact Accuracy
    (sum-reduced count states — the collector fold is bit-identical to a
    single job) plus a running MSE."""
    from metrics_tpu import MeanSquaredError, MetricCollection
    from metrics_tpu.classification import Accuracy

    return MetricCollection({"acc": Accuracy(num_classes=2), "mse": MeanSquaredError()})


# ---------------------------------------------------------------------------
# publisher role (subprocess)
# ---------------------------------------------------------------------------

def run_publisher(args) -> int:
    """One publisher process: update the collection with deterministic
    traffic, publish a cumulative snapshot every interval, and play the
    faults this publisher was assigned."""
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.observability import SnapshotSink, counter_payload, get_recorder, snapshot_states

    rng = np.random.default_rng(args.seed)
    rec = get_recorder()
    rec.reset()
    rec.enable()
    col = make_collection()
    sink = SnapshotSink(
        args.queue_dir,
        publisher=args.publisher_id,
        host=f"host-{args.publisher_id}",
        process=args.process,
    )
    t_start = time.time()
    stall_lo = args.stall_lo_frac * args.duration
    stall_hi = args.stall_hi_frac * args.duration
    published = 0
    sent_late = False
    while True:
        elapsed = time.time() - t_start
        if elapsed >= args.duration:
            break
        if stall_lo <= elapsed < stall_hi:
            # stalled publisher: no traffic, no snapshots — the collector
            # watches this publisher's lag grow past the staleness bound
            time.sleep(0.05)
            continue
        preds = jnp.asarray(rng.integers(0, 2, args.batch_size), jnp.int32)
        target = jnp.asarray(rng.integers(0, 2, args.batch_size), jnp.int32)
        col.update(preds, target)
        sink.publish(
            states=snapshot_states(col),
            states_template=col,
            telemetry=counter_payload(rec),
        )
        published += 1
        if args.dup_every and published % args.dup_every == 0:
            # byte-for-byte re-ship of the previous snapshot (same
            # publisher + seq): the dedup contract's live fixture
            sink.republish_last()
        if args.late_at_frac and not sent_late and elapsed >= args.late_at_frac * args.duration:
            # one snapshot stamped far behind the watermark — counted and
            # dropped; the fresh-seq/old-t combination is exactly what a
            # partitioned-then-healed publisher replays
            sent_late = True
            sink.publish(
                states=snapshot_states(col),
                states_template=col,
                telemetry=counter_payload(rec),
                t=time.time() - args.late_by_s,
            )
        time.sleep(args.interval)
    return 0


# ---------------------------------------------------------------------------
# orchestrator role (collector + subprocess publishers)
# ---------------------------------------------------------------------------

def run(
    duration: float = 12.0,
    inject: str = "all",
    out_dir: str = "fleet_artifacts",
    n_publishers: int = 3,
    interval: float = 0.2,
    poll_interval: float = 0.25,
    late_window_s: float = 3.0,
    window_s: float = 4.0,
    batch_size: int = 32,
    seed: int = 0,
    verbose: bool = True,
):
    """Drive the fleet and return the run report (also written to
    ``<out_dir>/report.json``)."""
    if inject not in INJECT_MODES:
        raise ValueError(f"inject must be one of {INJECT_MODES}, got {inject!r}")
    from metrics_tpu.observability import (
        FleetCollector,
        HealthMonitor,
        PeriodicExporter,
        default_rules,
        get_recorder,
        render_health,
        summary,
    )

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    queue_dir = out / "queue"
    queue_dir.mkdir(exist_ok=True)
    for stale in queue_dir.glob("*.snap"):
        stale.unlink()

    faults = inject in ("faults", "all")
    rec = get_recorder()
    was_enabled = rec.enabled
    rec.reset()
    rec.enable()
    rec.attach_timeseries(
        bucket_seconds=0.5,
        n_buckets=max(int(3 * window_s / 0.5), 16),
        sketch_capacity=128,
    )
    stale_after_s = max(6 * interval, 1.5)
    monitor = HealthMonitor(
        default_rules(
            window_s=window_s,
            publisher_lag_limit_s=stale_after_s,
            # steady state leaves ~n_publishers * poll/publish ratio files
            # per poll; the pause piles up an order of magnitude more
            backlog_limit=max(4 * n_publishers, 8),
            fold_errors_per_window=1,
        ),
        recorder=rec,
        alarm_log_path=str(out / "health_alarms.jsonl"),
    )
    template = make_collection()
    collector = FleetCollector(
        str(queue_dir),
        template=template,
        late_window_s=late_window_s,
        stale_after_s=stale_after_s,
        recorder=rec,
    )
    exporter = PeriodicExporter(
        interval_s=1.0,
        prometheus_path=str(out / "fleet.prom"),
        jsonl_path=str(out / "telemetry.jsonl"),
        recorder=rec,
        health=monitor,
    )
    exporter.start()

    # spawn the publishers: per-publisher fault assignments (dup / late /
    # stall) only under injection
    procs = []
    for i in range(n_publishers):
        cmd = [
            sys.executable,
            os.path.abspath(__file__),
            "--role", "publisher",
            "--queue-dir", str(queue_dir),
            "--publisher-id", f"pub{i}",
            "--process", str(i),
            "--duration", str(duration),
            "--interval", str(interval),
            "--batch-size", str(batch_size),
            "--seed", str(seed + i),
            "--late-by-s", str(late_window_s + 30.0),
        ]
        if faults and i == 0:
            cmd += ["--dup-every", "4"]
        if faults and i == 1:
            cmd += ["--late-at-frac", str((FAULT_LO_FRAC + FAULT_HI_FRAC) / 2)]
        if faults and i == 2:
            cmd += ["--stall-lo-frac", str(FAULT_LO_FRAC), "--stall-hi-frac", str(FAULT_HI_FRAC)]
        procs.append(subprocess.Popen(cmd, env=dict(os.environ, JAX_PLATFORMS="cpu")))

    fault_lo, fault_hi = FAULT_LO_FRAC * duration, FAULT_HI_FRAC * duration
    pause_lo, pause_hi = fault_lo, fault_lo + 0.6 * (fault_hi - fault_lo)
    t_start = time.time()
    # the collector-side fault window is anchored to the FIRST ABSORBED
    # snapshot, not to subprocess spawn: each publisher pays several
    # seconds of jax import before it ships anything, and a polling pause
    # scheduled on the spawn clock can land entirely inside that silence
    # on a slow box — no snapshots pile up, snapshot_backlog never fires
    fault_t0 = None
    corrupted = False
    polls = 0
    try:
        # collect until every publisher has exited AND the window has had
        # time to roll every fired alarm clear
        tail_end = None
        while True:
            now = time.time()
            if fault_t0 is None and collector.totals()["absorbed"] > 0:
                fault_t0 = now
            elapsed = (now - fault_t0) if fault_t0 is not None else -1.0
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    # clean shutdown deregisters the publisher from
                    # liveness: an exited-on-purpose publisher must not
                    # read as a stalled one through the tail
                    collector.retire_publisher(f"pub{i}")
            if tail_end is None and all(p.poll() is not None for p in procs):
                tail_end = time.time() + window_s + 2.0
            if tail_end is not None and time.time() >= tail_end:
                break
            in_pause = faults and pause_lo <= elapsed < pause_hi
            if faults and not corrupted and elapsed >= (pause_lo + pause_hi) / 2:
                # fold_error fixture: garbage bytes in the queue — the
                # collector must count it and keep folding
                corrupted = True
                (queue_dir / "zz-corrupt-000000000000.snap").write_bytes(b"not a snapshot")
            if not in_pause:
                collector.poll()
                polls += 1
                monitor.evaluate()
            time.sleep(poll_interval)
        collector.flush_pending()
        collector.poll()
        final = monitor.evaluate()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            p.wait(timeout=30)
        exporter.stop()

    # final artifacts: the federated page (global fold + per-host families
    # + collector families + fleet-wide metric values) and the report
    prom = collector.render_prometheus(include_fold_values=True)
    prom += "\n".join(monitor.prometheus_lines(final)) + "\n"
    (out / "fleet.prom").write_text(prom)
    health_text = render_health(final)
    (out / "health.txt").write_text(health_text + "\n")

    totals = collector.totals()
    values = {k: float(v) for k, v in collector.fold_values().items()}
    report = {
        "inject": inject,
        "duration_s": duration,
        "polls": polls,
        "publisher_exit_codes": [p.returncode for p in procs],
        "totals": totals,
        "fleet_values": values,
        "publishers": [
            {
                "publisher": s.publisher,
                "host": s.host,
                "last_seq": s.last_seq,
                "stale": s.stale,
                "absorbed": s.absorbed,
                "duplicates": s.duplicates,
                "late_dropped": s.late_dropped,
            }
            for s in collector.publishers()
        ],
        "final_status": final.status,
        "alarms_fired": monitor.fired_ever(),
        "alarms_fired_and_cleared": monitor.fired_and_cleared(),
        "fold_error_details": collector.fold_error_details,
    }
    (out / "report.json").write_text(json.dumps(report, indent=2) + "\n")
    if verbose:
        print(summary(rec))
        print(health_text)
        print(
            f"fleet_collector: {totals['absorbed']} snapshots folded from"
            f" {totals['publishers']} publishers ({totals['duplicates']} dup,"
            f" {totals['late_dropped']} late, {totals['fold_errors']} fold errors);"
            f" fleet values={values}; alarms fired={report['alarms_fired']}"
            f" fired_and_cleared={report['alarms_fired_and_cleared']};"
            f" artifacts in {out}/"
        )

    rec.disable()
    rec.detach_timeseries()
    rec.reset()
    if was_enabled:
        rec.enable()
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--role", choices=("orchestrator", "publisher"), default="orchestrator")
    parser.add_argument("--duration", type=float, default=12.0)
    parser.add_argument("--inject", choices=INJECT_MODES, default="all")
    parser.add_argument("--out-dir", default="fleet_artifacts")
    parser.add_argument("--publishers", type=int, default=3)
    parser.add_argument("--interval", type=float, default=0.2, help="publish interval (s)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--late-window-seconds", type=float, default=3.0)
    parser.add_argument("--window-seconds", type=float, default=4.0)
    parser.add_argument("--seed", type=int, default=0)
    # publisher-role plumbing (set by the orchestrator)
    parser.add_argument("--queue-dir", default="")
    parser.add_argument("--publisher-id", default="pub")
    parser.add_argument("--process", type=int, default=0)
    parser.add_argument("--dup-every", type=int, default=0)
    parser.add_argument("--late-at-frac", type=float, default=0.0)
    parser.add_argument("--late-by-s", type=float, default=60.0)
    parser.add_argument("--stall-lo-frac", type=float, default=0.0)
    parser.add_argument("--stall-hi-frac", type=float, default=0.0)
    parser.add_argument(
        "--assert-fired-cleared",
        action="store_true",
        help="exit nonzero unless at least one alarm both fired and cleared (CI smoke)",
    )
    parser.add_argument(
        "--assert-alarm",
        action="append",
        default=[],
        metavar="NAME",
        help="exit nonzero unless the NAMED alarm both fired and cleared (repeatable;"
        " the fleet smoke pins publisher_stale, snapshot_backlog, and fold_error"
        " specifically)",
    )
    parser.add_argument(
        "--assert-faults-observed",
        action="store_true",
        help="exit nonzero unless the collector counted at least one duplicate AND"
        " one late-dropped snapshot (the wire-hazard half of the smoke contract)",
    )
    args = parser.parse_args(argv)
    if args.role == "publisher":
        return run_publisher(args)
    report = run(
        duration=args.duration,
        inject=args.inject,
        out_dir=args.out_dir,
        n_publishers=args.publishers,
        interval=args.interval,
        late_window_s=args.late_window_seconds,
        window_s=args.window_seconds,
        batch_size=args.batch_size,
        seed=args.seed,
    )
    if args.assert_fired_cleared and not report["alarms_fired_and_cleared"]:
        print("FAIL: no alarm both fired and cleared", file=sys.stderr)
        return 2
    missing = [a for a in args.assert_alarm if a not in report["alarms_fired_and_cleared"]]
    if missing:
        print(
            f"FAIL: alarm(s) {missing} did not both fire and clear"
            f" (fired_and_cleared={report['alarms_fired_and_cleared']})",
            file=sys.stderr,
        )
        return 2
    if args.assert_faults_observed:
        totals = report["totals"]
        if not (totals["duplicates"] and totals["late_dropped"]):
            print(
                f"FAIL: expected duplicate AND late-dropped snapshots, saw"
                f" duplicates={totals['duplicates']} late_dropped={totals['late_dropped']}",
                file=sys.stderr,
            )
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
