"""Extended StatScores-family grid vs sklearn: multilabel, multidim-
multiclass (global + samplewise), per-class averages, and top-k — the input
regimes the reference's big classification grids cover
(/root/reference/tests/classification/test_{precision_recall,accuracy}.py)
that the earlier per-metric files here did not."""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import f1_score as sk_f1
from sklearn.metrics import precision_score as sk_precision
from sklearn.metrics import recall_score as sk_recall

import jax.numpy as jnp

from metrics_tpu.classification import Accuracy, F1Score, Precision, Recall
from tests.classification.inputs import (
    _input_multiclass_prob,
    _input_multidim_multiclass_prob,
    _input_multilabel_prob,
)
from tests.helpers.testers import EXTRA_DIM, NUM_CLASSES, THRESHOLD, MetricTester

_SK = {"precision": sk_precision, "recall": sk_recall, "f1": sk_f1}
_CLS = {"precision": Precision, "recall": Recall, "f1": F1Score}


# ---------------------------------------------------------------------------
# multilabel
# ---------------------------------------------------------------------------


def _sk_multilabel(preds, target, metric, average):
    preds = (np.asarray(preds) >= THRESHOLD).astype(int).reshape(-1, NUM_CLASSES)
    target = np.asarray(target).reshape(-1, NUM_CLASSES)
    avg = None if average == "none" else average
    return _SK[metric](target, preds, average=avg, zero_division=0)


@pytest.mark.parametrize("metric", ["precision", "recall", "f1"])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
# NOTE: integer (N, C) inputs deduce as multi-dim multi-class, not
# multilabel (reference deduction table, pinned in test_inputs.py), so only
# the probability fixture exercises the multilabel path here.
@pytest.mark.parametrize(
    "preds, target",
    [(_input_multilabel_prob.preds, _input_multilabel_prob.target)],
    ids=["prob"],
)
class TestMultilabelGrid(MetricTester):
    atol = 1e-6

    def test_class(self, preds, target, metric, average):
        self.run_class_metric_test(
            preds=preds,
            target=target,
            metric_class=_CLS[metric],
            sk_metric=partial(_sk_multilabel, metric=metric, average=average),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )


# ---------------------------------------------------------------------------
# multidim multiclass: global vs samplewise mdmc averaging
# ---------------------------------------------------------------------------


def _sk_mdmc(preds, target, metric, average, mdmc_average):
    preds = np.asarray(preds)
    target = np.asarray(target)
    top1 = np.argmax(preds, axis=-2)  # class axis is -2 for [N, C, X]
    avg = None if average == "none" else average
    labels = np.arange(NUM_CLASSES)
    if mdmc_average == "global":
        return _SK[metric](target.reshape(-1), top1.reshape(-1), average=avg, labels=labels, zero_division=0)
    values = [
        _SK[metric](t.reshape(-1), p.reshape(-1), average=avg, labels=labels, zero_division=0)
        for p, t in zip(top1, target)
    ]
    return np.mean(values, axis=0)


@pytest.mark.parametrize("metric", ["precision", "recall", "f1"])
@pytest.mark.parametrize("average", ["micro", "macro"])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
class TestMdmcGrid(MetricTester):
    atol = 1e-6

    def test_class(self, metric, average, mdmc_average):
        self.run_class_metric_test(
            preds=_input_multidim_multiclass_prob.preds,
            target=_input_multidim_multiclass_prob.target,
            metric_class=_CLS[metric],
            sk_metric=partial(_sk_mdmc, metric=metric, average=average, mdmc_average=mdmc_average),
            metric_args={
                "average": average,
                "num_classes": NUM_CLASSES,
                "mdmc_average": mdmc_average,
            },
        )


# ---------------------------------------------------------------------------
# per-class output + top-k accuracy
# ---------------------------------------------------------------------------


def test_average_none_returns_per_class():
    preds = jnp.asarray(_input_multiclass_prob.preds[0])
    target = jnp.asarray(_input_multiclass_prob.target[0])
    metric = Precision(average="none", num_classes=NUM_CLASSES)
    out = np.asarray(metric(preds, target))
    want = sk_precision(
        np.asarray(target), np.argmax(np.asarray(preds), axis=1),
        average=None, labels=np.arange(NUM_CLASSES), zero_division=0,
    )
    assert out.shape == (NUM_CLASSES,)
    np.testing.assert_allclose(out, want, atol=1e-6)


@pytest.mark.parametrize("top_k", [1, 2, 3])
def test_topk_accuracy_vs_manual(top_k):
    preds = np.asarray(_input_multiclass_prob.preds[0])
    target = np.asarray(_input_multiclass_prob.target[0])
    metric = Accuracy(top_k=top_k)
    got = float(metric(jnp.asarray(preds), jnp.asarray(target)))
    topk_sets = np.argsort(-preds, axis=1)[:, :top_k]
    want = float(np.mean([t in row for t, row in zip(target, topk_sets)]))
    np.testing.assert_allclose(got, want, atol=1e-6)
