"""Reference-vs-live distribution drift: PSI / KL / JS / total variation
over sketch histograms and categorical count leaves.

The windowed layer answers "what is the metric now"
(:mod:`metrics_tpu.windowed`); this module answers "is *now* still the
same distribution as *then*" — the online-evaluation question that fires
before any accuracy metric moves. Everything reduces to fixed-shape
histogram arithmetic:

* a **quantile-sketch window** (a ``TelemetrySeries.window_sketch`` fold,
  or a ``WindowedMetric`` ring row's merge leaf) histograms over SHARED
  STATIC edges via :func:`~metrics_tpu.sketches.quantile.
  qsketch_histogram` — one fixed-shape, jit-clean op per side;
* a **categorical count leaf** (a confusion matrix, per-class totals —
  any sum-reduced non-negative array) is already a histogram after
  flattening.

Normalized histograms then compare through the standard scores:

========  ============================================================
``psi``   Population Stability Index ``sum((p-q) * ln(p/q))`` — the
          industry drift score; > 0.1 is "investigate", > 0.25 "act".
``kl``    ``KL(live || reference)`` in nats — asymmetric, unbounded.
``js``    Jensen–Shannon divergence — symmetric, bounded by ``ln 2``.
``tv``    Total variation ``0.5 * sum(|p-q|)`` — bounded by 1; the
          natural score for categorical (confusion-matrix) leaves.
========  ============================================================

Histograms are epsilon-smoothed before normalizing, so a bin empty on one
side contributes a large-but-finite term instead of ``inf`` — drift
scores must rank severity, not overflow. The :class:`~metrics_tpu.
observability.health.DriftRule` turns these scores into the seventh
standard alarm class; see docs/windowed_metrics.md for the score
reference table (and for when drift is NOT a regression).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

__all__ = [
    "DRIFT_STATS",
    "categorical_drift",
    "histogram_drift",
    "js_divergence_hist",
    "kl_divergence_hist",
    "normalize_histogram",
    "psi_divergence",
    "reference_edges",
    "sketch_drift",
    "state_drift",
    "total_variation",
]

#: the drift statistics every comparator in this module reports
DRIFT_STATS = ("psi", "kl", "js", "tv")

#: RELATIVE smoothing mass per bin (added after normalizing) — the
#: standard PSI zero-bin floor. Absolute-count smoothing would scale the
#: floor with the histogram's total weight, making an empty bin's
#: log-ratio explode for well-sampled references and vanish for tiny ones;
#: a relative floor bounds every per-bin log term by ``ln(1/eps)``
#: regardless of sample counts, so scores rank severity instead of
#: measuring how many samples happened to be in the window.
DRIFT_EPS = 1e-4


def normalize_histogram(hist: Any, eps: float = DRIFT_EPS) -> jnp.ndarray:
    """Flatten, clip negatives (defensive: counts are non-negative by
    contract), normalize to a probability vector, then floor every bin at
    ``eps`` relative mass (renormalized). An all-zero histogram reads as
    uniform — two empty sides compare as identical, not as NaN."""
    h = jnp.clip(jnp.asarray(hist, jnp.float32).ravel(), 0.0, None)
    total = jnp.sum(h)
    p = jnp.where(total > 0, h / jnp.clip(total, 1e-30, None), 1.0 / h.shape[0])
    p = p + eps
    return p / jnp.sum(p)


def psi_divergence(p: Any, q: Any, eps: float = DRIFT_EPS) -> float:
    """Population Stability Index between two (un)normalized histograms."""
    p, q = normalize_histogram(p, eps), normalize_histogram(q, eps)
    return float(jnp.sum((p - q) * jnp.log(p / q)))


def kl_divergence_hist(p: Any, q: Any, eps: float = DRIFT_EPS) -> float:
    """``KL(p || q)`` in nats between two (un)normalized histograms."""
    p, q = normalize_histogram(p, eps), normalize_histogram(q, eps)
    return float(jnp.sum(p * jnp.log(p / q)))


def js_divergence_hist(p: Any, q: Any, eps: float = DRIFT_EPS) -> float:
    """Jensen–Shannon divergence (symmetric, ``<= ln 2``)."""
    p, q = normalize_histogram(p, eps), normalize_histogram(q, eps)
    m = (p + q) / 2.0
    return float(0.5 * jnp.sum(p * jnp.log(p / m)) + 0.5 * jnp.sum(q * jnp.log(q / m)))


def total_variation(p: Any, q: Any, eps: float = DRIFT_EPS) -> float:
    """Total variation distance ``0.5 * sum(|p - q|)`` (``<= 1``)."""
    p, q = normalize_histogram(p, eps), normalize_histogram(q, eps)
    return float(0.5 * jnp.sum(jnp.abs(p - q)))


def reference_edges(sketch: Any, n_bins: int = 16, pad_frac: float = 0.01) -> np.ndarray:
    """Static histogram edges spanning a reference sketch's occupied keys.

    Derived ONCE at reference-freeze time and then shared by every
    comparison — shared static edges are what keep the live-side
    ``qsketch_histogram`` a fixed-shape op (and the scores comparable
    across evaluations). The span is padded by ``pad_frac`` so live mass
    drifting slightly past the reference extremes still lands in the edge
    bins rather than all clamping into one."""
    if not isinstance(n_bins, int) or n_bins < 2:
        raise ValueError(f"`n_bins` must be an int >= 2, got {n_bins!r}")
    arr = np.asarray(sketch)
    occ = arr[arr[:, 0] > 0]
    if occ.size == 0:
        raise ValueError("cannot derive edges from an empty sketch (total weight 0)")
    lo, hi = float(occ[:, 1].min()), float(occ[:, 1].max())
    span = max(hi - lo, 1e-6)
    return np.linspace(lo - pad_frac * span, hi + pad_frac * span, n_bins + 1)


def sketch_drift(reference: Any, live: Any, edges: Any) -> Dict[str, float]:
    """All four drift scores between two quantile sketches histogrammed
    over shared static ``edges`` (reference first: ``kl`` reads as
    ``KL(live || reference)``, the "how surprised is the reference model
    by live traffic" direction)."""
    from metrics_tpu.sketches.quantile import qsketch_histogram

    edges = jnp.asarray(edges, jnp.float32)
    ref_hist = qsketch_histogram(jnp.asarray(reference), edges)
    live_hist = qsketch_histogram(jnp.asarray(live), edges)
    return histogram_drift(ref_hist, live_hist)


def histogram_drift(ref_hist: Any, live_hist: Any) -> Dict[str, float]:
    """All four drift scores between two pre-binned histograms. PSI, JS,
    and TV are symmetric; ``kl`` is oriented ``KL(live || reference)``.

    One normalization per side and one fused dispatch chain serve all
    four scores — this runs on every monitor tick per drift rule, so the
    per-score public functions (which re-normalize) are not called here.
    """
    p = normalize_histogram(ref_hist)  # reference
    q = normalize_histogram(live_hist)  # live
    log_pq = jnp.log(p / q)
    m = (p + q) / 2.0
    scores = jnp.stack(
        [
            jnp.sum((p - q) * log_pq),  # psi (symmetric)
            jnp.sum(q * -log_pq),  # KL(live || reference)
            0.5 * jnp.sum(p * jnp.log(p / m)) + 0.5 * jnp.sum(q * jnp.log(q / m)),  # js
            0.5 * jnp.sum(jnp.abs(p - q)),  # tv
        ]
    )
    host = [float(v) for v in np.asarray(scores)]
    return dict(zip(DRIFT_STATS, host))


def categorical_drift(ref_counts: Any, live_counts: Any) -> Dict[str, float]:
    """Drift scores between two categorical count leaves (confusion
    matrices, per-class totals): the flattened counts ARE the histograms.
    ``tv`` is the headline score here — bounded, symmetric, and exactly
    the fraction of probability mass that moved between cells."""
    ref = jnp.asarray(ref_counts, jnp.float32)
    live = jnp.asarray(live_counts, jnp.float32)
    if ref.shape != live.shape:
        # compared BEFORE ravel: a transposed leaf has the same size but
        # misaligned cells, and scoring it would read pure layout skew as
        # drift
        raise ValueError(
            f"categorical drift needs same-shaped count leaves, got"
            f" {tuple(ref.shape)} vs {tuple(live.shape)}"
        )
    return histogram_drift(ref.ravel(), live.ravel())


def state_drift(
    metric: Any,
    reference_state: Dict[str, Any],
    live_state: Dict[str, Any],
    edges: Optional[Any] = None,
    n_bins: int = 16,
) -> Dict[str, Dict[str, float]]:
    """Per-leaf drift between two window folds of the same metric — e.g.
    ``WindowedMetric.window_state(w, before=w)`` (reference) vs
    ``.window_state(w)`` (live).

    Sketch (``merge``-reduced) leaves compare via :func:`sketch_drift`
    over shared edges (derived from the reference leaf when ``edges`` is
    not given); multi-element sum-reduced count leaves (confusion-matrix
    shape) via :func:`categorical_drift`. Scalar leaves have no
    distribution and are skipped — compare their computed values directly.
    """
    from metrics_tpu.utils.data import dim_zero_sum

    out: Dict[str, Dict[str, float]] = {}
    for name, red in metric._reductions.items():
        if name not in reference_state or name not in live_state:
            continue
        ref, live = reference_state[name], live_state[name]
        # sum-shaped covers both a bare metric's dim_zero_sum leaves and a
        # WindowedMetric's tagged ring/decay sum reducers — window folds
        # are template-shaped either way, so passing the wrapper itself
        # must not silently skip its categorical leaves
        sum_shaped = red is dim_zero_sum or getattr(red, "inner_reduce", None) == "sum"
        if getattr(red, "merge_like", False):
            if getattr(red, "sketch_kind", "quantile") != "quantile":
                # reservoir/rank leaves pack [priority, payload...] rows —
                # column 0 is a Gumbel PRIORITY, not a weight, and reading
                # it as one scores identical distributions as drifted
                continue
            ref_arr = np.asarray(ref)
            if ref_arr.ndim != 2 or not (ref_arr[:, 0] > 0).any():
                continue  # empty reference window: nothing to anchor on
            leaf_edges = edges if edges is not None else reference_edges(ref_arr, n_bins=n_bins)
            out[name] = sketch_drift(ref, live, leaf_edges)
        elif sum_shaped and getattr(jnp.asarray(ref), "size", 1) > 1:
            out[name] = categorical_drift(ref, live)
    return out
