"""Modular SNR / SI-SNR.

Behavior parity with /root/reference/torchmetrics/audio/snr.py:22-173.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.snr import scale_invariant_signal_noise_ratio, signal_noise_ratio

Array = jax.Array


class SignalNoiseRatio(Metric):
    """Mean signal-to-noise ratio over all seen signals, in dB.

    Args:
        zero_mean: subtract the time-axis mean from both signals first.

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> snr(preds, target)
        Array(16.180481, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds, target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def _compute(self) -> Array:
        return self.sum_snr / self.total


class ScaleInvariantSignalNoiseRatio(Metric):
    """Mean scale-invariant SNR over all seen signals, in dB.

    Example:
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> si_snr(preds, target)
        Array(15.091757, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def _update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds, target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def _compute(self) -> Array:
        return self.sum_si_snr / self.total
