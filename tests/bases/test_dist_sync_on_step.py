"""Per-step sync semantics across EVERY domain.

The reference parametrizes each domain tester over ddp x dist_sync_on_step
(/root/reference/tests/helpers/testers.py:392-470): with per-step sync, the
step value is the metric computed over ALL ranks' current batches. Here each
domain's representative metrics run that contract through the pure state API
(the same merge path a mesh all_gather feeds): every virtual rank
accumulates its own batch, the rank states merge, and the merged compute
must equal a single-process metric fed all ranks' batches — for sum states,
cat/list states, gathered-not-reduced detection states, and string-consuming
text states alike. The accumulated (post-epoch) value must also be
unaffected by having computed per-step values along the way.
"""
import functools

import numpy as np
import pytest

import jax.numpy as jnp

RANKS = 2
STEPS = 2

_rng = np.random.default_rng(77)


def _cls_batches():
    return [
        (
            jnp.asarray(_rng.random((16, 4)).astype(np.float32)),
            jnp.asarray(_rng.integers(0, 4, 16)),
        )
        for _ in range(RANKS * STEPS)
    ]


def _reg_batches():
    return [
        (
            jnp.asarray(_rng.random(24).astype(np.float32)),
            jnp.asarray(_rng.random(24).astype(np.float32)),
        )
        for _ in range(RANKS * STEPS)
    ]


def _img_batches():
    a = _rng.random((RANKS * STEPS, 2, 3, 24, 24)).astype(np.float32)
    b = np.clip(a + 0.1 * _rng.standard_normal(a.shape).astype(np.float32), 0, 1)
    return [(jnp.asarray(x), jnp.asarray(y)) for x, y in zip(a, b)]


def _audio_batches():
    return [
        (
            jnp.asarray(_rng.standard_normal((2, 1200)).astype(np.float32)),
            jnp.asarray(_rng.standard_normal((2, 1200)).astype(np.float32)),
        )
        for _ in range(RANKS * STEPS)
    ]


def _text_batches():
    corpus = [
        (["the cat sat on the mat", "hello world"], ["the cat sat on a mat", "hello there world"]),
        (["a quick brown fox", "jumps over dogs"], ["the quick brown fox", "jumps over the dog"]),
        (["to be or not to be", "that is the question"], ["to be or to be", "this is a question"]),
        (["all good things", "come to an end"], ["all bad things", "came to the end"]),
    ]
    return corpus[: RANKS * STEPS]


def _retrieval_batches():
    out = []
    for _ in range(RANKS * STEPS):
        idx = np.repeat(np.arange(3), 5)
        preds = _rng.random(15).astype(np.float32)
        target = (_rng.random(15) < 0.4).astype(np.int64)
        target[::5] = 1  # every query keeps a positive
        out.append(
            ((jnp.asarray(preds), jnp.asarray(target)), {"indexes": jnp.asarray(idx)})
        )
    return out


def _det_batches():
    def boxes(n):
        x1 = _rng.uniform(0, 60, n).astype(np.float32)
        y1 = _rng.uniform(0, 60, n).astype(np.float32)
        w = _rng.uniform(4, 30, n).astype(np.float32)
        h = _rng.uniform(4, 30, n).astype(np.float32)
        return np.stack([x1, y1, x1 + w, y1 + h], 1)

    out = []
    for _ in range(RANKS * STEPS):
        preds = [
            dict(
                boxes=boxes(5),
                scores=_rng.random(5).astype(np.float32),
                labels=_rng.integers(0, 3, 5).astype(np.int64),
            )
        ]
        target = [dict(boxes=boxes(3), labels=_rng.integers(0, 3, 3).astype(np.int64))]
        out.append(((preds, target), {}))
    return out


def _normalize(batches):
    return [(b, {}) if not (isinstance(b, tuple) and len(b) == 2 and isinstance(b[1], dict)) else b for b in batches]


def _make_cases():
    from metrics_tpu.audio import ScaleInvariantSignalNoiseRatio, SignalNoiseRatio
    from metrics_tpu.classification import Accuracy, ConfusionMatrix, F1Score
    from metrics_tpu.detection import MeanAveragePrecision
    from metrics_tpu.image import PeakSignalNoiseRatio, StructuralSimilarityIndexMeasure
    from metrics_tpu.regression import MeanAbsoluteError, MeanSquaredError, PearsonCorrCoef
    from metrics_tpu.retrieval import RetrievalMAP, RetrievalNormalizedDCG
    from metrics_tpu.text import BLEUScore, CharErrorRate, WordErrorRate

    cls_b = [(b, {}) for b in _cls_batches()]
    reg_b = [(b, {}) for b in _reg_batches()]
    img_b = [(b, {}) for b in _img_batches()]
    aud_b = [(b, {}) for b in _audio_batches()]
    txt_b = [(b, {}) for b in _text_batches()]
    return [
        ("classification-Accuracy", lambda: Accuracy(num_classes=4), cls_b, 1e-6),
        ("classification-F1-macro", lambda: F1Score(num_classes=4, average="macro"), cls_b, 1e-6),
        ("classification-ConfusionMatrix", lambda: ConfusionMatrix(num_classes=4), cls_b, 1e-6),
        ("regression-MSE", MeanSquaredError, reg_b, 1e-6),
        ("regression-MAE", MeanAbsoluteError, reg_b, 1e-6),
        ("regression-Pearson", PearsonCorrCoef, reg_b, 1e-5),
        ("image-PSNR", lambda: PeakSignalNoiseRatio(data_range=1.0), img_b, 1e-5),
        (
            "image-SSIM",
            lambda: StructuralSimilarityIndexMeasure(data_range=1.0),
            img_b,
            1e-5,
        ),
        ("audio-SNR", SignalNoiseRatio, aud_b, 1e-5),
        ("audio-SI-SNR", ScaleInvariantSignalNoiseRatio, aud_b, 1e-5),
        ("text-WER", WordErrorRate, txt_b, 1e-6),
        ("text-CER", CharErrorRate, txt_b, 1e-6),
        ("text-BLEU", BLEUScore, [((p, [[t] for t in ts]), {}) for (p, ts) in _text_batches()], 1e-6),
        ("retrieval-MAP", RetrievalMAP, _retrieval_batches(), 1e-6),
        ("retrieval-NDCG", RetrievalNormalizedDCG, _retrieval_batches(), 1e-6),
        (
            "detection-mAP",
            lambda: MeanAveragePrecision(iou_thresholds=[0.5]),
            _det_batches(),
            1e-6,
        ),
    ]


_CASES = _make_cases()


@pytest.mark.parametrize("name, ctor, batches, atol", _CASES, ids=[c[0] for c in _CASES])
def test_dist_sync_on_step_semantics(name, ctor, batches, atol):
    """Each step: RANKS ranks update fresh states with their own batch, the
    merged cross-rank compute must equal a single-process metric fed the
    same batches (the reference's ddp+dist_sync_on_step step contract)."""
    m = ctor()
    for step in range(STEPS):
        step_batches = batches[step * RANKS : (step + 1) * RANKS]
        rank_states = [
            m.update_state(m.init_state(), *args, **kwargs) for args, kwargs in step_batches
        ]
        synced = functools.reduce(m.merge_states, rank_states)
        step_val = m.compute_state(synced)

        oracle = ctor()
        for args, kwargs in step_batches:
            oracle.update(*args, **kwargs)
        _assert_close(step_val, oracle.compute(), atol, f"{name} step {step}")


@pytest.mark.parametrize("name, ctor, batches, atol", _CASES, ids=[c[0] for c in _CASES])
def test_epoch_accumulation_matches_across_rank_split(name, ctor, batches, atol):
    """The post-epoch value from rank-wise accumulation + one final merge
    equals single-process accumulation over all batches (the
    dist_sync_on_step=False column of the reference grid)."""
    m = ctor()
    rank_states = []
    for rank in range(RANKS):
        state = m.init_state()
        for step in range(STEPS):
            args, kwargs = batches[step * RANKS + rank]
            state = m.update_state(state, *args, **kwargs)
        rank_states.append(state)
    merged = functools.reduce(m.merge_states, rank_states)
    merged_val = m.compute_state(merged)

    oracle = ctor()
    for args, kwargs in batches:
        oracle.update(*args, **kwargs)
    _assert_close(merged_val, oracle.compute(), atol, name)


def _assert_close(got, want, atol, msg):
    if isinstance(got, dict):
        for k in got:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want[k]), atol=atol, rtol=1e-5, err_msg=f"{msg}:{k}"
            )
    elif isinstance(got, (list, tuple)):
        for g, w in zip(got, want):
            _assert_close(g, w, atol, msg)
    else:
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=atol, rtol=1e-5, err_msg=msg)
