"""Edit-distance family (WER/CER/MER/WIL/WIP) parity.

Oracle: the reference implementation imported from /root/reference (jiwer,
the reference's usual oracle, is not installed in this environment — same
substitution tests/detection/test_map.py makes with pycocotools).
"""
from functools import partial

import pytest

from metrics_tpu.functional.text import (
    char_error_rate,
    match_error_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from metrics_tpu.text import CharErrorRate, MatchErrorRate, WordErrorRate, WordInfoLost, WordInfoPreserved
from tests.helpers.reference import load_reference_module
from tests.text.helpers import TextTester
from tests.text.inputs import _inputs_error_rate_batch_size_1, _inputs_error_rate_batch_size_2


def _reference_oracle(preds, targets, module, func):
    ref = load_reference_module(f"torchmetrics.functional.text.{module}")
    return getattr(ref, func)(preds, targets).item()


CASES = [
    ("wer", "word_error_rate", WordErrorRate, word_error_rate),
    ("cer", "char_error_rate", CharErrorRate, char_error_rate),
    ("mer", "match_error_rate", MatchErrorRate, match_error_rate),
    ("wil", "word_information_lost", WordInfoLost, word_information_lost),
    ("wip", "word_information_preserved", WordInfoPreserved, word_information_preserved),
]


@pytest.mark.parametrize(
    ["preds", "targets"],
    [
        (_inputs_error_rate_batch_size_1.preds, _inputs_error_rate_batch_size_1.targets),
        (_inputs_error_rate_batch_size_2.preds, _inputs_error_rate_batch_size_2.targets),
    ],
)
@pytest.mark.parametrize(["module", "func", "metric_class", "metric_functional"], CASES)
class TestErrorRates(TextTester):
    atol = 1e-6

    def test_class(self, preds, targets, module, func, metric_class, metric_functional):
        self.run_class_metric_test(
            preds=preds,
            targets=targets,
            metric_class=metric_class,
            sk_metric=partial(_reference_oracle, module=module, func=func),
        )

    def test_functional(self, preds, targets, module, func, metric_class, metric_functional):
        self.run_functional_metric_test(
            preds=preds,
            targets=targets,
            metric_functional=metric_functional,
            sk_metric=partial(_reference_oracle, module=module, func=func),
        )


def test_wer_accepts_single_string():
    assert float(word_error_rate("hello world", "hello world")) == 0.0
    metric = WordErrorRate()
    metric.update("hello there", "hello world")
    assert float(metric.compute()) == 0.5
