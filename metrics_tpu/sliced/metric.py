"""``SlicedMetric`` — one metric, a leading ``[S]`` slice axis on every state.

Where ``ClasswiseWrapper`` fans out to N metric objects (N states, N
dispatches per batch), a sliced metric keeps ONE state pytree whose every
leaf carries a leading slice dimension, and one ``update(slice_ids, *batch)``
scatters each batch row's contribution into its slice with a single
``segment_sum`` / ``segment_max`` / ``segment_min`` per leaf:

* **Per-row contributions** come from the wrapped metric's own pure update
  (``update_state``) vmapped over the batch rows against the default state —
  no per-slice Python dispatch, no [S, B] blow-up; cost is O(B) kernel work
  plus one O(B -> S) segment reduction per leaf.
* **Reducer-consistent scatter** — a ``"sum"``-reduced leaf accumulates the
  segment-summed per-row deltas additively; ``"max"``/``"min"`` leaves
  combine through the matching extremum, so an untouched slice is left
  bit-identical (empty segments fill with the reduction's identity). Leaves
  with any other reducer (``mean``/``cat``/custom/None, list states) have no
  exact scatter and are rejected at construction with the manifest's
  per-leaf ``sliceable`` verdict in the error.
* **Fused + async by construction** — the update is a pure traceable
  ``(state, batch) -> state`` transform over fixed-shape array states, so
  ``MetricCollection.compile_update()`` fuses it on the ordinary
  single-dispatch path (donation, AOT compile cache, and pad-and-mask shape
  bucketing intact: pad rows replicate the last real row *including its
  slice id*, so the standard ``k * delta(last_row)`` sum correction is exact
  per slice) and ``compile_update_async()``'s worker dispatches it without
  changes.
* **Sharding** — every leaf's leading ``[S]`` axis is the natural partition
  axis; :mod:`metrics_tpu.sliced.sharding` maps state-leaf paths to
  ``PartitionSpec``s and ``sync_pytree_in_mesh(partition_specs=...)`` skips
  the collective entirely for slice-sharded leaves (each mesh position owns
  disjoint slices — zero cross-host traffic).

Slice-id contract: ``slice_ids`` is a 1-D integer array aligned with the
batch's leading axis; ids outside ``[0, num_slices)`` follow XLA scatter
semantics and are silently dropped. The auto-registered ``_slice_rows``
counter tracks rows (not batches) per slice and powers top-k-by-count
``compute`` selection. See docs/sliced_metrics.md.
"""
from __future__ import annotations

import time
import weakref
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import _AUTO_COUNT, Metric
from metrics_tpu.core.readers import ReaderCache, pad_ids, round_up_bucket
from metrics_tpu.observability.memory import register_cache_plane
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY

# the single source of the prefix: the recorder owns it (it splits the
# footprint HWM on it), this module re-exports it for producers/users
from metrics_tpu.observability.recorder import SLICED_FOOTPRINT_PREFIX
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.data import dim_zero_max, dim_zero_min, dim_zero_sum
from metrics_tpu.utils.exceptions import MetricsUserError

Array = jax.Array

#: per-slice row counter: sum-reduced ``[S]`` int32 state every SlicedMetric
#: registers alongside the wrapped leaves (top-k-by-count selection, merge
#: weighting, scatter accounting)
SLICE_ROWS = "_slice_rows"

#: reducers with an exact slice-axis scatter (segment_sum / segment_max /
#: segment_min); everything else is rejected at construction
_SLICEABLE = {dim_zero_sum: "sum", dim_zero_max: "max", dim_zero_min: "min"}


def _reducer_name(red: Any) -> str:
    if red is None:
        return "None"
    return _SLICEABLE.get(red) or getattr(red, "__name__", repr(red))


#: every live SlicedMetric (weak); the ``sliced_value_cache`` memory plane
#: sums the host-side per-slice value cache + dirty bitmap over this set —
#: host bytes that scale with S and would otherwise be invisible to both
#: the device ledger and ``state_footprint()``
_LIVE_SLICED: "weakref.WeakSet" = weakref.WeakSet()


def _svc_plane_nbytes() -> int:
    total = 0
    for m in list(_LIVE_SLICED):
        dirty = getattr(m, "_dirty", None)
        if dirty is not None:
            total += int(dirty.nbytes)
        svc = getattr(m, "_svc", None)
        if svc is not None:
            total += int(
                sum(
                    getattr(leaf, "nbytes", 0) or 0
                    for leaf in jax.tree_util.tree_leaves(svc)
                )
            )
    return total


register_cache_plane("sliced_value_cache", _svc_plane_nbytes)


class SlicedMetric(Metric):
    """Track ``metric`` independently across ``num_slices`` slices.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> from metrics_tpu.sliced import SlicedMetric
        >>> per_tenant = SlicedMetric(MeanSquaredError(), num_slices=3)
        >>> per_tenant.update(jnp.array([0, 1, 2, 2]),  # slice ids, row-aligned
        ...                   jnp.array([1.0, 2.0, 2.0, 4.0]),   # preds
        ...                   jnp.array([1.0, 0.0, 0.0, 0.0]))   # target
        >>> per_tenant.compute()  # [S]-leading: one value per slice
        Array([ 0.,  4., 10.], dtype=float32)

    ``update(slice_ids, *args, **kwargs)`` forwards ``*args``/``kwargs`` to
    the wrapped metric row by row; ``compute()`` vmaps the wrapped compute
    over the slice axis. ``compute(slice_ids=...)`` evaluates a subset and
    ``compute(top_k=k)`` returns ``(slice_ids, values)`` for the ``k``
    slices with the most ingested rows. Reset / merge_states / state_dict /
    sync all ride the ordinary :class:`Metric` machinery — the states are
    plain array leaves with the wrapped reducers applied elementwise per
    slice.
    """

    higher_is_better = None
    is_differentiable = False

    def __init__(self, metric: Metric, num_slices: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise MetricsUserError(
                f"SlicedMetric wraps a Metric instance, got {type(metric).__name__}"
            )
        if isinstance(metric, SlicedMetric):
            raise MetricsUserError("SlicedMetric cannot wrap another SlicedMetric")
        if not isinstance(num_slices, int) or num_slices <= 0:
            raise MetricsUserError(f"`num_slices` must be a positive int, got {num_slices!r}")
        self._validate_sliceable(metric)
        self.num_slices = num_slices
        # the wrapped metric is a TEMPLATE: its pure update/compute transforms
        # run per row / per slice, its own (reset) states are never read as
        # accumulation. Stored via object.__setattr__ so it does NOT register
        # as a child metric — a child registry would mark this class a
        # wrapper and statically exclude it from the fused path, and the
        # template's placeholder states would double-count in footprints.
        object.__setattr__(self, "_template", metric.clone())
        self._template.reset()
        for name, red in self._template._reductions.items():
            default = jnp.asarray(self._template._defaults[name])
            self.add_state(
                name,
                default=jnp.broadcast_to(default, (num_slices,) + default.shape),
                dist_reduce_fx=red,
            )
        self.add_state(SLICE_ROWS, default=jnp.zeros(num_slices, jnp.int32), dist_reduce_fx="sum")
        # --- incremental read plane (host-side, never traced) ----------
        # dirty set: True where a slice was written since the per-slice
        # value cache last folded it. Eager updates mark exactly the
        # scattered concrete ids; traced ids (fused/async applies, jit)
        # and every out-of-band install degrade to all-dirty — never
        # wrong, at worst a full fold. Starts all-dirty (nothing cached).
        self._dirty = np.ones(num_slices, dtype=bool)
        # per-slice value cache: host pytree of [S]-leading arrays, shaped
        # lazily from the first fold; a slice's entry is trusted iff its
        # dirty bit is clear
        self._svc: Optional[Any] = None
        # pre-lowered subset-gather / top-k executables (core/readers.py)
        self._readers = ReaderCache()
        _LIVE_SLICED.add(self)

    # ------------------------------------------------------------------
    # construction-time sliceability validation
    # ------------------------------------------------------------------
    @staticmethod
    def _validate_sliceable(metric: Metric) -> None:
        """Reject metrics without an exact per-leaf scatter, with the
        tracelint manifest's machine-derived reason when one exists —
        mis-scattering (e.g. segment-summing a running mean) would corrupt
        every touched slice silently."""
        cls_name = type(metric).__name__
        if getattr(metric, "__jit_unsafe__", False):
            raise MetricsUserError(
                f"`{cls_name}` declares `__jit_unsafe__` — its update cannot trace, so it"
                " cannot run inside the sliced scatter kernel. Use object fan-out"
                " (e.g. ClasswiseWrapper) for jit-unsafe metrics."
            )
        if metric._children:
            raise MetricsUserError(
                f"`{cls_name}` is a wrapper metric (child registry"
                f" {sorted(dict(metric._iter_child_metrics()))}); slice the inner"
                " metric directly instead of the wrapper."
            )
        static = metric.static_sliceability() or {}
        for name, red in metric._reductions.items():
            default = metric._defaults[name]
            if isinstance(default, list):
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` is a list ('cat') state; unbounded"
                    " concatenation has no fixed-shape slice axis. Sliceable leaves"
                    " need a sum/max/min reducer over an array state."
                )
            if name == SLICE_ROWS:
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` collides with the reserved sliced"
                    " row-counter state name"
                )
            if red not in _SLICEABLE:
                hint = ""
                if name == _AUTO_COUNT:
                    # only present alongside a mean-reduced leaf, which is
                    # rejected on its own below/above — but name it clearly
                    # if a custom metric registered the counter directly
                    hint = " (the auto mean-merge counter has no per-slice scatter)"
                elif static.get(name) is False:
                    hint = " (the fusibility manifest's per-leaf `sliceable` verdict agrees)"
                raise MetricsUserError(
                    f"`{cls_name}` state `{name}` has reducer"
                    f" `{_reducer_name(red)}`; only sum/max/min-reduced array states"
                    " have an exact slice-axis scatter (segment_sum / scatter-max /"
                    f" scatter-min){hint}. A mean-style metric should accumulate"
                    " sum-reduced numerator/denominator leaves (see MeanMetric)."
                )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def wrapped(self) -> Metric:
        """The wrapped template metric (its states are placeholders)."""
        return self._template

    @property
    def slice_counts(self) -> Array:
        """Rows ingested per slice, ``[S]`` int32."""
        return jnp.asarray(getattr(self, SLICE_ROWS))

    def _row_states(self, args: Tuple, kwargs: Dict[str, Any], n_rows: int) -> Dict[str, Array]:
        """Per-row post-update states ``{leaf: [B, *leaf_shape]}``: the
        wrapped metric's pure update vmapped over single-row batches against
        the default state. Leaves whose leading axis matches the slice-id
        length are treated as batched; everything else is closed over."""
        m = self._template
        defaults = {k: jnp.asarray(v) for k, v in m._defaults.items()}
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        batched = [
            i
            for i, leaf in enumerate(leaves)
            if isinstance(leaf, (jnp.ndarray, np.ndarray))
            and getattr(leaf, "ndim", 0) >= 1
            and leaf.shape[0] == n_rows
        ]
        if not batched:
            raise MetricsUserError(
                "SlicedMetric.update: no batch argument shares the slice_ids"
                f" leading dimension ({n_rows}); slice ids must be row-aligned"
                " with the update inputs"
            )
        # rows keep a length-1 batch axis so the wrapped update sees an
        # ordinary (1, ...) batch — the same shape contract the fused pad
        # correction uses for its single-row delta
        rows = [jnp.asarray(leaves[i])[:, None] for i in batched]

        def one_row(*row_leaves: Array) -> Dict[str, Array]:
            full = list(leaves)
            for i, r in zip(batched, row_leaves):
                full[i] = r
            a, kw = jax.tree_util.tree_unflatten(treedef, full)
            return m.update_state(dict(defaults), *a, **kw)

        return jax.vmap(one_row)(*rows)

    def _update(self, slice_ids: Array, *args: Any, **kwargs: Any) -> None:
        slice_ids = jnp.asarray(slice_ids)
        if slice_ids.ndim != 1:
            raise MetricsUserError(
                f"`slice_ids` must be a 1-D integer array, got shape {slice_ids.shape}"
            )
        if not jnp.issubdtype(slice_ids.dtype, jnp.integer):
            raise MetricsUserError(
                f"`slice_ids` must be integer-typed, got dtype {slice_ids.dtype}"
            )
        m = self._template
        n_rows = int(slice_ids.shape[0])
        num = self.num_slices
        row_states = self._row_states(args, m._filter_kwargs(**kwargs), n_rows)
        defaults = {k: jnp.asarray(v) for k, v in m._defaults.items()}
        # per-leaf scatters route through the ops kernel registry: the tiled
        # one-hot MXU segment-sum kernel on TPU where the route predicts a
        # win, jax.ops.segment_* elsewhere (CPU states stay bit-identical)
        from metrics_tpu.ops import (
            segment_max_dispatch,
            segment_min_dispatch,
            segment_sum_dispatch,
        )

        for name, red in m._reductions.items():
            rows = row_states[name]
            old = getattr(self, name)
            if red is dim_zero_sum:
                # per-row delta against the default, segment-summed into the
                # slice axis: exact for additive (sum-reduced) accumulation
                new = old + segment_sum_dispatch(rows - defaults[name], slice_ids, num)
            elif red is dim_zero_max:
                # empty segments fill with the dtype's -inf/min — the
                # extremum identity — so untouched slices stay bit-identical
                new = jnp.maximum(old, segment_max_dispatch(rows, slice_ids, num))
            else:  # dim_zero_min (validated at construction)
                new = jnp.minimum(old, segment_min_dispatch(rows, slice_ids, num))
            object.__setattr__(self, name, new)
        counts = getattr(self, SLICE_ROWS)
        object.__setattr__(
            self,
            SLICE_ROWS,
            counts + segment_sum_dispatch(jnp.ones(n_rows, jnp.int32), slice_ids, num),
        )
        # dirty-slice tracking: concrete ids mark exactly the written
        # slices (out-of-range ids are excluded — the scatter DROPS them,
        # so the corresponding slices did not change); traced ids cannot
        # say which slices the kernel will touch, so the whole axis goes
        # dirty — degraded, never wrong
        if _is_concrete(slice_ids):
            written = np.asarray(slice_ids)
            self._dirty[written[(written >= 0) & (written < num)]] = True
        else:
            self._dirty[:] = True
        if _TELEMETRY.enabled:
            # under the fused kernel this records once per TRACE (shapes are
            # static), on the eager path once per update — mirroring the
            # sync-byte accounting convention in parallel/distributed.py
            hot_rows = None
            if _TELEMETRY.timeseries is not None and _is_concrete(slice_ids) and n_rows:
                # hottest-slice row count of THIS batch (eager path only —
                # needs concrete ids): its share of the batch feeds the
                # windowed hot-slice-skew series the health layer alarms on.
                # Gated on an attached registry — the bincount forces a
                # device readback, and counters-only telemetry must not pay
                # it for a series nothing consumes. Out-of-range ids are
                # clipped to match the scatter's drop semantics closely
                # enough for a skew signal.
                binc = np.bincount(
                    np.clip(np.asarray(slice_ids), 0, num - 1).astype(np.int64),
                    minlength=1,
                )
                hot_rows = int(binc.max())
            _TELEMETRY.record_sliced_scatter(
                self,
                n_rows=n_rows,
                n_slices=num,
                n_leaves=len(m._reductions),
                in_jit=isinstance(slice_ids, jax.core.Tracer),
                hot_rows=hot_rows,
            )

    # ------------------------------------------------------------------
    # incremental read plane
    # ------------------------------------------------------------------
    def _mark_state_written(self) -> None:
        # out-of-band installs (reset, restore, checkpoint load, fused
        # apply, group borrow) can't say WHICH slices changed
        super()._mark_state_written()
        dirty = getattr(self, "_dirty", None)
        if dirty is not None:
            dirty[:] = True

    def set_dtype(self, dst_type) -> "Metric":
        # cached per-slice values hold the OLD dtype's bits; a cast fold
        # would mix dtypes in one assembled result
        out = super().set_dtype(dst_type)
        self._dirty[:] = True
        self._svc = None
        # cached reader executables were lowered for the old dtype's leaf
        # signatures; the signature-free fast probe must never see them
        self._readers.clear()
        return out

    def _subset_reader(self, states: Dict[str, Array], ids: Array, bucket: int):
        """Pre-lowered subset fold: gather ``bucket`` slice rows out of the
        full states and vmap the wrapped compute over them."""
        m = self._template
        names = tuple(m._defaults)

        def build():
            def read(state_leaves: Dict[str, Array], idx: Array) -> Any:
                sub = {k: state_leaves[k][idx] for k in names}
                return jax.vmap(m.compute_state)(sub)

            return read

        return self._readers.get("sliced_subset", build, states, ids, bucket=bucket)

    def _fold_slices(self, req: np.ndarray) -> Tuple[Any, int]:
        """Fold the DIRTY subset of ``req`` through the bucketed AOT reader,
        refresh the per-slice value cache, and assemble the requested values
        from it. Returns ``(values, n_folded)``. Bit-parity: cached entries
        were produced by the same vmapped ``compute_state`` program a cold
        full fold runs, so assembly never mixes provenances."""
        m = self._template
        # invariant: a clear dirty bit implies a valid cache entry (bits
        # are cleared only after a fold scattered that slice), so folding
        # exactly the dirty requested ids always leaves `req` assemblable
        fold = np.unique(req[self._dirty[req]])
        n_folded = int(fold.size)
        if n_folded:
            bucket = round_up_bucket(n_folded, self.num_slices)
            # the pre-lowered executable device-puts its arguments itself;
            # eager jnp conversions here would only add dispatch overhead
            # on a sub-millisecond path
            padded = pad_ids(fold, bucket)
            states = {
                k: v if isinstance(v, jax.Array) else jnp.asarray(v)
                for k, v in ((k, getattr(self, k)) for k in m._defaults)
            }
            # state shapes/dtypes are fixed for this instance's lifetime
            # (set_dtype clears the cache), so the signature-free probe is
            # safe and skips per-read leaf hashing
            reader = self._readers.fast("sliced_subset", bucket)
            if reader is None:
                reader = self._subset_reader(states, padded, bucket)
            values = reader(states, padded)
            host_vals = jax.tree_util.tree_map(np.asarray, values)
            if self._svc is None:
                self._svc = jax.tree_util.tree_map(
                    lambda v: np.zeros((self.num_slices,) + v.shape[1:], v.dtype),
                    host_vals,
                )

            def _scatter(cache: np.ndarray, vals: np.ndarray) -> np.ndarray:
                cache[padded] = vals
                return cache

            jax.tree_util.tree_map(_scatter, self._svc, host_vals)
            self._dirty[fold] = False
        return (
            jax.tree_util.tree_map(lambda c: jnp.asarray(c[req]), self._svc),
            n_folded,
        )

    def _compute(self) -> Any:
        m = self._template
        # synced states are the cross-rank reduction, NOT the local
        # accumulation the dirty set and value cache describe — and traced
        # states have no host dirty set at all; both degrade to the plain
        # full fold without touching the cache
        if self._is_synced or not _is_concrete(getattr(self, SLICE_ROWS)):
            states = {k: getattr(self, k) for k in m._defaults}
            return jax.vmap(m.compute_state)(states)
        values, n_folded = self._fold_slices(np.arange(self.num_slices))
        self._last_fold_fanin = n_folded
        return values

    def _read_extras(self) -> Dict[str, Any]:
        # partial-fold fan-in of the last cold compute on the read event
        return {"fanin": getattr(self, "_last_fold_fanin", None)}

    def compute(self, *, slice_ids: Optional[Array] = None, top_k: Optional[int] = None) -> Any:
        """Per-slice values.

        With no arguments: the full ``[S]``-leading result through the
        ordinary :meth:`Metric.compute` cycle (compute caching, distributed
        sync of the slice states). ``slice_ids=`` evaluates only those
        slices (a gather + vmapped compute — local states, no sync, no
        cache). ``top_k=k`` selects the ``k`` slices with the most ingested
        rows and returns ``(slice_ids, values)``.
        """
        if slice_ids is None and top_k is None:
            return super().compute()
        if slice_ids is not None and top_k is not None:
            raise MetricsUserError("pass either `slice_ids` or `top_k`, not both")
        # subset reads bypass the base compute cycle (no cache, no sync), so
        # they emit their own typed read event — one bool check when disabled
        rec = _TELEMETRY if _TELEMETRY.enabled else None
        t0 = time.perf_counter() if rec is not None else 0.0
        m = self._template
        host_ids: Optional[np.ndarray] = None
        if top_k is not None:
            if not isinstance(top_k, int) or top_k <= 0:
                raise MetricsUserError(f"`top_k` must be a positive int, got {top_k!r}")
            k = min(top_k, self.num_slices)
            ids = self._top_ids(k)
        else:
            ids = jnp.asarray(slice_ids)
            if ids.ndim != 1 or not jnp.issubdtype(ids.dtype, jnp.integer):
                raise MetricsUserError(
                    f"`slice_ids` must be a 1-D integer array, got shape"
                    f" {ids.shape} dtype {ids.dtype}"
                )
            # unlike update() (XLA scatter DROPS out-of-range ids, documented),
            # a gather silently CLAMPS them — an off-by-one would return a
            # neighboring slice's value; reject it where we can see the
            # values (on host: two eager jnp reductions would cost a device
            # round-trip each on a path budgeted in hundreds of microseconds)
            if ids.size and _is_concrete(ids):
                host_ids = np.asarray(ids)
                lo, hi = int(host_ids.min()), int(host_ids.max())
                if lo < 0 or hi >= self.num_slices:
                    raise MetricsUserError(
                        f"`slice_ids` out of range for num_slices={self.num_slices}:"
                        f" min {lo}, max {hi}"
                    )
        n_folded: Optional[int] = None
        if ids.size and _is_concrete(ids) and not self._is_synced:
            # the incremental path: fold only the dirty requested slices
            # through the bucketed AOT reader, assemble the rest from the
            # per-slice value cache (reuse the host copy the range check
            # already paid for — a second device->host transfer per read
            # is measurable at this scale)
            if host_ids is None:
                host_ids = np.asarray(ids)
            values, n_folded = self._fold_slices(host_ids)
        else:
            # traced ids / synced states / empty subset: plain gather+fold
            states = {name: jnp.asarray(getattr(self, name))[ids] for name in m._defaults}
            values = jax.vmap(m.compute_state)(states)
        if rec is not None:
            # leaves folded = wrapped leaves gathered per selected slice
            rec.record_read(
                "sliced",
                self,
                duration_s=time.perf_counter() - t0,
                leaves=len(m._defaults) * int(ids.shape[0]) if _is_concrete(ids) else len(m._defaults),
                cache_hit=n_folded == 0,
                fanin=n_folded,
                freshness=self.freshness_stamp(),
            )
        return (ids, values) if top_k is not None else values

    def _top_ids(self, k: int) -> Array:
        """Ids of the ``k`` fullest slices via a BUCKETED pre-lowered top-k:
        ``lax.top_k(counts, k)`` compiles once per distinct ``k``, so a
        dashboard sweeping k (top-5, top-10, top-50 panels) retraces per
        panel — rounding k up to the reader-bucket family and slicing the
        prefix keeps one executable per bucket. Exact: XLA top-k returns
        descending order with ties broken by lower index, so the k-prefix
        of a larger-k result IS the k result."""
        kb = round_up_bucket(k, self.num_slices)
        counts = self.slice_counts
        if not _is_concrete(counts):
            _, ids = jax.lax.top_k(counts, kb)
            return ids[:k]

        def build():
            def read(c: Array) -> Array:
                return jax.lax.top_k(c, kb)[1]

            return read

        reader = self._readers.get("sliced_topk", build, counts, bucket=kb)
        return reader(counts)[:k]

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def hot_slices(self, k: int = 10) -> Tuple[Array, Array]:
        """The ``k`` slices with the most ingested rows and each one's
        share of ALL ingested rows — the cumulative skew view behind the
        hot-slice alarm (the per-batch share feeds the windowed series;
        this is the since-reset answer to "which tenants are hot")."""
        if not isinstance(k, int) or k <= 0:
            raise MetricsUserError(f"`k` must be a positive int, got {k!r}")
        counts = self.slice_counts
        total = jnp.clip(jnp.sum(counts), 1, None)
        # bucketed selection (see _top_ids): one executable per k-bucket
        # instead of one trace per distinct k
        ids = self._top_ids(min(k, self.num_slices))
        return ids, counts[ids].astype(jnp.float32) / total.astype(jnp.float32)

    def state_footprint(self, include_children: bool = True) -> Dict[str, int]:
        """Per-state bytes with every key under ``sliced/`` — the telemetry
        recorder splits on the prefix so sliced-state growth tracks under a
        distinct high-water-mark label (with a per-slice average in the
        summary exporter) instead of silently mixing with base-state
        growth."""
        base = super().state_footprint(include_children=include_children)
        return {f"{SLICED_FOOTPRINT_PREFIX}{k}": v for k, v in base.items()}

    def __repr__(self) -> str:
        return f"{type(self).__name__}({type(self._template).__name__}(), num_slices={self.num_slices})"
