"""True/false positive/negative counting — the spine of the classification stack.

Behavior parity with /root/reference/torchmetrics/functional/classification/
stat_scores.py (:64-286): canonical-format inputs are reduced by boolean
masks + sums over the (sample, class, extra) axes depending on ``reduce`` /
``mdmc_reduce``; ``_reduce_stat_scores`` implements the shared
micro/macro/weighted/none/samples averaging used by every StatScores-derived
metric. All functions are pure and jit-compatible (static shapes given
``num_classes``).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import AverageMethod, DataType, MDMCAverageMethod

Array = jax.Array


def _check_avg_arguments(
    average: str, mdmc_average: Optional[str], num_classes: Optional[int], ignore_index: Optional[int]
) -> None:
    """Shared argument validation for the StatScores-derived metric family."""
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def _del_column(data: Array, idx: int) -> Array:
    return jnp.concatenate([data[:, :idx], data[:, (idx + 1):]], axis=1)


def _drop_negative_ignored_indices(
    preds: Array, target: Array, ignore_index: int, mode: DataType
) -> Tuple[Array, Array]:
    """Remove positions whose target equals a negative ignore_index.

    Reference stat_scores.py:28-61. Boolean-mask indexing is data-dependent,
    so this path (negative ignore_index) is host-eager only.
    """
    if mode == DataType.MULTIDIM_MULTICLASS and jnp.issubdtype(preds.dtype, jnp.floating):
        n_dims = preds.ndim
        num_classes = preds.shape[1]
        preds = jnp.swapaxes(preds, 1, n_dims - 1).reshape(-1, num_classes)
        target = target.reshape(-1)

    if mode in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
        keep = target != ignore_index
        preds = preds[keep]
        target = target[keep]

    return preds, target


def _stat_scores(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn over canonical binary ``(N,C)`` / ``(N,C,X)`` inputs.

    Reference stat_scores.py:64-110; output shapes per reduce mode match.
    """
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = 0 if preds.ndim == 2 else 2
    else:  # samples
        dim = 1

    true_pred, false_pred = target == preds, target != preds
    pos_pred, neg_pred = preds == 1, preds == 0

    tp = jnp.sum(true_pred & pos_pred, axis=dim)
    fp = jnp.sum(false_pred & pos_pred, axis=dim)
    tn = jnp.sum(true_pred & neg_pred, axis=dim)
    fn = jnp.sum(false_pred & neg_pred, axis=dim)

    return (
        tp.astype(jnp.int32),
        fp.astype(jnp.int32),
        tn.astype(jnp.int32),
        fn.astype(jnp.int32),
    )


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    mode: Optional[DataType] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Format inputs and count statistics. Reference stat_scores.py:113-196."""
    _negative_index_dropped = False

    if ignore_index is not None and ignore_index < 0 and mode is not None:
        preds, target = _drop_negative_ignored_indices(preds, target, ignore_index, mode)
        _negative_index_dropped = True

    preds, target, _ = _input_format_classification(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
    )

    if ignore_index is not None and ignore_index < 0 and not _negative_index_dropped:
        # torch fails loudly here via scatter index-out-of-bounds; JAX one_hot /
        # .at[-1] would silently corrupt instead, so raise explicitly
        raise ValueError(
            f"A negative `ignore_index` {ignore_index} is only supported by metrics that infer the"
            " input mode (e.g. Accuracy); use a non-negative class index here instead"
        )
    if ignore_index is not None and ignore_index >= preds.shape[1]:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes")
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            preds = jnp.swapaxes(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.swapaxes(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro" and not _negative_index_dropped:
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce)

    if ignore_index is not None and reduce == "macro" and not _negative_index_dropped:
        tp = tp.at[..., ignore_index].set(-1)
        fp = fp.at[..., ignore_index].set(-1)
        tn = tn.at[..., ignore_index].set(-1)
        fn = fn.at[..., ignore_index].set(-1)

    return tp, fp, tn, fn


def _stat_scores_compute(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Concatenate [tp, fp, tn, fn, support] on the last dim. Reference :199-230."""
    stats = [
        jnp.expand_dims(tp, -1),
        jnp.expand_dims(fp, -1),
        jnp.expand_dims(tn, -1),
        jnp.expand_dims(fn, -1),
        jnp.expand_dims(tp, -1) + jnp.expand_dims(fn, -1),  # support
    ]
    outputs = jnp.concatenate(stats, axis=-1)
    return jnp.where(outputs < 0, -1, outputs)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Shared micro/macro/weighted/none/samples reduction. Reference :233-286."""
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    if weights is None:
        weights = jnp.ones_like(denominator)
    else:
        weights = weights.astype(jnp.float32)

    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    # sum(weights)==0 (e.g. only present class ignored with average='weighted')
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        scores = jnp.where(ignore_mask, jnp.nan, scores)
    else:
        scores = jnp.sum(scores)

    return scores


def stat_scores(
    preds: Array,
    target: Array,
    reduce: str = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """One-shot tp/fp/tn/fn/support counts; shapes per reference :288-420.

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([1, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> stat_scores(preds, target, reduce='macro', num_classes=3)
        Array([[0, 1, 2, 1, 1],
               [1, 1, 1, 1, 2],
               [1, 0, 3, 0, 1]], dtype=int32)
    """
    if reduce not in ("micro", "macro", "samples"):
        raise ValueError(f"The `reduce` {reduce} is not valid.")
    if mdmc_reduce not in (None, "samplewise", "global"):
        raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
    if reduce == "macro" and (not num_classes or num_classes < 1):
        raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        top_k=top_k,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _stat_scores_compute(tp, fp, tn, fn)
