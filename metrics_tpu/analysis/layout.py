"""Layout manifest: per-leaf shard/reshard contracts as a runtime input.

The fusibility manifest (``analysis/manifest.py``) records WHETHER a
metric's update can fuse; this manifest records WHERE each state leaf
lives on a mesh and HOW it moves when the mesh changes — the static
source of truth the elastic-reshard work (ROADMAP items 2/3) restores
against, instead of re-deriving layout from live objects.
``scripts/tracelint.py --manifest`` writes both files from the same
interp walk; ``--manifest --check`` freshness-gates both in CI.

Schema v1 (deterministic serialization — byte-stable)::

    {
      "version": 1,
      "tool": "tracelint",
      "classes": {
        "classification/confusion_matrix.py::ConfusionMatrix": {
          "sliceable": true,               # admits SlicedMetric wrapping
          "declared_jit_unsafe": null,
          "leaves": {
            "confmat": {
              "reducer": "sum",            # add_state dist_reduce_fx class
              "shard_axis": "[S]",         # [S] | [R] | replicated
              "partition_spec": ["slices"],# template for the leading dim
              "reshard": "reshape",        # reshape | fold | gather | opaque
              "container": "array", "dtype": "int32",
              "shape": ["num_classes", "num_classes"],
              "wire": "array"              # array | list | opaque
            }
          }
        }, ...
      }
    }

Field semantics:

* ``shard_axis`` — ``"[S]"``: the leaf's leading axis becomes the slice
  axis under ``SlicedMetric`` wrapping (every ``sum``/``max``/``min``
  array leaf of a sliceable class), so it may shard disjointly over a
  mesh axis and the sync path legitimately skips reducing it.
  ``"[R]"``: the leading axis is a windowed ring-slot axis (time
  buckets, replicated across the mesh but never foldable ACROSS slots).
  ``"replicated"``: every mesh position holds the whole leaf and a
  cross-rank reduction is REQUIRED — a partition spec claiming such a
  leaf sharded makes ``sync_pytree_in_mesh`` silently skip that
  reduction (the TL-SHARD bug class).
* ``partition_spec`` — leading-dim template naming the DEFAULT mesh axis
  (``sliced/sharding.SLICE_AXIS``); ``[]`` replicates.
* ``reshard`` — what a mesh-shape change does to the leaf:
  ``"reshape"`` (re-slice the ``[S]`` axis over the new axis size),
  ``"fold"`` (re-fold through the leaf's own reducer — merge/sum-family
  leaves reshard by folding per-shard snapshots, not by reshaping),
  ``"gather"`` (cat/list leaves concatenate), ``"opaque"`` (no static
  recipe — custom reducer, runtime owns it).
* ``wire`` — the wire codec class (``observability/wire.py``):
  ``"array"`` dtype+bytes, ``"list"`` element-wise, ``"opaque"``
  statically unresolvable container.

Runtime consumers (``sliced/sharding.py``, ``parallel/distributed.py``)
look classes up via :func:`layout_for_class` — a covered class skips the
live-leaf probe (observable via their probe-skip counters), and
``METRICS_TPU_VERIFY_MANIFEST=1`` cross-checks every manifest answer
against the probe. Env overrides: ``METRICS_TPU_LAYOUT_MANIFEST=<path>``
points at an alternate file; ``METRICS_TPU_NO_MANIFEST=1`` (shared with
the fusibility manifest) disables consultation entirely.

Stdlib-only, like the rest of the analysis package.
"""
from __future__ import annotations

import json
import os
import pathlib
from typing import Dict, List, Optional, Set

from .engine import default_package_root
from . import interp
from .manifest import ENV_NO_MANIFEST, class_key

LAYOUT_VERSION = 1

#: repo-root-relative location of the committed layout manifest
DEFAULT_LAYOUT_MANIFEST = "scripts/layout_manifest.json"

#: env var naming an alternate layout manifest file
ENV_LAYOUT_MANIFEST_PATH = "METRICS_TPU_LAYOUT_MANIFEST"

#: shard-axis classes (see module docstring)
AXIS_SLICE = "[S]"
AXIS_RING = "[R]"
AXIS_REPLICATED = "replicated"

#: reshard recipes
RESHARD_RESHAPE = "reshape"
RESHARD_FOLD = "fold"
RESHARD_GATHER = "gather"
RESHARD_OPAQUE = "opaque"

#: reducer classes with a registered cross-shard fold: the string
#: reducers plus the tagged merge families (interp._reducer_of's
#: abstraction of ``*merge_fx()`` / ``moments_merge_fx()`` /
#: ``ring_*_fx()`` / ``decay_sum_fx()``)
FOLD_REDUCERS = {"sum", "mean", "max", "min", "merge", "moments", "decay", "ring"}

#: stdlib-only mirrors of the runtime constants (this package can never
#: import them; the cross-module agreement is pinned by
#: tests/bases/test_layout_manifest.py)
SLICED_PREFIX = "sliced/"  # observability/recorder.SLICED_FOOTPRINT_PREFIX
SKETCH_PREFIX = "sketch/"  # observability/recorder.SKETCH_FOOTPRINT_PREFIX
WINDOWED_PREFIX = "windowed/"  # observability/recorder.WINDOWED_FOOTPRINT_PREFIX
SLICE_ROWS = "_slice_rows"  # sliced/metric.SLICE_ROWS
SLICE_AXIS_NAME = "slices"  # sliced/sharding.SLICE_AXIS

#: manifest key of the one class whose leaves are registered dynamically
#: (broadcast from the wrapped template's): its entry carries the
#: synthetic row-counter leaf plus the ``dynamic_leaves`` marker
SLICED_METRIC_KEY = "sliced/metric.py::SlicedMetric"


# ---------------------------------------------------------------------------
# build (analysis side)
# ---------------------------------------------------------------------------

def class_is_sliceable(facts: interp.ClassFacts) -> bool:
    """Static mirror of ``SlicedMetric._validate_sliceable``: every leaf is
    a sum/max/min-reduced ARRAY state and the class is not declared
    jit-unsafe. (The runtime check additionally rejects wrapper metrics
    with live children — invisible statically, so the runtime keeps
    authority and the consumers fall back on any disagreement.)"""
    if not facts.entries or facts.declared is True:
        return False
    return all(e.sliceable for e in facts.entries)


def _leaf_record(entry: interp.StateEntry, sliceable_class: bool) -> Dict[str, object]:
    reducer = entry.dist_reduce_fx
    if reducer == "ring":
        axis = AXIS_RING
    elif sliceable_class and entry.sliceable:
        axis = AXIS_SLICE
    else:
        axis = AXIS_REPLICATED
    if axis == AXIS_SLICE:
        reshard = RESHARD_RESHAPE
    elif reducer in FOLD_REDUCERS:
        reshard = RESHARD_FOLD
    elif reducer == "cat" or entry.container == "list":
        reshard = RESHARD_GATHER
    else:
        reshard = RESHARD_OPAQUE
    if entry.container == "array":
        wire = "array"
    elif entry.container == "list":
        wire = "list"
    else:
        wire = "opaque"
    return {
        "reducer": reducer,
        "shard_axis": axis,
        "partition_spec": [SLICE_AXIS_NAME] if axis == AXIS_SLICE else [],
        "reshard": reshard,
        "container": entry.container,
        "dtype": entry.dtype,
        "shape": entry.shape,
        "wire": wire,
    }


def _sliced_metric_entry() -> Dict[str, object]:
    """The synthetic ``SlicedMetric`` entry: its per-template leaves are
    registered dynamically (every template leaf broadcast to a
    ``(num_slices,) + shape`` ``[S]``-leading row block, keeping the
    template's reducer) so the interp walk cannot enumerate them; the one
    statically-known leaf is the reserved row counter."""
    return {
        "sliceable": False,  # wrapping a SlicedMetric collides on SLICE_ROWS
        "declared_jit_unsafe": None,
        "dynamic_leaves": "template-broadcast",
        "leaves": {
            SLICE_ROWS: {
                "reducer": "sum",
                "shard_axis": AXIS_SLICE,
                "partition_spec": [SLICE_AXIS_NAME],
                "reshard": RESHARD_RESHAPE,
                "container": "array",
                "dtype": "int32",
                "shape": ["num_slices"],
                "wire": "array",
            }
        },
    }


def build_layout_manifest(project: Optional[interp.Project] = None) -> Dict[str, object]:
    """Derive the per-leaf layout contract for every state-registering
    metric class in the package. Always a FULL-package walk (freshness
    checks diff the whole file)."""
    project = project or interp.Project()
    root = project.root
    classes: Dict[str, Dict[str, object]] = {}
    for path in sorted(root.rglob("*.py")):
        rel = "/".join(path.relative_to(root).parts)
        if rel.startswith("analysis/"):
            continue  # the analyzer does not classify itself
        ctx = project.ctx(rel)
        if ctx is None:
            continue
        for node in interp.iter_metric_classes(ctx):
            facts = interp.class_facts(project, ctx, node)
            if not facts.is_metric or not facts.entries:
                continue
            sliceable = class_is_sliceable(facts)
            classes[f"{rel}::{node.name}"] = {
                "sliceable": sliceable,
                "declared_jit_unsafe": facts.declared,
                "leaves": {
                    e.name: _leaf_record(e, sliceable) for e in facts.entries
                },
            }
    # synthetic SlicedMetric entry (dynamically-registered leaves)
    sliced_ctx = project.ctx("sliced/metric.py")
    if sliced_ctx is not None and any(
        getattr(n, "name", None) == "SlicedMetric" for n in sliced_ctx.tree.body
    ):
        classes[SLICED_METRIC_KEY] = _sliced_metric_entry()
    return {
        "version": LAYOUT_VERSION,
        "tool": "tracelint",
        "classes": {k: classes[k] for k in sorted(classes)},
    }


def render_layout_manifest(manifest: Dict[str, object]) -> str:
    """Deterministic, diff-friendly serialization (sorted keys, newline-
    terminated) — ``--manifest --check`` compares these bytes."""
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


def load_layout_manifest(path: pathlib.Path) -> Optional[Dict[str, object]]:
    """Parse a layout manifest file; None when missing/invalid/wrong
    version."""
    path = pathlib.Path(path)
    if not path.is_file():
        return None
    try:
        data = json.loads(path.read_text())
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None
    if not isinstance(data, dict) or data.get("version") != LAYOUT_VERSION:
        return None
    return data


# ---------------------------------------------------------------------------
# path universe (consumed by the TL-SHARD rule)
# ---------------------------------------------------------------------------

def shard_path_universe(layout: Dict[str, object]) -> Dict[str, Set[str]]:
    """Every state-leaf path a committed partition-rule set can be asked to
    match — the footprint-prefixed forms ``shard_sliced_states`` produces
    plus the plain state names — mapped to the set of shard-axis tags
    that admit a named-axis spec there (empty set = the leaf must
    replicate, so a named-axis spec on it silently skips a REQUIRED
    reduction)."""
    universe: Dict[str, Set[str]] = {}

    def add(path: str, *axes: str) -> None:
        universe.setdefault(path, set()).update(axes)

    classes = layout.get("classes") if isinstance(layout, dict) else None
    if not isinstance(classes, dict):
        return universe
    for key, ent in classes.items():
        leaves = ent.get("leaves", {}) if isinstance(ent, dict) else {}
        sliceable = bool(ent.get("sliceable")) if isinstance(ent, dict) else False
        for name, rec in leaves.items():
            axis = rec.get("shard_axis") if isinstance(rec, dict) else None
            reducer = rec.get("reducer") if isinstance(rec, dict) else None
            if axis == AXIS_SLICE:
                # the [S] plane: only the sliced/-prefixed footprint form
                # carries the slice axis — a PLAIN name in a footprint
                # belongs to an unwrapped metric, whose leading axis is a
                # batch/class dim the sync path must still reduce. (The
                # synthetic `_slice_rows` leaf keeps [S] in plain form too:
                # it exists only inside SlicedMetric and the shipped rule
                # pattern matches it suffix-anchored.)
                if name == SLICE_ROWS:
                    add(name, AXIS_SLICE)
                else:
                    add(name)
                add(SLICED_PREFIX + name, AXIS_SLICE)
                continue
            ring = AXIS_RING if axis == AXIS_RING else None
            add(name, *([ring] if ring else []))
            if reducer in ("merge", "moments", "ring"):
                # merge-tagged leaves footprint under the sketch prefix
                add(SKETCH_PREFIX + name, *([ring] if ring else []))
            if reducer in ("ring", "decay"):
                # windowed wrappers footprint under the windowed prefix
                add(WINDOWED_PREFIX + name, *([ring] if ring else []))
            if sliceable:
                add(SLICED_PREFIX + name, AXIS_SLICE)
    return universe


# ---------------------------------------------------------------------------
# runtime consumption (imported by sliced/sharding.py and
# parallel/distributed.py — keep import-light)
# ---------------------------------------------------------------------------

def default_layout_manifest_path() -> pathlib.Path:
    override = os.environ.get(ENV_LAYOUT_MANIFEST_PATH)
    if override:
        return pathlib.Path(override)
    return default_package_root().parent / DEFAULT_LAYOUT_MANIFEST


_runtime_cache: Dict[str, Optional[Dict[str, object]]] = {}
_axis_index_cache: Dict[str, Dict[str, Set[str]]] = {}


def runtime_layout(path: Optional[pathlib.Path] = None) -> Dict[str, Dict[str, object]]:
    """The committed layout manifest's classes map, cached per path; empty
    when the file is absent (installed package without the repo checkout)
    or ``METRICS_TPU_NO_MANIFEST`` is set — consumers then keep their
    live-object probes as the sole authority."""
    if os.environ.get(ENV_NO_MANIFEST):
        return {}
    path = pathlib.Path(path) if path is not None else default_layout_manifest_path()
    key = str(path)
    if key not in _runtime_cache:
        _runtime_cache[key] = load_layout_manifest(path)
    data = _runtime_cache[key]
    if data is None:
        return {}
    classes = data.get("classes")
    return classes if isinstance(classes, dict) else {}


def invalidate_layout_cache() -> None:
    """Drop cached layout manifests (tests and long-lived sessions that
    regenerate the manifest on disk)."""
    _runtime_cache.clear()
    _axis_index_cache.clear()


def layout_for_class(cls: type, path: Optional[pathlib.Path] = None) -> Optional[Dict[str, object]]:
    """The layout entry for ``cls`` (exact class only — layouts do not
    inherit: a subclass may register different states)."""
    key = class_key(cls)
    if key is None:
        return None
    return runtime_layout(path).get(key)


def _axis_index(path: Optional[pathlib.Path] = None) -> Dict[str, Set[str]]:
    """Leaf name -> union of ``[S]``/``[R]`` tags any manifest class
    assigns it; EVERY manifest leaf name has an entry (replicated-only
    names map to the empty set), so membership distinguishes
    known-replicated from never-seen."""
    key = str(pathlib.Path(path) if path is not None else default_layout_manifest_path())
    index = _axis_index_cache.get(key)
    if index is None:
        index = {}
        for ent in runtime_layout(path).values():
            leaves = ent.get("leaves", {}) if isinstance(ent, dict) else {}
            for leaf, rec in leaves.items():
                axis = rec.get("shard_axis") if isinstance(rec, dict) else None
                entry = index.setdefault(leaf, set())
                if axis in (AXIS_SLICE, AXIS_RING):
                    entry.add(axis)
        _axis_index_cache[key] = index
    return index


def leaf_shard_axes(name: str, path: Optional[pathlib.Path] = None) -> Set[str]:
    """Union of shard-axis tags any class in the manifest assigns to a
    state leaf named ``name`` — the sync path's cheap plausibility index
    for a sharded-claimed spec (a name NO class tags ``[S]``/``[R]``
    cannot legitimately skip its cross-rank reduction). Empty when the
    manifest is absent/disabled (callers must then trust the spec)."""
    return set(_axis_index(path).get(name, ()))


def leaf_may_shard(name: str, path: Optional[pathlib.Path] = None) -> Optional[bool]:
    """Whether a sharded-claimed spec on a leaf named ``name`` is
    manifest-plausible: True when some class tags it ``[S]``/``[R]``,
    False when the manifest covers the name only as replicated, and None
    when the manifest is absent/disabled or has never seen the name (no
    verdict either way). ``name`` may be a footprint path — only its
    basename is consulted (a ``sliced/``-prefixed form shards whenever
    the bare leaf can)."""
    if not runtime_layout(path):
        return None
    base = name.rsplit("/", 1)[-1]
    if base == SLICE_ROWS:
        return True
    index = _axis_index(path)
    if base not in index:
        return None
    axes = index[base]
    prefixed = name != base
    if AXIS_RING in axes:
        return True
    if AXIS_SLICE in axes:
        # the slice axis only exists on the sliced/-prefixed (template-
        # broadcast) form of the leaf; a BARE name in a footprint belongs
        # to an unwrapped metric whose leading axis still needs reducing.
        # Bare claims arrive from sliced_partition_specs' name-keyed spec
        # dicts though, so only a known-replicated name is refutable.
        return True if prefixed else None
    return False
