"""Modular ROC (sketch-backed streaming default; exact modes opt-in).

Behavior parity with /root/reference/torchmetrics/classification/roc.py:24-150.
State modes as in auroc.py: streaming quantile sketch by default (lossless —
bit-equal to ``exact=True`` — while the stream fits ``sketch_capacity``,
weighted curve points beyond), ``exact=True`` for the unbounded cat-state
path, ``capacity=N`` for the static exact buffers.
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.classification._capacity import CapacityCurveMixin
from metrics_tpu.classification._sketch import DEFAULT_SKETCH_CAPACITY, SketchCurveMixin
from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.exact_curve import (
    binary_roc_fixed,
    multiclass_roc_fixed,
)
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.functional.classification.sketch_curve import binary_roc_weighted
from metrics_tpu.sketches.compat import register_exact_list_states, warn_exact_buffer
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class ROC(SketchCurveMixin, CapacityCurveMixin, Metric):
    """Computes the Receiver Operating Characteristic curve.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0., 1., 2., 3.])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> roc = ROC(pos_label=1)
        >>> fpr, tpr, thresholds = roc(pred, target)
        >>> fpr
        Array([0., 0., 0., 0., 1.], dtype=float32)
    """

    __jit_unsafe__ = False  # sketch default: fixed-shape trace-safe update
    __exact_mode_attr__ = "_exact"
    __fused_mask_valid__ = True
    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        multilabel: bool = False,
        exact: bool = False,
        sketch_capacity: int = DEFAULT_SKETCH_CAPACITY,
        shape_stable_reads: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self._exact = bool(exact)
        if exact and capacity is not None:
            raise ValueError("`exact=True` and `capacity` are mutually exclusive state modes")
        # TPU-native exact mode: static [capacity] buffers, fully jit-safe.
        # Binary keeps the flat triple; num_classes >= 2 keeps [capacity, C]
        # score rows (one-vs-rest curves per class); `multilabel=True`
        # additionally stores [capacity, C] indicator targets.
        self._init_capacity_case(capacity, num_classes, multilabel)
        if capacity is None:
            if self._exact:
                register_exact_list_states(self, ("preds", "target"))
                warn_exact_buffer("ROC")
            else:
                self._init_sketch_curve(
                    sketch_capacity, num_classes, shape_stable_reads=shape_stable_reads
                )

    def _update(self, preds: Array, target: Array, n_valid: Optional[Array] = None) -> None:
        if self._capacity is not None:
            self._capacity_update(preds, target, pos_label=self.pos_label)
            return
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        if self._exact:
            self.preds.append(preds)
            self.target.append(target)
        else:
            self._sketch_insert_canonical(
                preds, target, pos_label if preds.ndim == 1 else 1, n_valid=n_valid
            )
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _compute(
        self,
    ) -> Union[
        Tuple[Array, Array, Array],
        Tuple[List[Array], List[Array], List[Array]],
        Tuple[Array, Array, Array, Array],  # capacity mode: (fpr, tpr, thresholds, point_mask)
    ]:
        if self._capacity is not None:
            # static-shape output: (fpr, tpr, thresholds, point_mask);
            # multiclass/multilabel rows are per-class one-vs-rest curves
            if self._capacity_cols is not None:
                return multiclass_roc_fixed(
                    *self._capacity_buffers_2d(),
                    self.num_classes,
                    multilabel=self._capacity_multilabel,
                )
            return binary_roc_fixed(*self._capacity_buffers())
        if self._exact:
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
            return _roc_compute(preds, target, self.num_classes, self.pos_label)
        if self._sketch_reads_exact():
            preds, target, pos_label = self._sketch_exact_arrays()
            return _roc_compute(preds, target, self.num_classes, pos_label)
        return self._sketch_approx_compute()

    def _sketch_approx_compute(self):
        """Weighted ROC points from the compacted sketch rows, trimmed
        host-side to the unbounded path's dynamic-length output contract."""
        scores, y, w = self._sketch_weighted_arrays()

        def _one(s, yy, ww):
            fpr, tpr, thr, mask = binary_roc_weighted(s, yy, ww)
            keep = jnp.asarray(mask)
            return fpr[keep], tpr[keep], thr[keep]

        if self._sketch_cols is None:
            return _one(scores, y, w)
        curves = [_one(scores[:, c], y[:, c], w) for c in range(self._sketch_cols)]
        return [c[0] for c in curves], [c[1] for c in curves], [c[2] for c in curves]
