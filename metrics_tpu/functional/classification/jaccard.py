"""Jaccard index (IoU) from the confusion matrix.

Behavior parity with /root/reference/torchmetrics/functional/classification/
jaccard.py:23-137.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update
from metrics_tpu.parallel.distributed import reduce

Array = jax.Array


def _jaccard_from_confmat(
    confmat: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    reduction: str = "elementwise_mean",
) -> Array:
    if ignore_index is not None and 0 <= ignore_index < num_classes:
        confmat = confmat.at[ignore_index].set(jnp.zeros((), dtype=confmat.dtype))

    intersection = jnp.diag(confmat)
    union = jnp.sum(confmat, axis=0) + jnp.sum(confmat, axis=1) - intersection

    scores = intersection.astype(jnp.float32) / jnp.where(union == 0, 1, union).astype(jnp.float32)
    scores = jnp.where(union == 0, absent_score, scores)

    if ignore_index is not None and 0 <= ignore_index < num_classes:
        scores = jnp.concatenate([scores[:ignore_index], scores[ignore_index + 1:]])

    return reduce(scores, reduction=reduction)


def jaccard_index(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    threshold: float = 0.5,
    reduction: str = "elementwise_mean",
) -> Array:
    """Computes the Jaccard index (intersection over union).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> jaccard_index(preds, target, num_classes=2)
        Array(0.5833334, dtype=float32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold)
    return _jaccard_from_confmat(confmat, num_classes, ignore_index, absent_score, reduction)
