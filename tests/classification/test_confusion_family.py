"""ConfusionMatrix family vs sklearn oracles."""
import numpy as np
import pytest
from sklearn.metrics import (
    cohen_kappa_score as sk_cohen_kappa,
    confusion_matrix as sk_confusion_matrix,
    hinge_loss as sk_hinge,
    jaccard_score as sk_jaccard,
    matthews_corrcoef as sk_matthews,
)

import jax.numpy as jnp

from metrics_tpu import (
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    MatthewsCorrCoef,
)
from metrics_tpu.functional import (
    calibration_error,
    cohen_kappa,
    confusion_matrix,
    dice_score,
    hinge_loss,
    jaccard_index,
    kl_divergence,
    matthews_corrcoef,
)
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, NUM_CLASSES, MetricTester

_rng = np.random.RandomState(42)
_preds_mc = _rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_target_mc = _rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
_preds_bin_prob = _rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
_target_bin = _rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))


def _sk_cm(preds, target):
    return sk_confusion_matrix(np.asarray(target), np.asarray(preds), labels=np.arange(NUM_CLASSES))


class TestConfusionMatrix(MetricTester):
    def test_confusion_matrix_class(self):
        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=ConfusionMatrix,
            sk_metric=_sk_cm,
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_confusion_matrix_functional(self):
        self.run_functional_metric_test(
            _preds_mc, _target_mc, metric_functional=confusion_matrix, sk_metric=_sk_cm,
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_confusion_matrix_normalized(self):
        cm = confusion_matrix(
            jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]), num_classes=NUM_CLASSES, normalize="true"
        )
        sk_cm_norm = sk_confusion_matrix(
            _target_mc[0], _preds_mc[0], labels=np.arange(NUM_CLASSES), normalize="true"
        )
        np.testing.assert_allclose(np.asarray(cm), sk_cm_norm, atol=1e-6)

    def test_confusion_matrix_binary_prob(self):
        cm = confusion_matrix(jnp.asarray(_preds_bin_prob[0]), jnp.asarray(_target_bin[0]), num_classes=2)
        sk_cm_bin = sk_confusion_matrix(_target_bin[0], (_preds_bin_prob[0] >= 0.5).astype(int), labels=[0, 1])
        np.testing.assert_allclose(np.asarray(cm), sk_cm_bin)


class TestCohenKappa(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_cohen_kappa(self, weights):
        def sk_metric(preds, target):
            return sk_cohen_kappa(np.asarray(target), np.asarray(preds), weights=weights, labels=np.arange(NUM_CLASSES))

        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=CohenKappa,
            sk_metric=sk_metric,
            metric_args={"num_classes": NUM_CLASSES, "weights": weights},
        )


class TestMatthews(MetricTester):
    atol = 1e-5

    def test_matthews(self):
        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=MatthewsCorrCoef,
            sk_metric=lambda p, t: sk_matthews(np.asarray(t), np.asarray(p)),
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_matthews_functional(self):
        self.run_functional_metric_test(
            _preds_mc, _target_mc, metric_functional=matthews_corrcoef,
            sk_metric=lambda p, t: sk_matthews(np.asarray(t), np.asarray(p)),
            metric_args={"num_classes": NUM_CLASSES},
        )


class TestJaccard(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("reduction, sk_average", [("elementwise_mean", "macro"), ("none", None)])
    def test_jaccard(self, reduction, sk_average):
        def sk_metric(preds, target):
            return sk_jaccard(
                np.asarray(target), np.asarray(preds), average=sk_average, labels=np.arange(NUM_CLASSES)
            )

        self.run_class_metric_test(
            preds=_preds_mc,
            target=_target_mc,
            metric_class=JaccardIndex,
            sk_metric=sk_metric,
            metric_args={"num_classes": NUM_CLASSES, "reduction": reduction},
        )

    def test_jaccard_ignore_index(self):
        result = jaccard_index(
            jnp.asarray(_preds_mc[0]), jnp.asarray(_target_mc[0]), num_classes=NUM_CLASSES, ignore_index=0
        )
        # oracle: per-class jaccard with class 0's row zeroed, then dropped
        cm = sk_confusion_matrix(_target_mc[0], _preds_mc[0], labels=np.arange(NUM_CLASSES)).astype(float)
        cm[0] = 0.0
        inter = np.diag(cm)
        union = cm.sum(0) + cm.sum(1) - inter
        scores = np.where(union == 0, 0.0, inter / np.where(union == 0, 1, union))
        expected = np.delete(scores, 0).mean()
        np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)


class TestHinge(MetricTester):
    atol = 1e-5

    def test_hinge_binary(self):
        decisions = (_rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) - 0.5) * 4

        def sk_metric(preds, target):
            return sk_hinge(np.asarray(target), np.asarray(preds), labels=[0, 1])

        self.run_class_metric_test(
            preds=decisions,
            target=_target_bin,
            metric_class=HingeLoss,
            sk_metric=sk_metric,
        )

    def test_hinge_multiclass_crammer_singer(self):
        decisions = _rng.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)

        def sk_metric(preds, target):
            return sk_hinge(np.asarray(target), np.asarray(preds), labels=np.arange(NUM_CLASSES))

        self.run_class_metric_test(
            preds=decisions,
            target=_target_mc,
            metric_class=HingeLoss,
            sk_metric=sk_metric,
        )


class TestKLDivergence(MetricTester):
    atol = 1e-5

    def test_kld(self):
        p = _rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32) + 0.1
        q = _rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32) + 0.1

        def sk_metric(p_, q_):
            p_ = np.asarray(p_, np.float64)
            q_ = np.asarray(q_, np.float64)
            p_ = p_ / p_.sum(-1, keepdims=True)
            q_ = q_ / q_.sum(-1, keepdims=True)
            return np.mean(np.sum(p_ * np.log(p_ / q_), axis=-1))

        self.run_class_metric_test(
            preds=p,
            target=q,
            metric_class=KLDivergence,
            sk_metric=sk_metric,
        )


class TestCalibrationError(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    def test_ce_binary(self, norm):
        def oracle(preds, target):
            # reference-equivalent binning in numpy float64
            conf = np.asarray(preds, np.float64)
            acc = np.asarray(target, np.float64)
            bins = np.linspace(0, 1, 16)
            idx = np.clip(np.searchsorted(bins, conf, side="left") - 1, 0, 14)
            acc_bin = np.zeros(15)
            conf_bin = np.zeros(15)
            count = np.zeros(15)
            np.add.at(count, idx, 1)
            np.add.at(conf_bin, idx, conf)
            np.add.at(acc_bin, idx, acc)
            with np.errstate(invalid="ignore"):
                conf_bin = np.nan_to_num(conf_bin / count)
                acc_bin = np.nan_to_num(acc_bin / count)
            prop = count / count.sum()
            if norm == "l1":
                return np.sum(np.abs(acc_bin - conf_bin) * prop)
            if norm == "max":
                return np.max(np.abs(acc_bin - conf_bin))
            ce = np.sum((acc_bin - conf_bin) ** 2 * prop)
            return np.sqrt(ce) if ce > 0 else 0.0

        self.run_class_metric_test(
            preds=_preds_bin_prob,
            target=_target_bin,
            metric_class=CalibrationError,
            sk_metric=oracle,
            metric_args={"norm": norm},
            check_merge=False,
            check_jit=False,
        )


def test_dice_score():
    pred = jnp.asarray(
        [[0.85, 0.05, 0.05, 0.05],
         [0.05, 0.85, 0.05, 0.05],
         [0.05, 0.05, 0.85, 0.05],
         [0.05, 0.05, 0.05, 0.85]]
    )
    target = jnp.asarray([0, 1, 3, 2])
    assert float(dice_score(pred, target)) == pytest.approx(0.3333333, abs=1e-5)
    assert float(dice_score(pred, target, bg=True)) == pytest.approx(0.5, abs=1e-5)


def test_kl_divergence_functional():
    p = jnp.asarray([[0.36, 0.48, 0.16]])
    q = jnp.asarray([[1 / 3, 1 / 3, 1 / 3]])
    assert float(kl_divergence(p, q)) == pytest.approx(0.085300, abs=1e-5)
    assert float(kl_divergence(jnp.log(p), jnp.log(q), log_prob=True)) == pytest.approx(0.085300, abs=1e-5)


def test_cohen_kappa_functional():
    target = jnp.asarray([1, 1, 0, 0])
    preds = jnp.asarray([0, 1, 0, 0])
    assert float(cohen_kappa(preds, target, num_classes=2)) == pytest.approx(0.5)


def test_hinge_one_vs_all():
    decisions = _rng.randn(64, NUM_CLASSES).astype(np.float32)
    target = _rng.randint(0, NUM_CLASSES, 64)
    result = hinge_loss(jnp.asarray(decisions), jnp.asarray(target), multiclass_mode="one-vs-all")
    t_oh = np.eye(NUM_CLASSES)[target]
    margin = np.where(t_oh.astype(bool), decisions, -decisions)
    expected = np.clip(1 - margin, 0, None).sum(0) / 64
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)


def test_calibration_error_functional_jit():
    import jax

    preds = jnp.asarray(_preds_bin_prob[0])
    target = jnp.asarray(_target_bin[0])
    eager = calibration_error(preds, target)
    jitted = jax.jit(lambda p, t: calibration_error(p, t))(preds, target)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager), atol=1e-6)


@pytest.mark.parametrize("squared", [False, True])
@pytest.mark.parametrize("multiclass_mode", [None, "crammer-singer", "one-vs-all"])
def test_hinge_modes_vs_reference(squared, multiclass_mode):
    """All (squared x multiclass_mode) combos vs the reference implementation
    (functional/classification/hinge.py:24-121)."""
    from tests.helpers.reference import load_reference_module

    ref_hinge = load_reference_module("torchmetrics.functional.classification.hinge").hinge_loss
    import torch

    rng = np.random.RandomState(3)
    if multiclass_mode is None:
        preds_np = rng.randn(32).astype(np.float32)
        target_np = rng.randint(0, 2, 32)
    else:
        preds_np = rng.randn(32, NUM_CLASSES).astype(np.float32)
        target_np = rng.randint(0, NUM_CLASSES, 32)

    kwargs = {"squared": squared}
    if multiclass_mode is not None:
        kwargs["multiclass_mode"] = multiclass_mode
    got = np.asarray(hinge_loss(jnp.asarray(preds_np), jnp.asarray(target_np), **kwargs))
    want = np.asarray(ref_hinge(torch.from_numpy(preds_np), torch.from_numpy(target_np), **kwargs))
    np.testing.assert_allclose(got, want, atol=1e-5)  # one-vs-all returns per-class


@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
@pytest.mark.parametrize("n_bins", [10, 30])
def test_calibration_norms_vs_reference(norm, n_bins):
    """ECE/RMSCE/MCE norms vs the reference (functional/classification/
    calibration_error.py:24-126)."""
    from tests.helpers.reference import load_reference_module

    ref_cal = load_reference_module(
        "torchmetrics.functional.classification.calibration_error"
    ).calibration_error
    import torch

    rng = np.random.RandomState(5)
    preds_np = rng.rand(256).astype(np.float32)
    target_np = rng.randint(0, 2, 256)
    got = float(calibration_error(jnp.asarray(preds_np), jnp.asarray(target_np), n_bins=n_bins, norm=norm))
    want = float(ref_cal(torch.from_numpy(preds_np), torch.from_numpy(target_np), n_bins=n_bins, norm=norm))
    np.testing.assert_allclose(got, want, atol=1e-6)
