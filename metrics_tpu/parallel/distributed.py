"""Distributed state synchronization — the TPU-native equivalent of the
reference's ``torch.distributed`` backend.

The reference (/root/reference/torchmetrics/utilities/distributed.py:96-145)
implements ``gather_all_tensors`` as: barrier -> gather per-rank shapes ->
pad to elementwise-max -> ``all_gather`` -> trim, over NCCL/Gloo process
groups. Here the same contract is provided two ways, both XLA-native:

* **Host-level** (`gather_all_arrays`): cross-process gather using a one-shot
  pjit'ed ``all_gather`` over the global device mesh (ICI within a host/pod
  slice, DCN across hosts via ``jax.distributed``). Uneven per-rank shapes
  are handled with the same pad-to-max + trim contract, with the shape
  exchange done host-side (it is outside any jit region, mirroring the
  reference where the gather is likewise eager).
* **In-jit** (`sync_in_mesh` / `reduce_state`): for metric state living
  inside a pjit/shard_map region, reductions map directly onto XLA
  collectives over a named mesh axis — ``psum``/``pmean``/``pmax``/``pmin``
  for scalar-reduced states and ``all_gather(tiled=True)`` for concat
  states. This is cheaper than gather-then-reduce (the reference's only
  strategy) because the reduction rides the ICI all-reduce.

``process_group`` in the reference maps to a *mesh axis name* (or a subset
axis) here.
"""
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def distributed_available() -> bool:
    """True when more than one process participates (multi-host JAX)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def world_size(group: Optional[Any] = None) -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def process_index() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# Host-level gather (cross-process, outside jit)
# ---------------------------------------------------------------------------

def _process_allgather(x: Array) -> List[Array]:
    """All-gather ``x`` across processes; returns a list of per-process arrays."""
    if not distributed_available():
        return [jnp.asarray(x)]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(np.asarray(x), tiled=False)
    return [jnp.asarray(stacked[i]) for i in range(stacked.shape[0])]


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather an array from all processes, supporting uneven dim sizes.

    Contract parity with the reference ``gather_all_tensors``
    (/root/reference/torchmetrics/utilities/distributed.py:96-145): returns a
    list of arrays, one per process, each with its true (untrimmed) shape.
    """
    result = jnp.asarray(result)
    if not distributed_available():
        return [result]

    if result.ndim == 0:
        return _process_allgather(result)

    # exchange shapes host-side, pad to elementwise max, gather, trim
    local_shape = np.asarray(result.shape, dtype=np.int64)
    all_shapes = _process_allgather(jnp.asarray(local_shape))
    all_shapes = [np.asarray(s) for s in all_shapes]
    max_shape = np.max(np.stack(all_shapes), axis=0)

    if all((s == all_shapes[0]).all() for s in all_shapes):
        return _process_allgather(result)

    pad_width = [(0, int(m - s)) for s, m in zip(result.shape, max_shape)]
    padded = jnp.pad(result, pad_width)
    gathered = _process_allgather(padded)
    return [g[tuple(slice(0, int(d)) for d in shp)] for g, shp in zip(gathered, all_shapes)]


# ---------------------------------------------------------------------------
# In-jit collectives over a named mesh axis
# ---------------------------------------------------------------------------

def _axis_size(axis_name: str) -> int:
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:  # older jax
        return jax.lax.psum(1, axis_name)


def all_gather_replicated(x: Array, axis_name: str, tiled: bool = True) -> Array:
    """All-gather whose output is *replicated* (VMA-clean) across the axis.

    Implemented as a psum of the local shard scattered into its slot — the
    same bytes over ICI as a ring all-gather, but the output is provably
    identical on every device, so ``shard_map`` can emit it with
    ``PartitionSpec()`` without ``check_vma=False``.
    """
    x = jnp.asarray(x)
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    work_dtype = jnp.int32 if x.dtype == jnp.bool_ else x.dtype
    buf = jnp.zeros((n,) + x.shape, work_dtype).at[idx].set(x.astype(work_dtype))
    out = jax.lax.psum(buf, axis_name)
    if x.dtype == jnp.bool_:
        out = out.astype(jnp.bool_)
    if tiled:
        out = out.reshape((n * x.shape[0],) + x.shape[1:]) if x.ndim >= 1 else out
    return out


def sync_in_mesh(
    state: Dict[str, Union[Array, list]],
    reductions: Dict[str, Union[str, Callable, None]],
    axis_name: str,
) -> Dict[str, Union[Array, list]]:
    """Synchronize a metric-state pytree across a named mesh axis, inside jit.

    ``"sum"/"mean"/"max"/"min"`` states use the matching XLA all-reduce;
    ``"cat"`` (and list) states use a tiled ``all_gather``. Use inside
    ``shard_map``/``pmap`` bodies where ``axis_name`` is bound.
    """
    out: Dict[str, Union[Array, list]] = {}
    for name, value in state.items():
        red = reductions.get(name)
        if isinstance(value, list):
            cat = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0) if value else jnp.zeros((0,))
            out[name] = [all_gather_replicated(cat, axis_name, tiled=True)]
            continue
        if red is None:
            # "gathered, not reduced" parity: stack per-rank values along a new dim 0
            out[name] = all_gather_replicated(value, axis_name, tiled=False)
        elif red == "sum":
            out[name] = jax.lax.psum(value, axis_name)
        elif red == "mean":
            out[name] = jax.lax.pmean(value, axis_name)
        elif red == "max":
            out[name] = jax.lax.pmax(value, axis_name)
        elif red == "min":
            out[name] = jax.lax.pmin(value, axis_name)
        elif red == "cat":
            out[name] = all_gather_replicated(value, axis_name, tiled=True)
        elif callable(red):
            out[name] = red(all_gather_replicated(value, axis_name, tiled=False))
        else:
            raise ValueError(f"Unknown reduction {red!r} for state {name!r}")
    return out


# ---------------------------------------------------------------------------
# Scalar reduction helpers (parity with reference reduce/class_reduce)
# ---------------------------------------------------------------------------

def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'.

    Parity with /root/reference/torchmetrics/utilities/distributed.py:21-40.
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: 'micro' | 'macro' | 'weighted' | 'none'.

    Parity with /root/reference/torchmetrics/utilities/distributed.py:43-93.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction) if class_reduction != "micro" else fraction

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")
