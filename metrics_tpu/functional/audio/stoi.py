"""Short-Time Objective Intelligibility (STOI / extended STOI).

The reference wraps the `pystoi` numpy package
(/root/reference/torchmetrics/functional/audio/stoi.py via audio/stoi.py:25);
neither pystoi nor an audio stack is available here, so this is a JAX
implementation of the published algorithm (Taal et al., "An Algorithm for
Intelligibility Prediction of Time-Frequency Weighted Noisy Speech", 2011;
eSTOI: Jensen & Taal 2016):

1. resample both signals to 10 kHz (host, polyphase);
2. remove silent frames (256-sample Hann frames, 50% overlap, 40 dB below
   the loudest frame; host — data-dependent length);
3. STFT magnitudes (256-frame / 512-FFT), 15 one-third-octave bands from
   150 Hz;
4. 30-frame sliding segments; STOI: per-band scale + clip then band-row
   correlation; eSTOI: row+column normalization and spectrogram correlation;
5. average over segments (and bands).

Steps 3-5 are a single jitted kernel (static shapes via a precomputed
segment count); steps 1-2 stay host-side numpy.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_FS = 10000  # internal rate
_N_FRAME = 256
_NFFT = 512
_NUM_BANDS = 15
_MIN_FREQ = 150.0
_SEG_LEN = 30  # frames per intelligibility segment
_BETA = -15.0  # clipping threshold (dB)
_DYN_RANGE = 40.0  # silent-frame energy range (dB)
_EPS = np.finfo(np.float64).eps


def _hann(n: int) -> np.ndarray:
    """Periodic-style Hann used by the STOI reference code: hanning(n+2)[1:-1]."""
    return np.hanning(n + 2)[1:-1]


def _third_octave_matrix(fs: int, nfft: int, num_bands: int, min_freq: float) -> np.ndarray:
    """[num_bands, nfft//2+1] 0/1 matrix mapping FFT bins to 1/3-octave bands."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands, dtype=np.float64)
    center = min_freq * 2 ** (k / 3)
    lo = center * 2 ** (-1 / 6)
    hi = center * 2 ** (1 / 6)
    obm = np.zeros((num_bands, len(f)))
    for i in range(num_bands):
        lo_idx = np.argmin((f - lo[i]) ** 2)
        hi_idx = np.argmin((f - hi[i]) ** 2)
        obm[i, lo_idx:hi_idx] = 1
    return obm


def _resample(x: np.ndarray, fs_in: int, fs_out: int) -> np.ndarray:
    if fs_in == fs_out:
        return x
    from scipy.signal import resample_poly

    g = np.gcd(int(fs_in), int(fs_out))
    return resample_poly(x, fs_out // g, fs_in // g)


def _remove_silent_frames(
    x: np.ndarray, y: np.ndarray, dyn_range: float, framelen: int, hop: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Drop frames of x more than ``dyn_range`` dB below its loudest frame,
    rebuilding both signals by windowed overlap-add (host: output length is
    data-dependent)."""
    window = _hann(framelen)
    # pystoi's exclusive range(0, len - framelen, hop): the frame starting
    # exactly at len - framelen is dropped
    n_frames = max(-(-(len(x) - framelen) // hop), 0) if len(x) > framelen else 0
    if n_frames == 0:
        return x, y
    idx = np.arange(framelen)[None, :] + hop * np.arange(n_frames)[:, None]
    x_frames = window * x[idx]
    y_frames = window * y[idx]

    energies = 20 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    keep = (np.max(energies) - dyn_range - energies) < 0
    x_frames, y_frames = x_frames[keep], y_frames[keep]

    n_kept = len(x_frames)
    out_len = (n_kept - 1) * hop + framelen if n_kept else 0
    x_out = np.zeros(out_len)
    y_out = np.zeros(out_len)
    for i in range(n_kept):  # overlap-add
        sl = slice(i * hop, i * hop + framelen)
        x_out[sl] += x_frames[i]
        y_out[sl] += y_frames[i]
    return x_out, y_out


@partial(jax.jit, static_argnames=("num_segments", "extended"))
def _stoi_kernel(
    x: Array, y: Array, obm: Array, window: Array, num_segments: int, extended: bool, n_valid: Array
) -> Array:
    """Band spectrograms -> sliding segments -> correlation, all static-shape.

    ``num_segments`` is a BUCKETED (rounded-up) static count so variable
    utterance lengths share a handful of compiled kernels; segments past the
    traced ``n_valid`` are masked out of the average.
    """
    n_frames = num_segments + _SEG_LEN - 1
    idx = jnp.arange(_N_FRAME)[None, :] + (_N_FRAME // 2) * jnp.arange(n_frames)[:, None]
    x_spec = jnp.abs(jnp.fft.rfft(x[idx] * window, n=_NFFT, axis=-1))  # [M, F]
    y_spec = jnp.abs(jnp.fft.rfft(y[idx] * window, n=_NFFT, axis=-1))

    x_tob = jnp.sqrt(obm @ (x_spec.T**2))  # [bands, frames]
    y_tob = jnp.sqrt(obm @ (y_spec.T**2))

    seg_idx = jnp.arange(_SEG_LEN)[None, :] + jnp.arange(num_segments)[:, None]
    x_seg = jnp.moveaxis(x_tob[:, seg_idx], 1, 0)  # [segments, bands, SEG_LEN]
    y_seg = jnp.moveaxis(y_tob[:, seg_idx], 1, 0)

    if extended:
        def _row_col_normalize(seg):
            seg = seg - seg.mean(axis=-1, keepdims=True)
            seg = seg / (jnp.linalg.norm(seg, axis=-1, keepdims=True) + _EPS)
            seg = seg - seg.mean(axis=-2, keepdims=True)
            return seg / (jnp.linalg.norm(seg, axis=-2, keepdims=True) + _EPS)

        x_n = _row_col_normalize(x_seg)
        y_n = _row_col_normalize(y_seg)
        seg_mask = jnp.arange(num_segments) < n_valid
        per_seg = jnp.sum(x_n * y_n / _SEG_LEN, axis=(1, 2))
        return jnp.sum(per_seg * seg_mask) / n_valid

    # per band-row scaling of the degraded segment + clipping
    alpha = jnp.sqrt(
        jnp.sum(x_seg**2, axis=-1, keepdims=True) / (jnp.sum(y_seg**2, axis=-1, keepdims=True) + _EPS)
    )
    y_scaled = alpha * y_seg
    y_prime = jnp.minimum(y_scaled, x_seg * (1 + 10 ** (-_BETA / 20)))

    xn = x_seg - x_seg.mean(axis=-1, keepdims=True)
    yn = y_prime - y_prime.mean(axis=-1, keepdims=True)
    corr = jnp.sum(xn * yn, axis=-1) / (
        jnp.linalg.norm(xn, axis=-1) * jnp.linalg.norm(yn, axis=-1) + _EPS
    )
    seg_mask = (jnp.arange(num_segments) < n_valid)[:, None]
    return jnp.sum(corr * seg_mask) / (n_valid * corr.shape[1])


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False
) -> Array:
    """STOI of a degraded signal vs its clean reference (≈ [0, 1], higher is
    more intelligible; eSTOI may go slightly negative).

    ``preds``/``target`` are 1-D waveforms (or [..., time] batches, averaged)
    at sample rate ``fs``.
    """
    preds_np = np.asarray(preds, np.float64)
    target_np = np.asarray(target, np.float64)
    if preds_np.shape != target_np.shape:
        raise ValueError("preds and target must have the same shape")
    if preds_np.ndim > 1:
        flat = [
            short_time_objective_intelligibility(p, t, fs, extended)
            for p, t in zip(preds_np.reshape(-1, preds_np.shape[-1]), target_np.reshape(-1, target_np.shape[-1]))
        ]
        return jnp.stack(flat).reshape(preds_np.shape[:-1])

    x = _resample(target_np, fs, _FS)  # clean
    y = _resample(preds_np, fs, _FS)  # degraded
    x, y = _remove_silent_frames(x, y, _DYN_RANGE, _N_FRAME, _N_FRAME // 2)

    hop = _N_FRAME // 2
    # exclusive frame count (pystoi convention, see _remove_silent_frames)
    n_frames = max(-(-(len(x) - _N_FRAME) // hop), 0) if len(x) > _N_FRAME else 0
    num_segments = n_frames - _SEG_LEN + 1
    if num_segments < 1:
        raise ValueError(
            "Not enough non-silent signal for STOI: need more than"
            f" {_SEG_LEN * hop + _N_FRAME} samples at {_FS} Hz after silent-frame removal"
        )

    # bucket the static segment count so variable lengths share compilations
    bucket = -(-num_segments // 32) * 32
    needed = (bucket + _SEG_LEN - 2) * hop + _N_FRAME
    x = np.pad(x, (0, max(0, needed - len(x))))
    y = np.pad(y, (0, max(0, needed - len(y))))

    obm = jnp.asarray(_third_octave_matrix(_FS, _NFFT, _NUM_BANDS, _MIN_FREQ))
    window = jnp.asarray(_hann(_N_FRAME))
    return _stoi_kernel(
        jnp.asarray(x), jnp.asarray(y), obm, window, int(bucket), bool(extended),  # tracelint: disable=TL-RECOMPILE — bucket is rounded to 32s above, so the static-arg compile set is bounded by design
        jnp.asarray(num_segments, jnp.float32),
    ).astype(jnp.float32)
