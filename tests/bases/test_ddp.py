"""Distributed-sync tests.

The reference spawns 2 Gloo processes (/root/reference/tests/bases/test_ddp.py);
here the same behaviors are verified with (a) real XLA collectives over an
8-virtual-device CPU mesh via ``sync_in_mesh`` inside ``shard_map`` and
(b) the Metric host sync machinery driven by a simulated 2-rank gather —
including uneven per-rank state sizes (pad-to-max + trim contract).
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Metric
from metrics_tpu.parallel.distributed import gather_all_arrays, sync_in_mesh
from metrics_tpu.utils.compat import shard_map
from tests.bases.test_metric import DummyListMetric, DummyMetric


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("rank",))


def test_sync_in_mesh_sum():
    mesh = _mesh()

    def body(x):
        state = {"total": jnp.sum(x)}
        synced = sync_in_mesh(state, {"total": "sum"}, "rank")
        return synced["total"]

    data = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("rank"), out_specs=P())
    )(data)
    assert np.allclose(out, data.sum())


def test_sync_in_mesh_all_reductions():
    mesh = _mesh()

    def body(x):
        state = {"s": jnp.sum(x), "m": jnp.max(x), "n": jnp.min(x), "a": jnp.mean(x)}
        reds = {"s": "sum", "m": "max", "n": "min", "a": "mean"}
        synced = sync_in_mesh(state, reds, "rank")
        return synced["s"], synced["m"], synced["n"], synced["a"]

    data = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    s, m, n, a = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("rank"), out_specs=(P(), P(), P(), P()))
    )(data)
    assert np.allclose(s, data.sum())
    assert np.allclose(m, data.max())
    assert np.allclose(n, data.min())
    assert np.allclose(a, np.mean([d.mean() for d in np.asarray(data).reshape(8, 2)]))


def test_sync_in_mesh_cat():
    mesh = _mesh()

    def body(x):
        state = {"vals": x}
        synced = sync_in_mesh(state, {"vals": "cat"}, "rank")
        return synced["vals"]

    data = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("rank"), out_specs=P())
    )(data)
    assert np.allclose(np.sort(np.asarray(out).ravel()), np.arange(16))


def test_metric_update_inside_shard_map():
    """Full pattern: per-device metric accumulation + collective sync, one jit."""
    mesh = _mesh()
    metric = DummyMetric()

    def step(x):
        state = metric.init_state()
        state = metric.update_state(state, jnp.sum(x))
        synced = sync_in_mesh(state, {"x": "sum"}, "rank")
        return metric.compute_state(synced)

    data = jnp.arange(8, dtype=jnp.float32)
    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("rank"), out_specs=P()))(data)
    assert np.allclose(out, data.sum())


# ---------------------------------------------------------------------------
# host-level sync machinery with a simulated 2-rank world
# ---------------------------------------------------------------------------

def test_host_sync_sum_two_ranks():
    """Simulate rank-local states and check sum reduction through _sync_dist."""
    rank_vals = [3.0, 5.0]
    metrics = [DummyMetric() for _ in rank_vals]
    for m, v in zip(metrics, rank_vals):
        m.update(v)

    for rank, m in enumerate(metrics):
        gather = lambda x, group=None, _r=rank: [
            x if i == _r else jnp.asarray(rank_vals[i], dtype=jnp.float32) for i in range(len(rank_vals))
        ]
        m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
        assert np.allclose(m.x, sum(rank_vals))
        m.unsync()
        assert np.allclose(m.x, rank_vals[rank])


def test_host_sync_cat_uneven_sizes():
    """Uneven per-rank list states: parity with reference test_ddp.py:63-81."""
    rank_data = [jnp.array([1.0, 2.0]), jnp.array([3.0, 4.0, 5.0])]
    m = DummyListMetric()
    m.update(rank_data[0])

    def gather(x, group=None):
        return [x, rank_data[1]]

    m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
    gathered = np.concatenate([np.asarray(v) for v in m.x]) if isinstance(m.x, list) else np.asarray(m.x)
    assert np.allclose(np.sort(gathered.ravel()), [1, 2, 3, 4, 5])
    m.unsync()
    assert len(m.x) == 1


def test_gather_all_arrays_single_process():
    out = gather_all_arrays(jnp.ones((2, 3)))
    assert len(out) == 1
    assert out[0].shape == (2, 3)


def test_compute_with_dist_sync_fn():
    """compute() drives the sync machinery and restores local state after."""
    m = DummyMetric(dist_sync_fn=lambda x, group=None: [x, x])
    m.update(2.0)
    assert np.allclose(m.compute(), 4.0)  # synced over fake world of 2
    assert np.allclose(m.x, 2.0)  # local state restored (unsynced)


def test_state_dict_is_synced_accumulation_continues():
    """Parity with reference _test_state_dict_is_synced (test_ddp.py:135-241):
    saving while synced must not corrupt continued accumulation."""
    m = DummyMetric(dist_sync_fn=lambda x, group=None: [x, x])
    for step in range(3):
        m.update(1.0)
        with m.sync_context():
            sd = m.state_dict()
            assert np.allclose(sd["x"], 2.0 * (step + 1))
        assert np.allclose(m.x, step + 1.0)
