"""Confusion matrix via index-mapped bincount.

Behavior parity with /root/reference/torchmetrics/functional/classification/
confusion_matrix.py:24-186. The (target*C + pred) -> bincount trick becomes a
static-length ``_bincount`` (jit-safe with ``num_classes`` given).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.data import _bincount
from metrics_tpu.utils.enums import DataType
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, threshold: float = 0.5, multilabel: bool = False
) -> Array:
    try:
        preds, target, mode = _input_format_classification(preds, target, threshold)
    except ValueError as err:
        # label inputs under jit cannot infer the class count from values;
        # retry with the explicit num_classes (eager path stays reference-parity)
        if "under jit" not in str(err):
            raise
        preds, target, mode = _input_format_classification(preds, target, threshold, num_classes=num_classes)
    if mode not in (DataType.BINARY, DataType.MULTILABEL):
        preds = jnp.argmax(preds, axis=1)
        target = jnp.argmax(target, axis=1)
    if multilabel:
        unique_mapping = ((2 * target + preds) + 4 * jnp.arange(num_classes)).flatten()
        minlength = 4 * num_classes
    else:
        unique_mapping = (target.reshape(-1) * num_classes + preds.reshape(-1)).astype(jnp.int32)
        minlength = num_classes**2

    bins = _bincount(unique_mapping.astype(jnp.int32), minlength=minlength)
    if multilabel:
        return bins.reshape(num_classes, 2, 2)
    return bins.reshape(num_classes, num_classes)


def _confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / jnp.sum(confmat, axis=1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / jnp.sum(confmat, axis=0, keepdims=True)
        elif normalize == "all":
            confmat = confmat / jnp.sum(confmat)

        nan_mask = jnp.isnan(confmat)
        from metrics_tpu.utils.checks import _is_concrete

        if _is_concrete(confmat) and bool(jnp.any(nan_mask)):
            rank_zero_warn(
                f"{int(jnp.sum(nan_mask))} nan values found in confusion matrix have been replaced with zeros."
            )
        confmat = jnp.where(nan_mask, 0.0, confmat)
    return confmat


def confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Array:
    """Computes the confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> confusion_matrix(preds, target, num_classes=2)
        Array([[2, 0],
               [1, 1]], dtype=int32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold, multilabel)
    return _confusion_matrix_compute(confmat, normalize)
