"""tracelint command line.

``python scripts/tracelint.py [paths...]`` (stdlib-only load) or
``python -m metrics_tpu.analysis [paths...]``.

Exit status: 0 when every violation is baselined or suppressed, 1 when new
violations exist (or, with ``--check``, when the baseline is stale), 2 on
usage errors. ``--baseline-update`` rewrites the baseline to the current
violation set and always exits 0.
"""
from __future__ import annotations

import argparse
import pathlib
import sys
from typing import List, Optional

from .baseline import load_baseline, save_baseline, split_by_baseline
from .engine import Violation, analyze_paths, default_package_root
from .layout import DEFAULT_LAYOUT_MANIFEST
from .manifest import DEFAULT_MANIFEST
from .reporters import render_github, render_json, render_text
from .rules import all_rules, get_rules

#: repo-root-relative default; lives next to the other check scripts
DEFAULT_BASELINE = "scripts/tracelint_baseline.json"


def _repo_root() -> pathlib.Path:
    return default_package_root().parent


def _baseline_entry_violation(rule: str, path: str, snippet: str) -> Violation:
    """Reconstruct a carry-over Violation from a baseline key (line/col are
    informational only and not part of the key)."""
    return Violation(rule=rule, path=path, line=0, col=0, message="", snippet=snippet)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tracelint",
        description="Static analyzer for metrics_tpu's trace-safety, state, and recompile invariants.",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=pathlib.Path,
        help="files/directories to lint (default: the metrics_tpu package)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--baseline",
        type=pathlib.Path,
        default=None,
        help=f"baseline file (default: <repo>/{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: report every violation as new",
    )
    parser.add_argument(
        "--baseline-update",
        action="store_true",
        help="rewrite the baseline to the current violation set and exit 0",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="CI mode: additionally fail (exit 1) on stale baseline entries",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "github"),
        default=None,
        help="report format: text (default), json (schema v2), or github "
        "(GitHub Actions ::error annotations for inline PR diffs)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="alias for --format=json (kept for script compatibility)",
    )
    parser.add_argument("--list-rules", action="store_true", help="list registered rules and exit")
    parser.add_argument(
        "--manifest",
        action="store_true",
        help="manifest mode: write BOTH committed analyzer manifests — the "
        "fusibility manifest (per-metric verdicts) and the layout manifest "
        "(per-leaf reducer/shard-axis/reshard recipes) — always full-package; "
        "with --check, fail instead if either committed file is stale",
    )
    parser.add_argument(
        "--manifest-path",
        type=pathlib.Path,
        default=None,
        help=f"fusibility manifest file (default: <repo>/{DEFAULT_MANIFEST})",
    )
    parser.add_argument(
        "--layout-manifest-path",
        type=pathlib.Path,
        default=None,
        help=f"layout manifest file (default: <repo>/{DEFAULT_LAYOUT_MANIFEST})",
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            sys.stdout.write(f"{rule.id}: {rule.description}\n")
        return 0

    if args.manifest:
        return _manifest_mode(args)

    try:
        rules = get_rules(args.rules.split(",")) if args.rules else all_rules()
    except KeyError as err:
        sys.stderr.write(f"tracelint: {err.args[0]}\n")
        return 2

    paths = args.paths or [default_package_root()]
    result = analyze_paths(paths, rules)
    for err in result.parse_errors:
        sys.stderr.write(f"tracelint: parse error: {err}\n")

    analyzed = set(result.relpaths)
    baseline_path = args.baseline or (_repo_root() / DEFAULT_BASELINE)
    if args.baseline_update:
        # scope the rewrite to the ANALYZED files: entries for files outside
        # this run's paths are carried over untouched, so a partial-path
        # update can never wipe other files' grandfathered violations
        carried = [
            v
            for (rule, vpath, snippet), count in load_baseline(baseline_path).items()
            for v in [_baseline_entry_violation(rule, vpath, snippet)] * count
            if vpath not in analyzed
        ]
        entries = carried + list(result.violations)
        save_baseline(baseline_path, entries)
        sys.stdout.write(
            f"tracelint: baseline {baseline_path} updated with "
            f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'}"
            f" ({len(carried)} carried over from outside the analyzed paths)\n"
        )
        return 0

    baseline = load_baseline(baseline_path) if not args.no_baseline else None
    if baseline is not None:
        new, grandfathered, stale = split_by_baseline(result.violations, baseline)
        # staleness is only meaningful for files this run actually looked at
        stale = {k: n for k, n in stale.items() if k[1] in analyzed}
    else:
        new, grandfathered, stale = list(result.violations), [], {}

    stale_count = sum(stale.values()) if stale else 0
    fmt = args.format or ("json" if args.json else "text")
    if fmt == "json":
        sys.stdout.write(
            render_json(
                new,
                grandfathered,
                suppressed_count=len(result.suppressed),
                n_files=result.n_files,
                rules=[r.id for r in rules],
                stale_count=stale_count,
            )
        )
    elif fmt == "github":
        sys.stdout.write(render_github(new, grandfathered))
    else:
        sys.stdout.write(
            render_text(
                new,
                grandfathered,
                suppressed_count=len(result.suppressed),
                n_files=result.n_files,
                stale_count=stale_count,
            )
        )

    if new or result.parse_errors:
        return 1
    if args.check and stale_count:
        return 1
    return 0


def _manifest_mode(args) -> int:
    """``--manifest``: regenerate BOTH committed manifests (fusibility +
    layout) from one interp walk; ``--manifest --check``: CI freshness gate
    (byte-compare each against its committed file — no jax import)."""
    from .interp import Project
    from .layout import build_layout_manifest, render_layout_manifest
    from .manifest import build_manifest, render_manifest

    project = Project()
    fus_path = args.manifest_path or (_repo_root() / DEFAULT_MANIFEST)
    lay_path = args.layout_manifest_path or (_repo_root() / DEFAULT_LAYOUT_MANIFEST)
    fus = render_manifest(build_manifest(project))
    lay = render_layout_manifest(build_layout_manifest(project))
    targets = (
        ("fusibility", fus_path, fus, fus.count('"verdict"'), "metrics"),
        ("layout", lay_path, lay, lay.count('"reducer"'), "leaves"),
    )
    if args.check:
        stale = False
        for kind, path, rendered, n, unit in targets:
            committed = path.read_text() if path.is_file() else None
            if committed != rendered:
                stale = True
                sys.stderr.write(
                    f"tracelint: {kind} manifest {path} is "
                    f"{'missing' if committed is None else 'STALE'} — regenerate with "
                    "`python scripts/tracelint.py --manifest` and commit the result\n"
                )
            else:
                sys.stdout.write(f"tracelint: {kind} manifest {path} is fresh ({n} {unit})\n")
        return 1 if stale else 0
    for kind, path, rendered, n, unit in targets:
        path.write_text(rendered)
        sys.stdout.write(f"tracelint: {kind} manifest written to {path} ({n} {unit})\n")
    return 0
