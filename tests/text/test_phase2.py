"""TER / CHRF / EED / SQuAD parity tests.

Oracles: sacrebleu (installed in this environment — the reference's own
upstream) for CHRF, and the reference implementation itself (loaded from
/root/reference) for TER/EED/SQuAD plus cross-checks, mirroring the
reference's tests/text/{test_ter,test_chrf,test_eed,test_squad}.py. TER is
pinned to the reference rather than modern sacrebleu because 0.8.0dev swaps
hypothesis/reference roles (ter.py:467), which newer sacrebleu fixed.
"""
import numpy as np
import pytest
from sacrebleu.metrics import CHRF as SacreCHRF

from metrics_tpu.functional.text import chrf_score, extended_edit_distance, squad, translation_edit_rate
from metrics_tpu.text import CHRFScore, ExtendedEditDistance, SQuAD, TranslationEditRate
from tests.helpers.reference import load_reference_module
from tests.text.helpers import TextTester
from tests.text.inputs import _inputs_multiple_references

_PREDS_BATCHES = _inputs_multiple_references.preds
_TARGETS_BATCHES = _inputs_multiple_references.targets
_FLAT_PREDS = [p for batch in _PREDS_BATCHES for p in batch]
_FLAT_TARGETS = [t for batch in _TARGETS_BATCHES for t in batch]


# ---------------------------------------------------------------------------
# TER vs sacrebleu
# ---------------------------------------------------------------------------


def _ref_ter(preds, targets, **kw):
    # Oracle is the reference implementation itself: torchmetrics 0.8.0dev
    # computes _translation_edit_rate with swapped hypothesis/reference roles
    # (reference functional/text/ter.py:467) — a quirk later sacrebleu
    # versions do not share, so modern sacrebleu values differ and parity is
    # pinned against the reference.
    ref = load_reference_module("torchmetrics.functional.text.ter")
    return float(ref.translation_edit_rate(preds, targets, **kw))


@pytest.mark.parametrize(
    "kwargs",
    [
        {},
        {"normalize": True},
        {"no_punctuation": True},
        {"lowercase": False},
        {"asian_support": True, "normalize": True},
    ],
)
def test_ter_vs_reference(kwargs):
    got = float(translation_edit_rate(_FLAT_PREDS, _FLAT_TARGETS, **kwargs))
    expected = _ref_ter(_FLAT_PREDS, _FLAT_TARGETS, **kwargs)
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_ter_class_accumulation_and_forward():
    TextTester().run_class_metric_test(
        preds=_PREDS_BATCHES,
        targets=_TARGETS_BATCHES,
        metric_class=TranslationEditRate,
        sk_metric=lambda preds, targets: _ref_ter(preds, targets),
        atol=1e-5,
    )


def test_ter_sentence_level_and_reference_parity():
    ref_ter = load_reference_module("torchmetrics.functional.text.ter").translation_edit_rate
    got, got_sent = translation_edit_rate(_FLAT_PREDS, _FLAT_TARGETS, return_sentence_level_score=True)
    want, want_sent = ref_ter(_FLAT_PREDS, _FLAT_TARGETS, return_sentence_level_score=True)
    np.testing.assert_allclose(float(got), float(want), atol=1e-6)
    np.testing.assert_allclose(
        [float(s) for s in got_sent], [float(s) for s in want_sent], atol=1e-6
    )


def test_ter_edge_cases():
    assert float(translation_edit_rate(["hello"], [["hello"]])) == 0.0
    assert float(translation_edit_rate([""], [["hello there"]])) == 0.0  # empty hyp vs ref
    assert float(translation_edit_rate(["a b"], [[""]])) == 1.0  # empty reference, edits > 0
    with pytest.raises(ValueError, match="normalize"):
        translation_edit_rate(["a"], [["a"]], normalize="yes")


# ---------------------------------------------------------------------------
# CHRF vs sacrebleu
# ---------------------------------------------------------------------------


def _sacre_chrf(preds, targets, **kw):
    chrf = SacreCHRF(
        char_order=kw.get("n_char_order", 6),
        word_order=kw.get("n_word_order", 2),
        beta=int(kw.get("beta", 2.0)),
        lowercase=kw.get("lowercase", False),
        whitespace=kw.get("whitespace", False),
        eps_smoothing=True,  # the reference implements the eps-smoothed variant
    )
    max_refs = max(len(t) for t in targets)
    refs = [[t[i] if i < len(t) else t[0] for t in targets] for i in range(max_refs)]
    return chrf.corpus_score(preds, refs).score / 100.0


@pytest.mark.parametrize(
    "kwargs",
    [
        {},
        {"n_word_order": 0},  # original chrF
        {"lowercase": True},
        {"whitespace": True},
        {"n_char_order": 4, "n_word_order": 1},
    ],
)
def test_chrf_vs_sacrebleu(kwargs):
    got = float(chrf_score(_FLAT_PREDS, _FLAT_TARGETS, **kwargs))
    expected = _sacre_chrf(_FLAT_PREDS, _FLAT_TARGETS, **kwargs)
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_chrf_class_accumulation_and_forward():
    TextTester().run_class_metric_test(
        preds=_PREDS_BATCHES,
        targets=_TARGETS_BATCHES,
        metric_class=CHRFScore,
        sk_metric=lambda preds, targets: _sacre_chrf(preds, targets),
        atol=1e-5,
    )


def test_chrf_sentence_level_matches_reference():
    ref_chrf = load_reference_module("torchmetrics.functional.text.chrf").chrf_score
    got, got_sent = chrf_score(_FLAT_PREDS, _FLAT_TARGETS, return_sentence_level_score=True)
    want, want_sent = ref_chrf(_FLAT_PREDS, _FLAT_TARGETS, return_sentence_level_score=True)
    np.testing.assert_allclose(float(got), float(want), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(got_sent), np.asarray([float(s) for s in want_sent]), atol=1e-6
    )


def test_chrf_arg_validation():
    with pytest.raises(ValueError, match="n_char_order"):
        chrf_score(["a"], [["a"]], n_char_order=0)
    with pytest.raises(ValueError, match="n_word_order"):
        chrf_score(["a"], [["a"]], n_word_order=-1)
    with pytest.raises(ValueError, match="beta"):
        CHRFScore(beta=-1.0)


# ---------------------------------------------------------------------------
# EED vs the reference implementation
# ---------------------------------------------------------------------------


def _ref_eed(preds, targets, **kw):
    ref = load_reference_module("torchmetrics.functional.text.eed")
    return float(ref.extended_edit_distance(preds, targets, **kw))


@pytest.mark.parametrize(
    "kwargs",
    [{}, {"alpha": 1.0, "rho": 0.5}, {"deletion": 1.0, "insertion": 0.5}, {"language": "ja"}],
)
def test_eed_vs_reference(kwargs):
    got = float(extended_edit_distance(_FLAT_PREDS, _FLAT_TARGETS, **kwargs))
    np.testing.assert_allclose(got, _ref_eed(_FLAT_PREDS, _FLAT_TARGETS, **kwargs), atol=1e-6)


def test_eed_class_accumulation_and_forward():
    TextTester().run_class_metric_test(
        preds=_PREDS_BATCHES,
        targets=_TARGETS_BATCHES,
        metric_class=ExtendedEditDistance,
        sk_metric=_ref_eed,
        atol=1e-6,
    )


def test_eed_sentence_level_and_validation():
    got, got_sent = extended_edit_distance(
        _FLAT_PREDS, _FLAT_TARGETS, return_sentence_level_score=True
    )
    assert got_sent.shape[0] == len(_FLAT_PREDS)
    with pytest.raises(ValueError, match="alpha"):
        extended_edit_distance(["a"], [["a"]], alpha=-1.0)
    with pytest.raises(ValueError, match="language"):
        ExtendedEditDistance(language="de")


# ---------------------------------------------------------------------------
# SQuAD vs the reference implementation
# ---------------------------------------------------------------------------


def _squad_fixture():
    preds = [
        {"prediction_text": "1976", "id": "id1"},
        {"prediction_text": "the big bang theory", "id": "id2"},
        {"prediction_text": "a quick brown fox", "id": "id3"},
    ]
    targets = [
        {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "id1"},
        {"answers": {"answer_start": [1], "text": ["The Big Bang Theory!", "big bang"]}, "id": "id2"},
        {"answers": {"answer_start": [0], "text": ["the quick brown fox", "lazy dog"]}, "id": "id3"},
    ]
    return preds, targets


def test_squad_vs_reference():
    ref_squad = load_reference_module("torchmetrics.functional.text.squad").squad
    preds, targets = _squad_fixture()
    got = squad(preds, targets)
    want = ref_squad(preds, targets)
    for key in want:
        np.testing.assert_allclose(float(got[key]), float(want[key]), atol=1e-4)


def test_squad_class_accumulates_and_syncs():
    preds, targets = _squad_fixture()
    metric = SQuAD()
    metric.update(preds[:1], targets[:1])
    metric.update(preds[1:], targets[1:])
    whole = SQuAD()
    whole.update(preds, targets)
    for key in ("f1", "exact_match"):
        np.testing.assert_allclose(
            float(metric.compute()[key]), float(whole.compute()[key]), atol=1e-5
        )

    # scalar sum states: simulated 2-rank sync doubles both numerator and count
    synced = SQuAD(dist_sync_fn=lambda x, group=None: [x, x])
    synced.update(preds, targets)
    for key in ("f1", "exact_match"):
        np.testing.assert_allclose(
            float(synced.compute()[key]), float(whole.compute()[key]), atol=1e-5
        )


def test_squad_single_dict_inputs_and_errors():
    pred = {"prediction_text": "yes", "id": "q"}
    target = {"answers": {"answer_start": [0], "text": ["yes"]}, "id": "q"}
    result = squad(pred, target)
    assert float(result["exact_match"]) == 100.0
    with pytest.raises(KeyError, match="prediction_text"):
        squad({"id": "q"}, target)
    with pytest.raises(KeyError, match="answers"):
        squad(pred, {"id": "q"})
    with pytest.raises(KeyError, match="text"):
        squad(pred, {"answers": {"answer_start": [0]}, "id": "q"})
