"""Seeded SDR corpus — shared by the stored-oracle generator
(scripts/make_text_audio_oracle.py) and tests/audio/test_sdr_stored_oracle.py
(the tests/audio/pesq_corpus.py pattern)."""
import numpy as np


def sdr_corpus():
    """(preds, target) float64 [2, time]: harmonic + square-wave targets,
    estimates = short-FIR-filtered targets plus seeded noise."""
    rng = np.random.default_rng(31337)
    n = 4000
    t = np.arange(n) / 8000.0
    target = np.stack(
        [
            np.sin(2 * np.pi * 440 * t) + 0.5 * np.sin(2 * np.pi * 880 * t),
            np.sign(np.sin(2 * np.pi * 220 * t)) * 0.7,
        ]
    ).astype(np.float64)
    kernel = np.array([0.9, 0.3, -0.1, 0.05])
    filtered = np.stack([np.convolve(ch, kernel, mode="same") for ch in target])
    preds = filtered + 0.05 * rng.standard_normal(filtered.shape)
    return preds, target


def engine_scores():
    """Our SDR/SI-SDR over the corpus — the ONE definition of the swept
    variants, shared by the fixture generator and the drift-pin test."""
    import jax.numpy as jnp

    from metrics_tpu.functional.audio import (
        scale_invariant_signal_distortion_ratio,
        signal_distortion_ratio,
    )

    preds, target = sdr_corpus()
    jp, jt = jnp.asarray(preds), jnp.asarray(target)
    out = {}
    vals = np.asarray(signal_distortion_ratio(jp, jt))
    out["sdr_ch0"], out["sdr_ch1"] = float(vals[0]), float(vals[1])
    vals_cg = np.asarray(signal_distortion_ratio(jp, jt, use_cg_iter=10))
    out["sdr_cg_ch0"], out["sdr_cg_ch1"] = float(vals_cg[0]), float(vals_cg[1])
    vals_zm = np.asarray(signal_distortion_ratio(jp, jt, zero_mean=True))
    out["sdr_zm_ch0"], out["sdr_zm_ch1"] = float(vals_zm[0]), float(vals_zm[1])
    si = np.asarray(scale_invariant_signal_distortion_ratio(jp, jt))
    out["sisdr_ch0"], out["sisdr_ch1"] = float(si[0]), float(si[1])
    return out
