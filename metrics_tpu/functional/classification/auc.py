"""Area under a curve via the trapezoidal rule.

Behavior parity with /root/reference/torchmetrics/functional/classification/
auc.py:20-136.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _is_concrete

Array = jax.Array


def _auc_update(x: Array, y: Array) -> Tuple[Array, Array]:
    if x.ndim > 1:
        x = jnp.squeeze(x)
    if y.ndim > 1:
        y = jnp.squeeze(y)
    if x.ndim > 1 or y.ndim > 1:
        raise ValueError(
            f"Expected both `x` and `y` tensor to be 1d, but got tensors with dimension {x.ndim} and {y.ndim}"
        )
    if x.size != y.size:
        raise ValueError(
            f"Expected the same number of elements in `x` and `y` tensor but received {x.size} and {y.size}"
        )
    return x, y


def _auc_compute_without_check(x: Array, y: Array, direction: float) -> Array:
    return jnp.trapezoid(y, x) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    if reorder:
        idx = jnp.argsort(x, stable=True)
        x, y = x[idx], y[idx]

    dx = x[1:] - x[:-1]
    if _is_concrete(dx):
        if bool(jnp.any(dx < 0)) and not bool(jnp.all(dx <= 0)):
            raise ValueError(
                "The `x` tensor is neither increasing or decreasing. Try setting the reorder argument to `True`."
            )
    # trace-safe direction (the mixed-order error above needs concrete values,
    # but decreasing-x negation must agree between jit and eager)
    direction = jnp.where(jnp.any(dx < 0) & jnp.all(dx <= 0), -1.0, 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Computes the area under the curve (x, y) by the trapezoidal rule.

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1., 2., 3.])
        >>> y = jnp.array([0., 1., 2., 2.])
        >>> auc(x, y)
        Array(4., dtype=float32)
    """
    x, y = _auc_update(x, y)
    return _auc_compute(x, y, reorder=reorder)
