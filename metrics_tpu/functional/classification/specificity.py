"""Specificity functional kernel.

Behavior parity with /root/reference/torchmetrics/functional/classification/
specificity.py:23-186 (weights for 'weighted' averaging are tn+fp, i.e. the
denominator — matching the reference's choice at specificity.py:64).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import (
    _check_avg_arguments,
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _specificity_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: str,
    mdmc_average: Optional[str],
) -> Array:
    """Reference specificity.py:23-70."""
    numerator = tn.astype(jnp.float32)
    denominator = (tn + fp).astype(jnp.float32)
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp | fn | fp) == 0
        numerator = jnp.where(cond, -1.0, numerator)
        denominator = jnp.where(cond, -1.0, denominator)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tn + fp),
        average=average,
        mdmc_average=mdmc_average,
    )


def specificity(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """One-shot specificity. Reference specificity.py:73-186.

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> specificity(preds, target, average='macro', num_classes=3)
        Array(0.61111116, dtype=float32)
    """
    _check_avg_arguments(average, mdmc_average, num_classes, ignore_index)

    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _specificity_compute(tp, fp, tn, fn, average, mdmc_average)
