"""Dice score.

Behavior parity with /root/reference/torchmetrics/functional/classification/
dice.py:60-112, with the per-class Python loop vectorized over the class
axis (identical numerics).
"""
import jax
import jax.numpy as jnp

from metrics_tpu.parallel.distributed import reduce
from metrics_tpu.utils.data import to_categorical

Array = jax.Array


def dice_score(
    preds: Array,
    target: Array,
    bg: bool = False,
    nan_score: float = 0.0,
    no_fg_score: float = 0.0,
    reduction: str = "elementwise_mean",
) -> Array:
    """Computes the Dice score from prediction scores.

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([[0.85, 0.05, 0.05, 0.05],
        ...                   [0.05, 0.85, 0.05, 0.05],
        ...                   [0.05, 0.05, 0.85, 0.05],
        ...                   [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.array([0, 1, 3, 2])
        >>> dice_score(pred, target)
        Array(0.33333334, dtype=float32)
    """
    num_classes = preds.shape[1]
    bg_inv = 1 - int(bg)
    pred_labels = to_categorical(preds, argmax_dim=1) if jnp.issubdtype(preds.dtype, jnp.floating) else preds

    classes = jnp.arange(bg_inv, num_classes)
    pred_1h = pred_labels[:, None] == classes[None, :]  # [N, K]
    target_1h = target[:, None] == classes[None, :]

    tp = jnp.sum(pred_1h & target_1h, axis=0).astype(jnp.float32)
    fp = jnp.sum(pred_1h & ~target_1h, axis=0).astype(jnp.float32)
    fn = jnp.sum(~pred_1h & target_1h, axis=0).astype(jnp.float32)

    denom = 2 * tp + fp + fn
    score = jnp.where(denom == 0, nan_score, (2 * tp) / jnp.where(denom == 0, 1.0, denom))

    has_fg = jnp.any(target_1h, axis=0)
    scores = jnp.where(has_fg, score, no_fg_score)

    return reduce(scores, reduction=reduction)
