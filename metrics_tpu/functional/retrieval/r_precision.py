"""Retrieval R-precision.

Behavior parity with /root/reference/torchmetrics/functional/retrieval/
r_precision.py:20-55.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Precision at R where R is the number of relevant documents.

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_r_precision(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]))
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    relevant_number = int(jnp.sum(target))
    if not relevant_number:
        return jnp.asarray(0.0, dtype=preds.dtype)

    relevant = jnp.sum(target[jnp.argsort(-preds, axis=-1)][:relevant_number]).astype(jnp.float32)
    return relevant / relevant_number
