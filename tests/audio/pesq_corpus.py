"""Deterministic PESQ oracle corpus — shared by the stored-score fixture
test (tests/audio/test_pesq_engine.py) and the oracle generator
(scripts/make_pesq_oracle.py).

The corpus is fully seeded so the SAME (ref, deg) pairs are reproducible in
any environment: an environment with the official ``pesq`` C binding runs
``python scripts/make_pesq_oracle.py`` once to store official scores next to
the engine scores, and the fixture test then bounds |engine − official|
unconditionally from the stored csv (the BERTScore baseline-csv pattern).
"""
from typing import Dict, List, Tuple

import numpy as np


def _speechlike(rng: np.random.Generator, n: int, fs: int) -> np.ndarray:
    """Seeded speech-shaped test signal: 2.5 Hz syllabic envelope over a
    four-partial harmonic carrier plus a low noise floor."""
    t = np.arange(n) / fs
    envelope = np.clip(np.sin(2 * np.pi * 2.5 * t), 0, None)
    carrier = sum(
        np.sin(2 * np.pi * f0 * t + rng.uniform(0, 6)) for f0 in (220, 450, 900, 1800)
    )
    return ((envelope * carrier + 0.01 * rng.standard_normal(n)) * 0.1).astype(np.float64)


def _with_snr(clean: np.ndarray, rng: np.random.Generator, snr_db: float) -> np.ndarray:
    noise = rng.standard_normal(len(clean))
    noise *= np.sqrt(np.mean(clean**2) / (np.mean(noise**2) * 10 ** (snr_db / 10)))
    return clean + noise


def build_corpus() -> List[Tuple[str, int, str, np.ndarray, np.ndarray]]:
    """Return [(item_id, fs, mode, ref, deg)]: 3 (fs, mode) configs x 5
    degradation classes, all seeded."""
    items = []
    for fs, mode in ((8000, "nb"), (16000, "nb"), (16000, "wb")):
        rng = np.random.default_rng(1234 + fs + (100 if mode == "wb" else 0))
        clean = _speechlike(rng, 3 * fs, fs)
        degradations = {
            "clean": clean.copy(),
            "snr20": _with_snr(clean, rng, 20.0),
            "snr10": _with_snr(clean, rng, 10.0),
            "snr05": _with_snr(clean, rng, 5.0),
            # constant 25 ms delay + mild noise: exercises time alignment
            "delay": np.concatenate(
                [np.zeros(fs // 40), _with_snr(clean, rng, 15.0)[: -fs // 40]]
            ),
        }
        for name, deg in degradations.items():
            items.append((f"{mode}{fs}_{name}", fs, mode, clean, deg))
    return items


def score_with(fn) -> Dict[str, float]:
    """Score the whole corpus with ``fn(ref, deg, fs, mode) -> float``."""
    return {item_id: float(fn(ref, deg, fs, mode)) for item_id, fs, mode, ref, deg in build_corpus()}
