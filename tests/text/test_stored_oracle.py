"""Stored-oracle fixtures for the text engines
(scripts/make_text_audio_oracle.py — the PESQ/FID stored-corpus pattern).

Two layers, both asserted UNCONDITIONALLY from committed csvs:

1. engine drift pin — our SacreBLEU/TER/chrF/EED scores over the committed
   MT corpus must match the stored values exactly (any numeric change to
   the Tercom shift DP, the chrF n-gram F machinery, or a sacre tokenizer
   fails here and must regenerate the fixture deliberately);
2. official comparison from storage — the sacrebleu-package scores stored
   beside them bound |ours − official| per family without importing
   sacrebleu at test time. SacreBLEU and chrF agree with sacrebleu to
   ~1e-6 across the full grid. TER and chrF++ hold REFERENCE-faithful
   divergences from modern sacrebleu (verified three-way in round 5:
   ours is bit-identical to the reference implementation; sacrebleu
   differs by up to ~0.03 on TER corpus aggregation and ~1e-5 on chrF++
   — see docs/differences.md), so their bounds pin the divergence as
   KNOWN AND STABLE rather than asserting equality.
"""
import csv
import os

import pytest

from tests.text.oracle_corpus import engine_scores

_FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _read(name):
    path = os.path.join(_FIXDIR, name)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return {row["case"]: float(row["score"]) for row in csv.DictReader(fh)}


def test_engine_drift_pin():
    pinned = _read("text_engine_scores.csv")
    assert pinned is not None, "run scripts/make_text_audio_oracle.py"
    got = engine_scores()  # the generator's own scoring definition
    assert set(got) == set(pinned)
    for key, val in got.items():
        assert val == pytest.approx(pinned[key], abs=1e-5), key


def test_official_scores_from_storage():
    ours = _read("text_engine_scores.csv")
    official = _read("text_official_scores.csv")
    assert ours is not None and official is not None, "run scripts/make_text_audio_oracle.py"

    for key, off in official.items():
        diff = abs(ours[key] - off)
        if key.startswith("sacrebleu_") or key in ("chrf", "chrf_lc"):
            assert diff <= 2e-4, (key, ours[key], off)
        elif key == "chrfpp":
            # reference-faithful chrF++ word-ngram divergence vs sacrebleu
            assert diff <= 5e-4, (key, ours[key], off)
        elif key.startswith("ter_"):
            # reference-faithful multi-reference corpus aggregation
            # divergence vs sacrebleu TER; bound pins it as stable
            assert diff <= 0.06, (key, ours[key], off)
        else:  # pragma: no cover — unknown rows would mean fixture drift
            raise AssertionError(f"unexpected fixture row {key}")
