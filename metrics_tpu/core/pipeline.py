"""Async update pipeline: double-buffered, backpressured metric ingest that
never stalls the serving loop.

The fused path (``core/fused.py``) solved the *dispatch* side — one XLA
dispatch per batch instead of N — but the host still serializes: every
``collection.update(batch)`` pays the fused call's host work (coercion,
cache lookup, state-pytree packing, dispatch) inline, and any ``compute()``
or telemetry readback is a full sync barrier. This module moves that host
work off the hot path:

* :meth:`MetricCollection.compile_update_async` returns an
  :class:`AsyncUpdateHandle` layered on the existing :class:`FusedUpdate`
  kernel. ``update_async(batch)`` enqueues the batch into a **bounded
  double-buffered queue** (depth 2 by default) and returns in microseconds;
  a single worker thread drains the queue and issues the already-compiled
  fused kernel. JAX's async dispatch does the device-side pipelining — the
  point is to get the host out of the way: step k+1's ingest overlaps step
  k's dispatch and compute, and the hot path never performs a blocking
  readback (enforced at review time by tracelint rule **TL-BLOCK**).
* **Backpressure** is the bounded queue depth with a ``block`` / ``drop`` /
  ``error`` policy: ``block`` waits for a slot (lossless, the default),
  ``drop`` discards the batch and counts it (telemetry's dropped-batches
  counter), ``error`` raises :class:`AsyncQueueFull` at the call site.
* ``compute()`` reads a **bounded-staleness snapshot**: it waits only until
  at most ``max_staleness`` accepted batches remain unapplied (default 0 =
  drain-then-compute) and never calls ``block_until_ready`` itself. With a
  positive bound the snapshot is *stale but batch-atomic*: the state lock
  serializes each batch's dispatch-and-install against the read, so the
  snapshot sits between whole batches — up to the bound behind, never
  mid-install, never a donating dispatch's dead buffers.
* ``flush()`` / ``close()`` give a deterministic drain for epoch
  boundaries and tests; ``close()`` joins the worker so no thread leaks.
* **Worker exceptions** are captured with the originating batch index and
  re-raised at the next ``update_async``/``flush`` call site as
  :class:`AsyncWorkerError` (chained to the original). A failed handle is
  poisoned: later queued batches are discarded, never half-applied.
* **Buffer ownership under donation**: while a batch is in flight the
  worker owns the collection's state arrays — on donating backends the
  previous buffers are dead the moment the kernel is dispatched. All state
  access therefore funnels through the handle: blocking
  ``collection.update()`` calls enqueue-then-drain (FIFO order with queued
  async batches), ``forward`` and ``compute`` drain first, and the bytes
  pinned by queued batches + donated in-flight state are accounted by
  :meth:`AsyncUpdateHandle.in_flight_bytes` into
  ``MetricCollection.total_state_bytes`` and the telemetry footprint
  high-water mark.

Single-producer contract: ``update_async`` may be called from one thread at
a time (the serving loop). The worker is the only thread that mutates
metric state between drains.
"""
from __future__ import annotations

import contextlib
import queue
import threading
import time
import weakref
from typing import Any, Dict, Optional, Tuple

from metrics_tpu.observability.freshness import FreshnessStamp
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.observability.recorder import _nbytes
from metrics_tpu.utils.exceptions import MetricsUserError

#: queue sentinel: instructs the worker to exit (close())
_SHUTDOWN = object()

#: accepted backpressure policies for a full queue
POLICIES = ("block", "drop", "error")


class AsyncQueueFull(MetricsUserError):
    """Raised by ``update_async`` under the ``error`` backpressure policy
    when the bounded queue is full — the producer outran the device and
    asked to be told instead of blocked."""


class AsyncWorkerError(RuntimeError):
    """A batch failed inside the async worker.

    Raised at the next ``update_async``/``flush``/``compute`` call site,
    carrying :attr:`batch_index` (the 0-based accepted-batch index that
    failed) and chained to the original exception (``__cause__``). The
    handle is poisoned afterwards: queued batches are discarded and every
    later call re-raises, so a partially-applied epoch cannot silently
    masquerade as a complete one — ``reset()`` + a fresh
    ``compile_update_async()`` recovers.
    """

    def __init__(self, batch_index: int, original: BaseException) -> None:
        self.batch_index = batch_index
        self.original = original
        super().__init__(
            f"async metric update failed on batch {batch_index}: {original!r}"
            " (the handle is now poisoned; reset() and re-compile to recover)"
        )


def _wake_worker(q: "queue.Queue") -> None:
    """GC fallback (``weakref.finalize``) for a handle abandoned without
    ``close()``: wake the worker parked in ``q.get()`` so it notices the
    dead handle and exits instead of leaking as a daemon thread. Non-
    blocking on purpose — a full queue means the worker is active and will
    re-check its weakref at the next loop iteration anyway."""
    try:
        q.put_nowait(_SHUTDOWN)
    except queue.Full:
        pass


def _worker_main(handle_ref: "weakref.ref", q: "queue.Queue") -> None:
    """Queue drain loop, deliberately a module-level function: the thread
    must NOT hold a strong reference to the handle while parked in
    ``q.get()``, or an abandoned handle (and through it the collection,
    the fused compile cache, and every device state array) would be
    pinned by its own worker forever. The strong ref is taken per item
    and dropped before parking; ``_wake_worker`` (a ``weakref.finalize``)
    unblocks the park when the handle is collected."""
    while True:
        handle = handle_ref()
        if handle is None:
            return
        handle._yield_to_snapshot_waiters()
        del handle
        item = q.get()
        if item is _SHUTDOWN:
            return
        handle = handle_ref()
        if handle is None:
            return
        handle._drain_item(item)
        del handle


def _payload_nbytes(args: Tuple, kwargs: Dict[str, Any]) -> int:
    """Best-effort bytes held by a queued batch payload (array leaves only;
    static scalars/strings are free). Host-side attribute reads — never a
    device sync."""
    total = 0

    def walk(obj: Any) -> None:
        nonlocal total
        nb = _nbytes(obj)
        if nb:
            total += nb
        elif isinstance(obj, (list, tuple)):
            for o in obj:
                walk(o)
        elif isinstance(obj, dict):
            for o in obj.values():
                walk(o)

    walk(args)
    if kwargs:
        walk(kwargs)
    return total


class AsyncUpdateHandle:
    """Handle returned by :meth:`MetricCollection.compile_update_async`.

    ``update_async(batch)`` enqueues and returns immediately; a worker
    thread drains the bounded queue through the fused kernel. See the
    module docstring for the queue model, staleness contract, and
    ownership rules, and ``docs/async_updates.md`` for the user guide.
    """

    def __init__(
        self,
        collection: Any,
        fused: Any,
        queue_depth: int = 2,
        policy: str = "block",
        max_staleness: int = 0,
    ) -> None:
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
        if int(queue_depth) < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if int(max_staleness) < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        self._collection = collection
        self._fused = fused
        self.queue_depth = int(queue_depth)
        self.policy = policy
        self.max_staleness = int(max_staleness)

        self._queue: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._cond = threading.Condition()
        self._state_lock = threading.Lock()
        self._snapshot_waiters = 0  # computes waiting for the next lock window
        self._pending = 0  # accepted batches not yet applied (queued or in hand)
        self._in_flight_bytes = 0
        self._attempts = 0  # monotonic batch-index source; drops consume one
        self._enqueued = 0  # accepted batches ever
        self._applied = 0
        self._dropped = 0
        self._error: Optional[Tuple[int, BaseException]] = None
        # freshness bookkeeping (guarded by _cond): wall-clock accept time
        # per accepted-but-unapplied batch index, and the wall times of the
        # first/last batch actually applied — what freshness() composes into
        # a FreshnessStamp (min/max contributing event-time + in-flight age)
        self._pending_wall: Dict[int, float] = {}
        self._first_apply_wall: Optional[float] = None
        self._last_apply_wall: Optional[float] = None
        self._closed = False
        self._discard = False  # close(drain=False): worker drops queued items
        self._staleness_override: Optional[int] = None
        # the worker targets a module-level function holding only a weakref
        # to this handle: a handle abandoned without close() must not be
        # pinned forever by its own parked worker (see _worker_main);
        # _wake_worker is the GC fallback that unblocks the park
        self._thread = threading.Thread(
            target=_worker_main,
            args=(weakref.ref(self), self._queue),
            name="metrics-tpu-async-update",
            daemon=True,
        )
        self._thread.start()
        self._finalizer = weakref.finalize(self, _wake_worker, self._queue)

    # the worker thread and compiled executables cannot be copied:
    # MetricCollection.clone() drops the handle (same contract as
    # FusedUpdate) and the clone re-compiles on its own
    def __deepcopy__(self, memo: Dict) -> None:
        return None

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending(self) -> int:
        """Accepted batches not yet applied to the metric states."""
        with self._cond:
            return self._pending

    @property
    def dropped(self) -> int:
        """Batches discarded by the ``drop`` backpressure policy."""
        with self._cond:
            return self._dropped

    @property
    def enqueued(self) -> int:
        """Batches accepted into the queue over the handle's lifetime."""
        with self._cond:
            return self._enqueued

    @property
    def applied(self) -> int:
        """Batches successfully applied to the metric states."""
        with self._cond:
            return self._applied

    @property
    def state_lock(self) -> "threading.Lock":
        """Serializes a donating dispatch's buffers-dead-until-reinstalled
        window against state readers. A ``compute()`` under a positive
        staleness bound is allowed to see *stale* states — never deleted
        ones: on donating backends the old arrays are dead from the moment
        the kernel is enqueued until the new ones are installed. Readers
        should use :meth:`snapshot` rather than taking the lock raw: a bare
        acquire races the worker's immediate re-acquire (``threading.Lock``
        has no fairness), and losing that race every round degenerates a
        bounded-staleness read into a full drain."""
        return self._state_lock

    @contextlib.contextmanager
    def snapshot(self):
        """Priority window for state readers: registers as a waiter (the
        worker yields the lock between batches instead of re-acquiring in
        its tight loop), takes the state lock, and deregisters on exit.
        ``MetricCollection.compute()`` wraps its metric reads in this."""
        with self._cond:
            self._snapshot_waiters += 1
        try:
            with self._state_lock:
                yield
        finally:
            with self._cond:
                self._snapshot_waiters -= 1
                self._cond.notify_all()

    def freshness(self, now: Optional[float] = None) -> FreshnessStamp:
        """The pipeline's contribution to a read's
        :class:`~metrics_tpu.observability.freshness.FreshnessStamp`:
        wall clock of the first/last APPLIED batch (the ingest span of
        everything a snapshot can see) plus the age of the oldest batch
        accepted but not yet applied (``async_age_s`` — data a bounded-
        staleness read is allowed to be missing). Identity before any
        batch is accepted."""
        now = time.time() if now is None else now
        with self._cond:
            oldest = min(self._pending_wall.values()) if self._pending_wall else None
            first = self._first_apply_wall
            last = self._last_apply_wall
        return FreshnessStamp(
            min_event_t=first,
            max_event_t=last,
            async_age_s=max(0.0, now - oldest) if oldest is not None else 0.0,
        )

    @property
    def in_flight_bytes(self) -> int:
        """Bytes pinned by queued batch payloads plus (on donating backends)
        the state buffers owned by the batch currently being applied —
        exactly the memory ``state_footprint()`` used to undercount while a
        fused/async update was in flight."""
        with self._cond:
            return self._in_flight_bytes

    # ------------------------------------------------------------------
    # hot path
    # ------------------------------------------------------------------
    def _accept(self, name: str, args: Tuple, kwargs: Dict[str, Any]) -> Tuple:
        """Shared accept path: error/closed checks, then reserve the batch
        index and accounting slot. Returns the queue item."""
        self._raise_pending_error()
        if self._closed:
            raise MetricsUserError(
                f"{name}() on a closed AsyncUpdateHandle; call"
                " compile_update_async() again after reset()/close()"
            )
        nbytes = _payload_nbytes(args, kwargs)
        with self._cond:
            # the batch index comes from a monotonic attempt counter that a
            # rejected (dropped/errored) batch still consumes: an operator
            # correlating events must never see one index both dropped and
            # applied, so indexes are unique even though `enqueued` (the
            # ACCEPTED count) is rolled back on rejection
            idx = self._attempts
            self._attempts += 1
            self._enqueued += 1
            self._pending += 1
            self._in_flight_bytes += nbytes
            self._pending_wall[idx] = time.time()
        # the accept timestamp rides with the item: the worker reports the
        # enqueue->apply age at dequeue — the live staleness signal the
        # windowed telemetry layer (async_age_ms) alarms on
        return (idx, args, kwargs, nbytes, time.perf_counter())

    def _record_enqueue(self, idx: int) -> None:
        """Exactly one ``enqueue`` event per ACCEPTED batch (the
        observability guard pins this)."""
        if _TELEMETRY.enabled:
            with self._cond:
                depth = self._pending
                inflight = self._in_flight_bytes
            _TELEMETRY.record_async_event(
                "enqueue", batch_index=idx, queue_depth=depth, in_flight_bytes=inflight
            )

    def update_async(self, *args: Any, **kwargs: Any) -> bool:
        """Enqueue one batch and return immediately.

        Returns ``True`` when the batch was accepted, ``False`` when the
        ``drop`` policy discarded it. Re-raises a captured worker exception
        (:class:`AsyncWorkerError`) before touching the queue. Never
        performs a blocking device readback (TL-BLOCK-enforced).
        """
        item = self._accept("update_async", args, kwargs)
        idx, _, _, nbytes, _ = item
        # The enqueue event is recorded BEFORE queue.put so the worker's
        # matching dequeue event can never precede it in the stream. Under
        # the single-producer contract the ``full()`` precheck is stable:
        # only the worker mutates the queue concurrently, and it only
        # drains, so not-full cannot flip to full before our put.
        if self.policy != "block" and self._queue.full():
            with self._cond:
                self._enqueued -= 1
                self._pending -= 1
                self._in_flight_bytes -= nbytes
                self._pending_wall.pop(idx, None)
                if self.policy == "drop":
                    self._dropped += 1
                inflight = self._in_flight_bytes
            if self.policy == "error":
                raise AsyncQueueFull(
                    f"async update queue is full (depth {self.queue_depth});"
                    " the producer outran the device — flush(), raise"
                    " queue_depth, or use the 'block'/'drop' policy"
                )
            if _TELEMETRY.enabled:
                # counter-only: the enqueue-event-per-accepted-batch
                # guard stays exact
                _TELEMETRY.record_async_event(
                    "drop", batch_index=idx, in_flight_bytes=inflight
                )
            return False
        self._enqueue_lossless(item)
        return True

    def _enqueue_lossless(self, item: Tuple) -> None:
        """Wait for a queue slot (lossless), then record the enqueue event
        and put. The slot wait runs BEFORE the event (the event marks an
        ACCEPTED batch, and recording it first keeps dequeue-after-enqueue
        ordering in the stream) and carries a worker-liveness check: a dead
        worker (interpreter teardown is the realistic cause — in-loop
        failures poison the handle instead) would otherwise leave the
        producer parked in ``queue.put`` forever. The worker notifies
        ``_cond`` after each item it removes from the queue."""
        idx, _, _, nbytes, _ = item
        with self._cond:
            while self._queue.full():
                if not self._thread.is_alive():
                    self._enqueued -= 1
                    self._pending -= 1
                    self._in_flight_bytes -= nbytes
                    self._pending_wall.pop(idx, None)
                    raise MetricsUserError(
                        "async update worker thread is not running; the"
                        " queue cannot drain (was the interpreter shutting"
                        " down?)"
                    )
                self._cond.wait(timeout=0.1)
        self._record_enqueue(idx)
        # single-producer contract: after the not-full observation only the
        # worker mutates the queue, and it only drains — put cannot block
        self._queue.put(item)

    def update_blocking(self, *args: Any, **kwargs: Any) -> None:
        """Apply one batch synchronously, preserving FIFO order with any
        queued async batches: a forced (lossless) enqueue followed by a
        drain. This is what ``collection.update()`` routes through while
        the handle is open, so blocking and async ingest interleave without
        reordering or racing the worker's buffer ownership."""
        item = self._accept("update_blocking", args, kwargs)
        self._enqueue_lossless(item)  # policy-exempt
        # drain WITHOUT a flush event: per-batch blocking updates are not
        # epoch-boundary flushes, and counting them would make the flushes
        # counter track batch count under mixed ingest
        self._wait_drained()

    # ------------------------------------------------------------------
    # drain / snapshot
    # ------------------------------------------------------------------
    def flush(self, timeout: Optional[float] = None) -> int:
        """Block until every accepted batch has been applied (deterministic
        drain for epoch boundaries). Idempotent: a drained handle returns
        immediately. Returns the number of batches that were pending when
        the flush began; re-raises any worker exception — including one
        raised by a batch that was applied *during* this flush."""
        rec = _TELEMETRY if _TELEMETRY.enabled else None
        t0 = time.perf_counter() if rec is not None else 0.0
        waited = self._wait_drained(timeout)
        if rec is not None:
            rec.record_async_event(
                "flush",
                batches_drained=waited,
                dur_ms=round((time.perf_counter() - t0) * 1e3, 4),
                queue_depth=0,
                in_flight_bytes=self.in_flight_bytes,
            )
        return waited

    def _wait_drained(self, timeout: Optional[float] = None) -> int:
        """The drain wait shared by ``flush()`` (which additionally records
        the flush event) and ``update_blocking`` (which must not — a
        per-batch blocking update is not an epoch-boundary flush)."""
        self._raise_pending_error()
        with self._cond:
            waited = self._pending
            deadline = None if timeout is None else time.monotonic() + timeout
            while self._pending > 0 and self._error is None:
                if not self._thread.is_alive():
                    raise MetricsUserError(
                        "async update worker thread is not running; the handle"
                        " cannot drain (was the interpreter shutting down?)"
                    )
                remaining = 0.1 if deadline is None else min(0.1, deadline - time.monotonic())
                if remaining <= 0:
                    raise MetricsUserError(
                        f"flush() timed out with {self._pending} batches still pending"
                    )
                self._cond.wait(timeout=remaining)
        self._raise_pending_error()
        return waited

    def compute(self, max_staleness: Optional[int] = None) -> Dict[str, Any]:
        """Bounded-staleness snapshot compute: wait only until at most
        ``max_staleness`` accepted batches remain unapplied (the handle's
        default when ``None``; 0 = drain-then-compute), then run the
        collection's ordinary ``compute()``. No device barrier is forced —
        only the host-side drain the bound requires."""
        if max_staleness is not None and int(max_staleness) < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        if self._closed or getattr(self._collection, "_async", None) is not self:
            # the collection consults ITS current handle for the staleness
            # bound — an override set on a replaced/closed handle would be
            # silently ignored and return a snapshot staler than asked for
            raise MetricsUserError(
                "compute() on a closed or replaced AsyncUpdateHandle; use"
                " the collection's current handle (collection.async_update)"
            )
        self._staleness_override = None if max_staleness is None else int(max_staleness)
        try:
            return self._collection.compute()
        finally:
            self._staleness_override = None

    def _before_compute(self) -> None:
        """Collection-compute hook: enforce the staleness bound and record
        the snapshot's staleness gauge."""
        self._raise_pending_error()
        bound = (
            self.max_staleness
            if self._staleness_override is None
            else self._staleness_override
        )
        with self._cond:
            while self._pending > bound and self._error is None:
                if not self._thread.is_alive():
                    raise MetricsUserError(
                        "async update worker thread is not running; compute()"
                        " cannot reach its staleness bound"
                    )
                self._cond.wait(timeout=0.1)
            staleness = self._pending
        self._raise_pending_error()
        if _TELEMETRY.enabled:
            _TELEMETRY.record_async_event("snapshot", staleness_steps=staleness)

    def close(self, drain: bool = True) -> None:
        """Stop the worker and release the handle. ``drain=True`` (default)
        applies every queued batch first; ``drain=False`` discards queued
        batches (reset/add_metrics invalidation — the states are about to
        be wiped or restructured anyway). Idempotent; never raises on a
        poisoned handle (the error already surfaced, or will at the owner's
        next call). Joins the worker thread, so ``threading.active_count()``
        is restored."""
        if self._closed:
            return
        self._closed = True
        with self._cond:
            waited = self._pending
        if not drain:
            # flag FIRST: the worker checks it per item, so a batch the
            # worker wins from the queue while we drain below is discarded
            # there rather than applied — the documented contract is that
            # QUEUED batches never land (the one already mid-dispatch is in
            # flight, not queued, and completes either way)
            self._discard = True
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is _SHUTDOWN:
                    continue
                with self._cond:
                    self._pending -= 1
                    self._in_flight_bytes -= item[3]
                    self._pending_wall.pop(item[0], None)
                    self._cond.notify_all()
        # liveness-guarded: with drain=True the queue may still be full and
        # the sentinel put waits for the worker's FIFO drain to open a slot
        # — but a DEAD worker (interpreter teardown) never will, and an
        # atexit/finally close() must not park here forever
        while True:
            try:
                self._queue.put(_SHUTDOWN, timeout=0.1)
                break
            except queue.Full:
                if not self._thread.is_alive():
                    break
        self._thread.join(timeout=60.0)
        self._finalizer.detach()  # worker is gone; no GC wake-up needed
        # only a DRAINING close is a flush; close(drain=False) discards its
        # queued batches, and counting it would let an operator read
        # "flushes" as deterministic drains that never happened
        if drain and _TELEMETRY.enabled:
            _TELEMETRY.record_async_event(
                "flush", batches_drained=waited, queue_depth=0,
                in_flight_bytes=0, closed=True,
            )

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------
    def _raise_pending_error(self) -> None:
        with self._cond:
            err = self._error
        if err is not None:
            idx, original = err
            raise AsyncWorkerError(idx, original) from original

    def _yield_to_snapshot_waiters(self) -> None:
        """Yield the lock window to any waiting compute() BEFORE pulling
        the next batch: the bare lock has no fairness, and the drain loop
        re-acquires so quickly that a reader could starve until the queue
        ran dry — a full drain in all but name."""
        with self._cond:
            while self._snapshot_waiters and self._error is None:
                self._cond.wait(timeout=0.1)

    def _drain_item(self, item: Tuple) -> None:
        """Apply one dequeued batch. Owns the collection's state arrays
        between dequeue and install; must stay readback-free (TL-BLOCK) —
        the fused dispatch it calls returns as soon as XLA has enqueued the
        kernel. EVERYTHING fallible runs inside the error capture: a raise
        anywhere (donation accounting, dispatch, telemetry) must poison the
        handle and release waiters, never kill the worker with ``_pending``
        stuck — block-policy producers and ``flush()`` wait on it."""
        idx, args, kwargs, nbytes, t_accept = item
        # the queue slot freed at q.get(): wake a block-policy producer
        # parked in _enqueue_lossless NOW, not at the post-dispatch
        # bookkeeping notify — overlapping the next batch's ingest with
        # this batch's dispatch is the pipeline's entire point
        with self._cond:
            self._cond.notify_all()
        rec = None
        t0 = 0.0
        donated = 0
        err: Optional[BaseException] = None
        # a poisoned handle discards instead of half-applying; so does
        # close(drain=False), whichever thread wins the queue race
        poisoned = self._error is not None or self._discard
        if not poisoned:
            try:
                rec = _TELEMETRY if _TELEMETRY.enabled else None
                t0 = time.perf_counter() if rec is not None else 0.0
                if self._fused.donating:
                    # the dispatched kernel owns (donates) the current state
                    # buffers until the new ones are installed below; count
                    # them as in flight so footprint accounting sees them
                    donated = self._fused.donated_state_bytes()
                    with self._cond:
                        self._in_flight_bytes += donated
                # exclusive vs compute(): a bounded-staleness snapshot
                # must never traverse the donation window's dead arrays
                with self._state_lock:
                    self._fused.dispatch(args, kwargs)
            except BaseException as e:  # noqa: BLE001 — re-raised at the call site
                err = e
        with self._cond:
            self._pending -= 1
            self._in_flight_bytes -= nbytes + donated
            t_wall = self._pending_wall.pop(idx, None)
            if err is not None and self._error is None:
                self._error = (idx, err)
            if err is None and not poisoned:
                self._applied += 1
                if t_wall is not None:
                    if self._first_apply_wall is None:
                        self._first_apply_wall = t_wall
                    self._last_apply_wall = t_wall
            depth = self._pending
            inflight = self._in_flight_bytes
            self._cond.notify_all()
        if rec is not None and err is None and not poisoned:
            try:
                # no staleness_steps here: that gauge tracks COMPUTE-SNAPSHOT
                # staleness (the "snapshot" event in _before_compute feeds
                # it); stamping queue depth into it would report every
                # drained compute as queue_depth-stale
                rec.record_async_event(
                    "dequeue",
                    batch_index=idx,
                    queue_depth=depth,
                    in_flight_bytes=inflight,
                    dur_ms=round((time.perf_counter() - t0) * 1e3, 4),
                    # enqueue->apply age: how long this batch sat accepted-
                    # but-unapplied — the wall-clock staleness signal behind
                    # the windowed async_age_ms series
                    age_ms=round((time.perf_counter() - t_accept) * 1e3, 4),
                )
            except BaseException as e:  # noqa: BLE001 — surfaced, not fatal
                with self._cond:
                    if self._error is None:
                        self._error = (idx, e)
                    self._cond.notify_all()
