"""ROUGEScore parity vs the rouge-score package (the reference's own oracle,
/root/reference/tests/text/test_rouge.py:28-77)."""
from functools import partial

import numpy as np
import pytest

rouge_scorer_mod = pytest.importorskip("rouge_score.rouge_scorer")
rouge_scoring_mod = pytest.importorskip("rouge_score.scoring")

from metrics_tpu.functional.text.rouge import _regex_sent_tokenize, rouge_score
from metrics_tpu.text.rouge import ROUGEScore
from tests.text.helpers import TextTester
from tests.text.inputs import _inputs_multiple_references

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL")


def _rouge_score_oracle(preds, targets, use_stemmer, rouge_level, metric, accumulate):
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(targets, str):
        targets = [[targets]]

    scorer = rouge_scorer_mod.RougeScorer(list(ROUGE_KEYS), use_stemmer=use_stemmer)
    aggregator = rouge_scoring_mod.BootstrapAggregator()
    for pred_raw, target_raw in zip(preds, targets):
        list_results = [scorer.score(tgt, pred_raw) for tgt in target_raw]
        if accumulate == "best":
            key_curr = list(list_results[0].keys())[0]
            all_fmeasure = [v[key_curr].fmeasure for v in list_results]
            aggregator.add_scores(list_results[int(np.argmax(all_fmeasure))])
        else:  # avg
            aggregator_avg = rouge_scoring_mod.BootstrapAggregator()
            for score in list_results:
                aggregator_avg.add_scores(score)
            aggregator.add_scores({k: s.mid for k, s in aggregator_avg.aggregate().items()})
    return getattr(aggregator.aggregate()[rouge_level].mid, metric)


@pytest.mark.parametrize(
    ["rouge_metric_key", "use_stemmer"],
    [
        ("rouge1_precision", True),
        ("rouge1_recall", True),
        ("rouge1_fmeasure", False),
        ("rouge2_precision", False),
        ("rouge2_recall", True),
        ("rouge2_fmeasure", True),
        ("rougeL_precision", False),
        ("rougeL_recall", False),
        ("rougeL_fmeasure", True),
    ],
)
@pytest.mark.parametrize("accumulate", ["avg", "best"])
class TestROUGEScore(TextTester):
    def test_rouge_score_class(self, rouge_metric_key, use_stemmer, accumulate):
        rouge_level, metric = rouge_metric_key.split("_")
        self.run_class_metric_test(
            preds=_inputs_multiple_references.preds,
            targets=_inputs_multiple_references.targets,
            metric_class=ROUGEScore,
            sk_metric=partial(
                _rouge_score_oracle,
                use_stemmer=use_stemmer,
                rouge_level=rouge_level,
                metric=metric,
                accumulate=accumulate,
            ),
            metric_args={"use_stemmer": use_stemmer, "accumulate": accumulate, "rouge_keys": ROUGE_KEYS},
            key=rouge_metric_key,
        )

    def test_rouge_score_functional(self, rouge_metric_key, use_stemmer, accumulate):
        rouge_level, metric = rouge_metric_key.split("_")
        preds = [p for batch in _inputs_multiple_references.preds for p in batch]
        targets = [t for batch in _inputs_multiple_references.targets for t in batch]
        result = rouge_score(
            preds, targets, accumulate=accumulate, use_stemmer=use_stemmer, rouge_keys=ROUGE_KEYS
        )[rouge_metric_key]
        oracle = _rouge_score_oracle(
            preds, targets, use_stemmer=use_stemmer, rouge_level=rouge_level, metric=metric, accumulate=accumulate
        )
        np.testing.assert_allclose(np.asarray(result), oracle, atol=1e-4, rtol=1e-5)


def test_rouge_lsum_offline():
    """rougeLsum must work without network/punkt: the offline regex splitter
    stands in for nltk sent_tokenize (pins the no-network behavior flagged
    in round 2 — default keys must not throw in an air-gapped environment)."""
    preds = "The cat sat on the mat. It was a sunny day."
    target = "A cat was sitting on the mat. The day was sunny."
    result = ROUGEScore(rouge_keys=("rougeLsum",))(preds, target)
    assert 0.0 <= float(result["rougeLsum_fmeasure"]) <= 1.0


def test_regex_sent_tokenize():
    assert _regex_sent_tokenize("One. Two! Three? Four") == ["One.", "Two!", "Three?", "Four"]


def test_rouge_unknown_key_raises():
    with pytest.raises(ValueError, match="unknown rouge key"):
        ROUGEScore(rouge_keys=("rougeX",))
    with pytest.raises(ValueError, match="unknown accumulate"):
        ROUGEScore(accumulate="median")
