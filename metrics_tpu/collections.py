"""MetricCollection — chain metrics with one call pattern, with automatic
compute-group state dedup.

Behavior parity with /root/reference/torchmetrics/collections.py:28-371:
list/dict/args construction, per-metric kwarg filtering, prefix/postfix,
clone, and **compute groups** (collections.py:144-227): every metric starts
as its own group; after the first real update, groups whose member states
are identical are merged (pairwise deep comparison), and later updates touch
only group leaders — the documented 2-3x cost reduction. Group discovery
pre-filters on static state *definitions* (names, shapes, reducers) before
the value comparison, so no array data is fetched for obviously-different
metrics.
"""
import time
from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.observability.freshness import FreshnessStamp, merge_stamps
from metrics_tpu.observability.recorder import _DEFAULT_RECORDER as _TELEMETRY
from metrics_tpu.observability.trace import span as _span
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.utils.prints import rank_zero_warn


def _flatten_dict(x: Dict) -> Dict:
    """Flatten dict-valued results (e.g. ClasswiseWrapper) into the parent."""
    new_dict = {}
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                new_dict[k] = v
        else:
            new_dict[key] = value
    return new_dict


class MetricCollection:
    """Chain metrics that have the same call pattern into one object.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, Precision, Recall
        >>> target = jnp.array([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.array([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([Accuracy(),
        ...                             Precision(num_classes=3, average='macro'),
        ...                             Recall(num_classes=3, average='macro')])
        >>> {k: float(v) for k, v in metrics(preds, target).items()}
        {'Accuracy': 0.125, 'Precision': 0.06666667014360428, 'Recall': 0.111111119389534}
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        self._metrics: "OrderedDict[str, Metric]" = OrderedDict()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups: Dict[int, List[str]] = {}
        self._groups_checked: bool = False
        self._fused = None  # FusedUpdate handle once compile_update() is called
        self._async = None  # AsyncUpdateHandle once compile_update_async() is called
        self._bulk_insert = False  # add_metrics defers the membership handler
        # wall clock of the first/last batch ingested through THIS object
        # (telemetry-enabled updates only) — covers the fused path, whose
        # member metrics never see their own update() stamps
        self._ingest_first_t: Optional[float] = None
        self._ingest_last_t: Optional[float] = None

        self.add_metrics(metrics, *additional_metrics)

    # ------------------------------------------------------------------
    # dict-like access
    # ------------------------------------------------------------------
    def __getitem__(self, key: str) -> Metric:
        return self._metrics[key]

    def __setitem__(self, key: str, value: Metric) -> None:
        self._metrics[key] = value
        # a dict-style insert is a membership change exactly like
        # add_metrics (which routes here and runs the shared handler once,
        # after its whole batch of inserts — per-item group reseeds would
        # spuriously fail explicit compute_groups-list validation against
        # a partially-built membership)
        if not self._bulk_insert:
            self._on_membership_change()

    def _on_membership_change(self) -> None:
        """Everything a membership change must refresh: compiled fused and
        async handles are stale (the worker would keep writing through the
        old member set in the background), and the compute groups must be
        reseeded — a merge over the pre-insert ``_groups`` would silently
        exclude the new member from every future update."""
        self._groups_checked = False
        self._invalidate_compiled()
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {}

    def _invalidate_compiled(self) -> None:
        """Drop any compiled fused update and close an open async handle
        (discarding queued batches — their fused set no longer matches the
        membership); a fresh ``compile_update[_async]()`` is required to
        resume."""
        self._fused = None
        if self._async is not None:
            self._async.close(drain=False)
            self._async = None

    def __contains__(self, key: str) -> bool:
        return key in self._metrics

    def __len__(self) -> int:
        return len(self._metrics)

    def __iter__(self) -> Iterable[str]:
        return iter(self._metrics)

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return self._metrics.keys()
        return self._to_renamed_ordered_dict().keys()

    def items(self, keep_base: bool = False) -> Iterable[Tuple[str, Metric]]:
        if keep_base:
            return self._metrics.items()
        return self._to_renamed_ordered_dict().items()

    def values(self) -> Iterable[Metric]:
        return self._metrics.values()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call forward for each metric; kwargs are filtered per metric."""
        if not _TELEMETRY.enabled:
            return self._forward_impl(*args, **kwargs)
        with _span("MetricCollection.forward", n_metrics=len(self._metrics)):
            return self._forward_impl(*args, **kwargs)

    def _forward_impl(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        # forward's double-update cycle reads AND restores every state; it
        # must not race the async worker's buffer ownership
        self._drain_async()
        res = {k: m(*args, **m._filter_kwargs(**kwargs)) for k, m in self.items(keep_base=True)}
        res = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Call update for each metric (only group leaders once groups are known)."""
        if not _TELEMETRY.enabled:
            self._update_impl(*args, **kwargs)
            return
        now = time.time()
        if self._ingest_first_t is None:
            self._ingest_first_t = now
        self._ingest_last_t = now
        # the collection span parents every member metric's own span, so the
        # per-metric rows nest instead of reading as unrelated siblings
        with _span("MetricCollection.update", n_metrics=len(self._metrics)):
            self._update_impl(*args, **kwargs)

    def _update_impl(self, *args: Any, **kwargs: Any) -> None:
        if self._async is not None and not self._async.closed:
            # blocking updates interleave with queued async batches in FIFO
            # order (enqueue-then-drain), so the two ingest styles compose
            # without racing the worker's donated-buffer ownership
            self._async.update_blocking(*args, **kwargs)
            return
        if self._fused is not None:
            self._fused(*args, **kwargs)
            return
        if self._groups_checked:
            for cg in self._groups.values():
                m0 = self._metrics[cg[0]]
                if _TELEMETRY.enabled and len(cg) > 1:
                    # compute-group attribution: the leader's single update
                    # event carries the member names it serves, so shared
                    # updates are counted once and attributed, not per-member
                    with _TELEMETRY.group_attribution(cg):
                        m0.update(*args, **m0._filter_kwargs(**kwargs))
                else:
                    m0.update(*args, **m0._filter_kwargs(**kwargs))
        else:
            for m in self._metrics.values():
                m.update(*args, **m._filter_kwargs(**kwargs))
            if self._enable_compute_groups:
                self._merge_compute_groups()
                self._groups_checked = True

    def _merge_compute_groups(self) -> None:
        """Pairwise-merge groups whose member states are identical.

        Parity with reference collections.py:159-192.
        """
        n_groups = len(self._groups)
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 == cg_idx2:
                        continue
                    metric1 = self._metrics[cg_members1[0]]
                    metric2 = self._metrics[cg_members2[0]]
                    if self._equal_metric_states(metric1, metric2):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                if len(self._groups) != n_groups:
                    break
            if len(self._groups) == n_groups:
                break
            n_groups = len(self._groups)

        self._groups = {idx: values for idx, values in enumerate(deepcopy(self._groups).values())}

    @staticmethod
    def _equal_update_attrs(metric1: Metric, metric2: Metric) -> bool:
        """True if every public attribute the two metrics share compares equal.

        Hyperparameters (threshold, top_k, num_classes, ...) live as public
        instance attributes; if any common one differs, the metrics' update
        paths may diverge on later batches, so they must not share a group
        even when their states coincide on the first one.
        """
        # sliced metrics keep their real update config on the wrapped
        # TEMPLATE (an underscored attribute the public-attr walk below
        # skips): two SlicedMetrics over same-shape states but differently
        # configured inner metrics (e.g. thresholds) must not share a group
        t1 = getattr(metric1, "_template", None)
        t2 = getattr(metric2, "_template", None)
        if (t1 is None) != (t2 is None):
            return False
        if isinstance(t1, Metric) and isinstance(t2, Metric):
            if type(t1) is not type(t2) or not MetricCollection._equal_update_attrs(t1, t2):
                return False
        skip = set(metric1._defaults) | set(metric2._defaults)
        attrs1 = {k: v for k, v in vars(metric1).items() if not k.startswith("_") and k not in skip}
        attrs2 = {k: v for k, v in vars(metric2).items() if not k.startswith("_") and k not in skip}
        for key in attrs1.keys() & attrs2.keys():
            v1, v2 = attrs1[key], attrs2[key]
            if v1 is v2:  # shared objects (callables, extractors, arrays) compare equal
                continue
            try:
                if isinstance(v1, np.ndarray) or isinstance(v2, np.ndarray):
                    if not (isinstance(v1, np.ndarray) and isinstance(v2, np.ndarray) and np.array_equal(v1, v2)):
                        return False
                elif isinstance(v1, jnp.ndarray) or isinstance(v2, jnp.ndarray):
                    if (
                        not isinstance(v1, jnp.ndarray)
                        or not isinstance(v2, jnp.ndarray)
                        or v1.shape != v2.shape
                        or not bool(jnp.all(v1 == v2))
                    ):
                        return False
                elif v1 != v2:
                    return False
            except Exception:  # incomparable values: refuse to merge
                return False
        return True

    @staticmethod
    def _equal_metric_states(metric1: Metric, metric2: Metric) -> bool:
        """True if the two metrics' states are identical.

        Static pre-filter on definitions (names, reducers, default shapes)
        avoids fetching array values for obviously-different metrics; the
        value comparison then proves the update paths agree (parity with
        reference collections.py:194-213).

        Unlike the reference heuristic (which merges two metrics whose states
        coincide on the FIRST batch even when their update-time
        hyperparameters differ, e.g. thresholds), shared public attributes
        are also compared — metrics differing in any common hyperparameter
        never share a group. Pass explicit ``compute_groups=[[...]]`` to
        override.
        """
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        if not MetricCollection._equal_update_attrs(metric1, metric2):
            return False
        # wrapper metrics hold their real state in child metrics; two wrappers
        # with (possibly empty) matching registries are NOT state-equal
        if metric1._children or metric2._children or not metric1._defaults:
            return False
        for key in metric1._defaults:
            d1, d2 = metric1._defaults[key], metric2._defaults[key]
            if type(d1) is not type(d2):
                return False
            if metric1._reductions[key] is not metric2._reductions[key]:
                return False
            if isinstance(d1, jnp.ndarray) and (d1.shape != d2.shape or d1.dtype != d2.dtype):
                return False

        for key in metric1._defaults:
            state1 = getattr(metric1, key)
            state2 = getattr(metric2, key)
            if type(state1) is not type(state2):
                return False
            if isinstance(state1, (int, float)):
                # host-resident counters (the eager `_n_updates` fast path)
                if state1 != state2:
                    return False
            elif isinstance(state1, jnp.ndarray):
                if state1.shape != state2.shape or not bool(jnp.allclose(state1, state2)):
                    return False
            elif isinstance(state1, list):
                if len(state1) != len(state2):
                    return False
                if not all(
                    s1.shape == s2.shape and bool(jnp.allclose(s1, s2)) for s1, s2 in zip(state1, state2)
                ):
                    return False
        return True

    def compute(self) -> Dict[str, Any]:
        """Compute each metric; group members borrow the leader's state."""
        if not _TELEMETRY.enabled:
            return self._compute_impl()
        with _span("MetricCollection.compute", n_metrics=len(self._metrics)):
            return self._compute_impl()

    def _compute_impl(self) -> Dict[str, Any]:
        handle = self._async if self._async is not None and not self._async.closed else None
        if handle is not None:
            # bounded-staleness snapshot: wait only until at most
            # max_staleness accepted batches remain unapplied (0 = full
            # drain); no device barrier is forced
            handle._before_compute()
            applied_mark = handle.applied
            try:
                # a positive staleness bound lets the worker keep applying
                # while we compute, but on donating backends a dispatch's
                # buffers-dead-until-reinstalled window must stay exclusive:
                # the snapshot may be *stale*, never deleted
                with handle.snapshot():
                    return self._compute_metrics()
            finally:
                if handle.applied != applied_mark:
                    # batches landed WHILE computing: each install cleared
                    # `_computed`, but a compute finishing afterwards writes
                    # its (now stale) value back into the cache — and with
                    # no later update to clear it, the next compute() would
                    # serve the stale snapshot as the drained answer
                    for m in self._metrics.values():
                        m._computed = None
        return self._compute_metrics()

    def freshness(self, now: Optional[float] = None) -> FreshnessStamp:
        """The collection's :class:`~metrics_tpu.observability.freshness.
        FreshnessStamp`: the merge (min/max monoid fold) of the collection-
        level ingest span, every member metric's own stamp, and — when an
        async handle is open — the pipeline's applied-span + in-flight-age
        stamp. This is THE read-side staleness answer serving loops should
        use instead of hand-rolled `pending`-count math."""
        stamps: List[FreshnessStamp] = [
            FreshnessStamp(
                min_event_t=self._ingest_first_t, max_event_t=self._ingest_last_t
            )
        ]
        stamps.extend(m.freshness_stamp(now) for m in self._metrics.values())
        if self._async is not None and not self._async.closed:
            stamps.append(self._async.freshness(now))
        return merge_stamps(stamps)

    def _compute_metrics(self) -> Dict[str, Any]:
        if self._enable_compute_groups and self._groups_checked:
            for cg in self._groups.values():
                m0 = self._metrics[cg[0]]
                for i in range(1, len(cg)):
                    mi = self._metrics[cg[i]]
                    for state in m0._defaults:
                        object.__setattr__(mi, state, getattr(m0, state))
                    mi._update_called = m0._update_called
                    # epoch-aware borrow: installing the leader's states is
                    # an out-of-band write ONLY when the leader actually
                    # advanced since the last borrow — a repeat compute on
                    # an unchanged group re-installs identical arrays, and
                    # wiping the member's cache there would force a cold
                    # fold per member per read forever
                    src_epoch = (cg[0], m0._write_epoch)
                    if getattr(mi, "_borrowed_epoch", None) != src_epoch:
                        mi._mark_state_written()
                        mi._borrowed_epoch = src_epoch
        res = {k: m.compute() for k, m in self.items(keep_base=True)}
        res = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def compile_update(self, buckets=None, donate=None, use_manifest=None):
        """Compile the whole collection's update into ONE jitted XLA dispatch.

        Returns a :class:`metrics_tpu.core.fused.FusedUpdate` handle and
        routes subsequent :meth:`update` calls through it: every fusible
        member metric's pure ``update_state`` transform (one per compute
        group, not per metric) runs inside a single jitted
        ``(states, batch) -> states`` function with donated state buffers,
        including the per-metric mean-merge counter bump. Metrics flagged
        ``__jit_unsafe__``, wrapper metrics, and list-state metrics fall
        back to the eager per-metric path transparently in the same call.

        ``buckets`` — optional ascending batch-size buckets for pad-and-mask
        shape bucketing: shape-varying batches pad up to the nearest bucket
        and reuse its one compilation instead of recompiling per shape.
        ``donate`` — override the backend-derived buffer-donation default
        (donation is honored on TPU/GPU; donated state arrays must not be
        aliased by callers). See docs/fused_updates.md.

        ``use_manifest`` — consult the committed tracelint fusibility
        manifest (``scripts/fusibility_manifest.json``) to skip the
        ``eval_shape`` probe for statically-proven-fusible members (default
        on; ``METRICS_TPU_NO_MANIFEST=1`` disables globally, and
        ``METRICS_TPU_VERIFY_MANIFEST=1`` cross-checks verdicts against the
        probe). See docs/static_analysis.md for the verdict lattice.

        ``forward`` keeps the eager double-update semantics; ``clone()``
        drops the handle (compiled executables are not copyable) and the
        clone re-compiles on first use.
        """
        from metrics_tpu.core.fused import FusedUpdate

        # idempotent warm reuse: reset() keeps the handle, so an epoch
        # loop's reset(); compile_update[_async]() must not discard a warm
        # compile cache and pay a fresh XLA build (membership changes go
        # through add_metrics()/clone(), which drop the handle)
        if self._fused is not None and self._fused.config_matches(
            buckets=buckets, donate=donate, use_manifest=use_manifest
        ):
            return self._fused
        if self._async is not None and not self._async.closed:
            # a config-changing rebuild under a live worker would install a
            # second fused handle the async path never routes to — and
            # dispatching it directly would race the worker's donation
            # window on the same state arrays
            raise MetricsUserError(
                "compile_update() with a different config while an async"
                " handle is open; close() the handle (or reset(), or call"
                " compile_update_async() with the new config) first"
            )
        self._fused = FusedUpdate(self, buckets=buckets, donate=donate, use_manifest=use_manifest)
        return self._fused

    @property
    def fused_update(self):
        """The active :class:`FusedUpdate` handle, or ``None`` (eager)."""
        return self._fused

    def compile_update_async(
        self,
        buckets=None,
        donate=None,
        use_manifest=None,
        *,
        queue_depth: int = 2,
        policy: str = "block",
        max_staleness: int = 0,
    ):
        """Compile the fused update AND layer the async ingest pipeline on
        top: returns a :class:`metrics_tpu.core.pipeline.AsyncUpdateHandle`
        whose ``update_async(batch)`` enqueues into a bounded
        double-buffered queue (depth ``queue_depth``, default 2) and
        returns immediately; a worker thread drains the queue through the
        fused single-dispatch kernel, so host ingest overlaps device
        compute and the serving loop never stalls on metrics accounting.

        ``buckets``/``donate``/``use_manifest`` are forwarded to
        :meth:`compile_update`. ``policy`` picks the backpressure behavior
        when the queue is full (``"block"`` waits, ``"drop"`` discards and
        counts, ``"error"`` raises ``AsyncQueueFull``); ``max_staleness``
        is the default ``compute()`` staleness bound in accepted-but-
        unapplied batches (0 = drain-then-compute).

        While the handle is open, blocking ``update()`` calls route through
        it (enqueue-then-drain, FIFO with queued async batches), ``compute``
        honors the staleness bound, and ``forward`` drains first.
        ``reset()``/``add_metrics()`` close and invalidate the handle (as
        they invalidate ``compile_update``'s); ``clone()`` drops it (worker
        threads are not copyable). See docs/async_updates.md.
        """
        from metrics_tpu.core.pipeline import AsyncUpdateHandle

        if self._async is not None:
            # a poisoned handle must surface its captured AsyncWorkerError
            # here, not vanish: close() never raises by contract, so
            # re-compiling over a failed handle would silently discard the
            # error AND the queued batches the failure stranded (reset() is
            # the documented way to discard and recover)
            self._async._raise_pending_error()
            self._async.close(drain=True)
        fused = self.compile_update(buckets=buckets, donate=donate, use_manifest=use_manifest)
        self._async = AsyncUpdateHandle(
            self,
            fused,
            queue_depth=queue_depth,
            policy=policy,
            max_staleness=max_staleness,
        )
        return self._async

    @property
    def async_update(self):
        """The active :class:`AsyncUpdateHandle`, or ``None``."""
        return self._async

    def update_async(self, *args: Any, **kwargs: Any) -> bool:
        """Enqueue one batch into the async pipeline and return immediately
        (see :meth:`compile_update_async`); ``True`` if accepted, ``False``
        if the ``drop`` backpressure policy discarded it."""
        if self._async is None or self._async.closed:
            raise MetricsUserError(
                "update_async() requires an open async handle; call"
                " compile_update_async() first"
            )
        return self._async.update_async(*args, **kwargs)

    def state_reductions(self) -> Dict[str, Dict[str, Any]]:
        """Per-metric reducer specs (name -> ``Metric.state_reductions()``)
        — the shape :func:`metrics_tpu.parallel.distributed.sync_pytree_in_mesh`
        takes for a one-collective-round sync of the whole collection."""
        return {name: m.state_reductions() for name, m in self._metrics.items()}

    def reset(self) -> None:
        """Reset all metrics; discovered compute groups are kept (parity with
        reference collections.py — discovery cost is amortized across epochs).

        An open async handle is closed (queued batches DISCARDED — the
        states are being wiped anyway) and invalidated, so a worker cannot
        apply a stale batch on top of freshly-reset states; call
        :meth:`compile_update_async` again to resume async ingest."""
        if self._async is not None:
            self._async.close(drain=False)
            self._async = None
        self._ingest_first_t = None
        self._ingest_last_t = None
        for m in self._metrics.values():
            m.reset()

    def _drain_async(self) -> None:
        """State-access guard: drain the open async handle before reading,
        copying, or replacing metric state. Without it a checkpoint or
        clone races the worker — on donating backends the dispatch window's
        dead arrays raise 'Array has been deleted', and on any backend the
        copied/serialized state silently misses the queued batches (or,
        for load_state_dict, stale queued batches land on top of the
        freshly loaded state). Re-raises a captured worker error, like
        every other drain. Uses the event-free drain: the flushes counter
        tracks explicit flush() calls and draining closes, not internal
        guards (forward() routes through here per batch)."""
        if self._async is not None and not self._async.closed:
            self._async._wait_drained()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        self._drain_async()
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self._metrics.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        self._drain_async()
        destination: Dict[str, Any] = {}
        for name, m in self._metrics.items():
            m.state_dict(destination, prefix=f"{name}.")
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        # drain applies already-accepted batches to the OLD state before it
        # is replaced — same ordering a blocking loop would have produced
        self._drain_async()
        for name, m in self._metrics.items():
            m.load_state_dict(state_dict, prefix=f"{name}.")

    def state_footprint(self) -> Dict[str, Dict[str, int]]:
        """Per-metric state footprints (name -> ``Metric.state_footprint()``).

        NOTE: metrics sharing a compute group report the same logical state;
        :meth:`total_state_bytes` is the deduplicated total.
        """
        return {name: m.state_footprint() for name, m in self._metrics.items()}

    def total_state_bytes(self) -> int:
        """Total UNIQUE state bytes: once compute groups are discovered, only
        each group's leader contributes (members borrow the leader's arrays
        at compute time, so counting them would double-book the memory).

        While an async handle is open, the bytes pinned by queued batch
        payloads and by donated state buffers still owned by an in-flight
        fused dispatch are counted too (``AsyncUpdateHandle.in_flight_bytes``)
        — without them the footprint silently undercounts exactly when
        memory pressure peaks (the same bytes feed the telemetry footprint
        high-water mark via the ``async_in_flight`` label)."""
        if self._enable_compute_groups and self._groups_checked:
            names = [cg[0] for cg in self._groups.values()]
        else:
            names = list(self._metrics)
        total = sum(self._metrics[name].total_state_bytes() for name in names)
        if self._async is not None and not self._async.closed:
            total += self._async.in_flight_bytes
        return total

    def to_device(self, device: Any) -> "MetricCollection":
        # replaces every state array: must not race the worker's donation
        # window, and queued batches must land on the pre-move state
        self._drain_async()
        for m in self._metrics.values():
            m.to_device(device)
        return self

    def set_dtype(self, dst_type: Any) -> "MetricCollection":
        self._drain_async()  # same state-replacement guard as to_device
        for m in self._metrics.values():
            m.set_dtype(dst_type)
        return self

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence) and not isinstance(metrics, str):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        # defer the shared membership handler to one run after the whole
        # batch of inserts: an explicit compute_groups list validates its
        # names against the membership, which is incomplete mid-loop
        self._bulk_insert = True
        try:
            if isinstance(metrics, dict):
                for name in sorted(metrics.keys()):
                    metric = metrics[name]
                    if not isinstance(metric, Metric):
                        raise ValueError(f"Value {metric} belonging to key {name} is not an instance of `Metric`")
                    self[name] = metric
            elif isinstance(metrics, Sequence):
                for metric in metrics:
                    if not isinstance(metric, Metric):
                        raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `Metric`")
                    name = metric.__class__.__name__
                    if name in self:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self[name] = metric
            else:
                raise ValueError("Unknown input to MetricCollection.")
        finally:
            self._bulk_insert = False

        self._on_membership_change()

    def _init_compute_groups(self) -> None:
        if isinstance(self._enable_compute_groups, list):
            self._groups = {i: k for i, k in enumerate(self._enable_compute_groups)}
            for v in self._groups.values():
                for metric in v:
                    if metric not in self:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the"
                            f" collection. Please make sure that {self._enable_compute_groups} matches"
                            f" {list(self.keys(keep_base=True))}"
                        )
            self._groups_checked = True
        else:
            self._groups = {i: [str(k)] for i, k in enumerate(self._metrics.keys())}

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        return self._groups

    # ------------------------------------------------------------------
    # naming
    # ------------------------------------------------------------------
    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def _to_renamed_ordered_dict(self) -> "OrderedDict[str, Metric]":
        od: "OrderedDict[str, Metric]" = OrderedDict()
        for k, v in self._metrics.items():
            od[self._set_name(k)] = v
        return od

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for name, m in self._metrics.items():
            repr_str += f"\n  {name}: {repr(m)}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)" if len(self._metrics) else repr_str + ")"
