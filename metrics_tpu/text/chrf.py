"""Modular CHRFScore.

Behavior parity with /root/reference/torchmetrics/text/chrf.py:46-208 (which
registers one scalar state per n-gram order so corpus statistics sum across
ranks; here the per-order scalars are kept in the same layout).
"""
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.chrf import (
    _chrf_score_compute,
    _chrf_score_update,
    _validate_chrf_args,
    _zero_totals,
)

Array = jax.Array

_TOTAL_NAMES = ("pred_char", "pred_word", "tgt_char", "tgt_word", "match_char", "match_word")


class CHRFScore(Metric):
    """Corpus chrF/chrF++ with per-order accumulator states.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> metric = CHRFScore()
        >>> float(metric(preds, target))  # doctest: +ELLIPSIS
        0.8640...
    """

    is_differentiable = False
    higher_is_better = True
    __jit_unsafe__ = True  # update consumes Python strings

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_chrf_args(n_char_order, n_word_order, beta)
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)

        # one scalar state per (accumulator, n-gram order): sums across ranks
        for name, orders in zip(_TOTAL_NAMES, _zero_totals(n_char_order, n_word_order)):
            for n in orders:
                self.add_state(f"total_{name}_{n}grams", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", [], dist_reduce_fx="cat")

    def _totals(self):
        # one stacked device->host readback for all ~16 per-order scalars (a
        # per-scalar float() would cost a blocking roundtrip each on
        # tunneled/remote accelerators)
        import numpy as np

        layout = list(zip(_TOTAL_NAMES, _zero_totals(self.n_char_order, self.n_word_order)))
        stacked = np.asarray(
            jnp.stack(
                [jnp.asarray(getattr(self, f"total_{name}_{n}grams"), jnp.float32) for name, orders in layout for n in orders]
            )
        )
        out = []
        i = 0
        for name, orders in layout:
            out.append({n: float(stacked[i + j]) for j, n in enumerate(orders)})
            i += len(orders)
        return tuple(out)

    def _store_totals(self, totals) -> None:
        for name, orders in zip(_TOTAL_NAMES, totals):
            for n, value in orders.items():
                setattr(self, f"total_{name}_{n}grams", jnp.asarray(value, jnp.float32))

    def _update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        totals, sentence_scores = _chrf_score_update(
            preds,
            target,
            self._totals(),
            self.n_char_order,
            self.n_word_order,
            self.n_order,
            self.beta,
            self.lowercase,
            self.whitespace,
        )
        self._store_totals(totals)
        if self.return_sentence_level_score:
            self.sentence_chrf_score.extend(jnp.asarray(s, jnp.float32)[None] for s in sentence_scores)

    def _compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _chrf_score_compute(self._totals(), self.n_order, self.beta)
        if self.return_sentence_level_score:
            return score, jnp.concatenate(self.sentence_chrf_score)
        return score
