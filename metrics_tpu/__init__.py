"""metrics_tpu — a TPU-native (JAX/XLA) machine-learning metrics framework.

Capability parity target: TorchMetrics v0.8.0dev (/root/reference). Exports
grow as domains land; see SURVEY.md §2.8 for the full target inventory.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.core.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    ROC,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.aggregation import (  # noqa: E402
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)
from metrics_tpu.image import (  # noqa: E402
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.regression import (  # noqa: E402
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)

__all__ = [
    "AUC",
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "BootStrapper",
    "CalibrationError",
    "CatMetric",
    "ClasswiseWrapper",
    "CohenKappa",
    "ConfusionMatrix",
    "CompositionalMetric",
    "CosineSimilarity",
    "ExplainedVariance",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KLDivergence",
    "MatthewsCorrCoef",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "MaxMetric",
    "MeanMetric",
    "Metric",
    "MetricCollection",
    "MetricTracker",
    "MinMaxMetric",
    "MinMetric",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "MultioutputWrapper",
    "PeakSignalNoiseRatio",
    "SumMetric",
    "PearsonCorrCoef",
    "Precision",
    "PrecisionRecallCurve",
    "R2Score",
    "ROC",
    "Recall",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "SpearmanCorrCoef",
    "StructuralSimilarityIndexMeasure",
    "UniversalImageQualityIndex",
    "Specificity",
    "StatScores",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
]
