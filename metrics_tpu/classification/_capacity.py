"""Fixed-capacity exact-mode support for the curve metric classes.

TPU-native extension (no reference analog): passing ``capacity=N`` to
AUROC / AveragePrecision / PrecisionRecallCurve / ROC switches the unbounded
cat-list states to a static ``[N]`` buffer triple (preds, target, valid) so
the ENTIRE metric — update, compute, sync — is jit-traceable and mesh-
syncable (SURVEY §7 design-3; kernels in
functional/classification/exact_curve.py). The case must be declared
statically (the shape/dtype case deduction of the unbounded path is host
logic): binary is the default (1-D scores, 0/1 integer targets);
``num_classes >= 2`` switches to ``[capacity, C]`` score rows with integer
labels (multiclass one-vs-rest) or, with ``multilabel=True``, ``[capacity,
C]`` indicator targets.
"""
from typing import Optional

import jax.numpy as jnp

from metrics_tpu.utils.exceptions import MetricsUserError

try:  # jax.core.is_concrete moved across versions; checks has the shim
    from metrics_tpu.utils.checks import _is_concrete
except ImportError:  # pragma: no cover
    def _is_concrete(*arrays):
        return True


class CapacityCurveMixin:
    """Adds ``capacity`` handling. Call ``_init_capacity`` in ``__init__``
    INSTEAD of registering the list states when capacity is not None; guard
    ``_update``/``_compute`` with ``self._capacity is not None``."""

    _capacity: Optional[int] = None

    def _init_capacity(
        self, capacity: int, num_cols: Optional[int] = None, multilabel: bool = False
    ) -> None:
        """Register the fixed-capacity buffer triple. ``num_cols`` switches the
        score buffer from ``[capacity]`` (binary) to ``[capacity, num_cols]``
        (per-class score rows, the multiclass exact mode); ``multilabel``
        additionally widens the target buffer to ``[capacity, num_cols]``
        per-class indicators."""
        if not (isinstance(capacity, int) and capacity > 0):
            raise ValueError(f"Argument `capacity` must be a positive int, got {capacity}")
        if multilabel and num_cols is None:
            raise ValueError("`multilabel` capacity mode requires `num_cols`")
        self._capacity = capacity
        self._capacity_cols = num_cols
        self._capacity_multilabel = multilabel
        # defaults spelled as the zeros arrays curve_buffer_init produces so
        # the abstract interpreter reads container/shape/dtype statically
        preds_default = (
            jnp.zeros((capacity,), dtype=jnp.float32)
            if num_cols is None
            else jnp.zeros((capacity, num_cols), dtype=jnp.float32)
        )
        target_default = (
            jnp.zeros((capacity, num_cols), dtype=jnp.int32)
            if multilabel
            else jnp.zeros((capacity,), dtype=jnp.int32)
        )
        self.add_state("preds", default=preds_default, dist_reduce_fx="cat")
        self.add_state("target", default=target_default, dist_reduce_fx="cat")
        self.add_state("valid", default=jnp.zeros((capacity,), dtype=bool), dist_reduce_fx="cat")
        # overflow tally: counts samples dropped by the `mode='drop'` scatter
        # when the fill count is traced (inside jit the eager raise below
        # cannot fire); compute NaN-poisons / raises when it is non-zero so a
        # too-small capacity can never yield a silently wrong exact value
        self.add_state("overflow", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        # fixed-shape states + pure array ops: the whole metric traces under jit
        self.__dict__["__jit_unsafe__"] = False

    _capacity_cols: Optional[int] = None
    _capacity_multilabel: bool = False

    def _init_capacity_case(
        self, capacity: Optional[int], num_classes: Optional[int], multilabel: bool
    ) -> None:
        """Shared constructor dispatch for the curve classes: binary buffers
        by default, ``[capacity, C]`` rows when ``num_classes >= 2``; validates
        the ``multilabel``/``capacity`` combinations. No-op states are NOT
        registered here when ``capacity`` is None — the caller keeps its
        unbounded cat-state path."""
        if capacity is None:
            if multilabel:
                raise ValueError("`multilabel` is a capacity-mode argument; pass `capacity` as well")
            return
        if num_classes is not None and num_classes >= 2:
            self._init_capacity(capacity, num_cols=num_classes, multilabel=multilabel)
        elif multilabel:
            raise ValueError("`multilabel` capacity mode requires `num_classes >= 2`")
        else:
            self._init_capacity(capacity)

    def _capacity_update(self, preds, target, pos_label=None) -> None:
        num_cols = self._capacity_cols
        multilabel = self._capacity_multilabel
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if not multilabel:
            target = target.reshape(-1)
        if num_cols is None:
            preds = preds.reshape(-1)
            if preds.shape != target.shape:
                raise ValueError("preds and target must have the same shape in capacity mode")
        else:
            if preds.ndim != 2 or preds.shape[1] != num_cols:
                raise ValueError(
                    f"Expected `preds` of shape [N, {num_cols}] in multiclass capacity mode,"
                    f" got {preds.shape}"
                )
            if multilabel and preds.shape != target.shape:
                raise ValueError(
                    f"Expected `target` of shape [N, {num_cols}] in multilabel capacity mode,"
                    f" got {target.shape}"
                )
            if preds.shape[0] != target.shape[0]:
                raise ValueError("preds and target must agree on the batch dimension")
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError("preds must be float scores/probabilities in capacity mode")
        if jnp.issubdtype(target.dtype, jnp.floating):
            raise ValueError("target must be integer labels in capacity mode")
        if pos_label is not None and num_cols is None:
            # same binarization the unbounded path applies (target == pos_label)
            target = (target == pos_label).astype(jnp.int32)
        elif _is_concrete(target) and target.size:
            upper = 1 if (num_cols is None or multilabel) else num_cols - 1
            if int(jnp.min(target)) < 0 or int(jnp.max(target)) > upper:
                hint = (
                    "target must be binary (0/1); pass `pos_label` to select the positive class"
                    if num_cols is None
                    else ("multilabel indicators must be 0/1" if multilabel else f"labels must be in [0, {upper}]")
                )
                raise ValueError(f"target out of range in capacity mode; {hint}")
        count = jnp.sum(self.valid).astype(jnp.int32)
        if _is_concrete(count) and int(count) + preds.shape[0] > self._capacity:
            raise MetricsUserError(
                f"Exact-curve capacity overflow: buffer holds {int(count)} of"
                f" {self._capacity} samples and the batch adds {preds.shape[0]}."
                " Construct the metric with a larger `capacity`."
            )
        # write into the first free slots rather than at offset `count`: a
        # state restored from a merged/gathered buffer may be non-contiguous,
        # and an offset write would clobber later valid entries
        idx = jnp.nonzero(~self.valid, size=preds.shape[0], fill_value=self._capacity)[0].astype(jnp.int32)
        self.preds = self.preds.at[idx].set(preds.astype(jnp.float32), mode="drop")
        self.target = self.target.at[idx].set(target.astype(jnp.int32), mode="drop")
        self.valid = self.valid.at[idx].set(True, mode="drop")
        self.overflow = self.overflow + jnp.maximum(
            count + preds.shape[0] - self._capacity, 0
        ).astype(jnp.int32)

    def _capacity_guard(self):
        """Overflow-checked flat valid mask.

        Outside jit a non-zero overflow tally raises; under tracing the mask
        is blanked instead, which routes every downstream kernel into its
        degenerate branch (NaN scalars / empty curve points) — a truncated
        buffer can never produce a plausible-but-wrong exact value.
        """
        overflow = jnp.sum(self.overflow).astype(jnp.int32)
        if _is_concrete(overflow) and int(overflow) > 0:
            raise MetricsUserError(
                f"Exact-curve capacity overflow: {int(overflow)} sample(s) were dropped by"
                f" jitted updates beyond the declared capacity ({self._capacity})."
                " Construct the metric with a larger `capacity`."
            )
        return jnp.asarray(self.valid).reshape(-1) & (overflow == 0)

    def _capacity_buffers(self):
        """Flattened (preds, target, valid): after a distributed sync the
        stacked ``(num_process, capacity)`` state (reference tensor-state sync
        convention) flattens to the cross-rank union; locally it's a no-op."""
        valid = self._capacity_guard()
        return self.preds.reshape(-1), self.target.reshape(-1), valid

    def _capacity_buffers_2d(self):
        """Row-flattened (preds [N, C], target, valid) for the multiclass /
        multilabel kernels; stacked post-sync states flatten along rows."""
        num_cols = self._capacity_cols
        valid = self._capacity_guard()
        target = (
            self.target.reshape(-1, num_cols)
            if self._capacity_multilabel
            else self.target.reshape(-1)
        )
        return self.preds.reshape(-1, num_cols), target, valid
