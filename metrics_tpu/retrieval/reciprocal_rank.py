"""RetrievalMRR.

Behavior parity with /root/reference/torchmetrics/retrieval/reciprocal_rank.py:20-96.
"""
import jax

from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank
from metrics_tpu.functional.retrieval.padded import reciprocal_rank_row
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank over queries.

    Default state is the fixed-capacity per-query table (fusible /
    async / mesh-synced; ``max_queries`` / ``max_docs`` size it);
    ``exact=True`` restores the unbounded cat-state reference path.
    """

    _padded_metric = staticmethod(reciprocal_rank_row)

    def _metric(self, preds: Array, target: Array) -> Array:
        return retrieval_reciprocal_rank(preds, target)
