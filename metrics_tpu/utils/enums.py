"""String-valued enums for metric configuration.

Behavior parity with /root/reference/torchmetrics/utilities/enums.py:15-83
(the case-deduction ``DataType`` and averaging enums), re-expressed for the
TPU-native framework. All enums compare case-insensitively against strings.
"""
from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """String enum with a tolerant ``from_str`` constructor."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: Union[str, Enum, None]) -> bool:  # type: ignore[override]
        other = other.value if isinstance(other, Enum) else str(other)
        return self.value.lower() == other.lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Classification input "case" deduced from shapes/dtypes.

    Reference: /root/reference/torchmetrics/utilities/enums.py:35-45.
    """

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Reduction over classes. Reference: utilities/enums.py:48-66."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multidim-multiclass extra-dim handling. Reference: utilities/enums.py:69-76."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
